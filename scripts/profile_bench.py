"""Per-phase timing breakdown of the fused graph on the bench workload.

Times each stage (extraction, chaos, correlation, pattern match) as its own
jitted function with block_until_ready, on the same synthetic dataset and
batch shapes bench.py uses.  Run on the real chip to attribute cost before
optimizing (VERDICT round-1 item 2).
"""

from __future__ import annotations

import time
from functools import partial
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np

from sm_distributed_tpu.io.dataset import SpectralDataset
from sm_distributed_tpu.io.fixtures import FIXTURE_FORMULAS, generate_synthetic_dataset
from sm_distributed_tpu.models.msm_jax import JaxBackend
from sm_distributed_tpu.models.msm_basic import _slice_table
from sm_distributed_tpu.ops.fdr import FDR
from sm_distributed_tpu.ops.imager_jax import extract_images, window_rank_grid
from sm_distributed_tpu.ops.isocalc import IsocalcWrapper
from sm_distributed_tpu.ops.metrics_jax import (
    isotope_image_correlation_batch,
    isotope_pattern_match_batch,
    measure_of_chaos_batch,
)
from sm_distributed_tpu.ops.quantize import quantize_window
from sm_distributed_tpu.utils.config import DSConfig, SMConfig
from sm_distributed_tpu.utils.logger import init_logger, logger


def timeit(name, fn, *args, reps=5):
    out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn(*args)
    jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / reps
    logger.info("%-28s %8.2f ms", name, dt * 1e3)
    return out, dt


def main():
    init_logger()
    cache_dir = Path(__file__).parent.parent / ".cache"
    path, truth = generate_synthetic_dataset(
        cache_dir / "bench_ds", nrows=64, ncols=64,
        formulas=FIXTURE_FORMULAS, present_fraction=0.6, noise_peaks=200, seed=7,
    )
    ds = SpectralDataset.from_imzml(path)
    ds_config = DSConfig.from_dict(
        {"isotope_generation": {"adducts": ["+H"]}, "image_generation": {"ppm": 3.0}}
    )
    sm_config = SMConfig.from_dict(
        {"backend": "jax_tpu", "fdr": {"decoy_sample_size": 20},
         "parallel": {"formula_batch": 512}}
    )

    fdr = FDR(decoy_sample_size=20, target_adducts=("+H",), seed=42)
    assignment = fdr.decoy_adduct_selection(truth.formulas)
    pairs, flags = assignment.all_ion_tuples(truth.formulas, ("+H",))
    calc = IsocalcWrapper(ds_config.isotope_generation, cache_dir=str(cache_dir / "isocalc"))
    table = calc.pattern_table(pairs, flags)

    backend = JaxBackend(ds, ds_config, sm_config)
    b = sm_config.parallel.formula_batch
    sub = _slice_table(table, 0, min(b, table.n_ions))
    n, k = sub.n_ions, sub.max_peaks

    lo_q, hi_q = quantize_window(sub.mzs, ds_config.image_generation.ppm)
    lo_p = np.zeros((b, k), np.int32); hi_p = np.zeros((b, k), np.int32)
    ints_p = np.zeros((b, k), np.float32); nv_p = np.zeros(b, np.int32)
    lo_p[:n], hi_p[:n] = lo_q, hi_q
    ints_p[:n] = sub.ints; nv_p[:n] = sub.n_valid
    grid, r_lo, r_hi = window_rank_grid(lo_p, hi_p)
    logger.info("batch=%d ions, k=%d, grid=%d bins, cube=%s",
                b, k, grid.shape[0], backend._mz_q.shape)

    grid_d = jax.device_put(grid)
    r_lo_d = jax.device_put(r_lo); r_hi_d = jax.device_put(r_hi)
    ints_d = jax.device_put(ints_p); nv_d = jax.device_put(nv_p)

    # full fused graph
    _, t_full = timeit("fused full", backend._fn, backend._mz_q, backend._ints,
                       grid_d, r_lo_d.reshape(b, k), r_hi_d.reshape(b, k),
                       ints_d, nv_d)

    # extraction only
    ext = jax.jit(extract_images)
    imgs_flat, t_ext = timeit("extract_images", ext, backend._mz_q, backend._ints,
                              grid_d, r_lo_d, r_hi_d)
    imgs = imgs_flat.reshape(b, k, -1)[:, :, : ds.nrows * ds.ncols]
    imgs = jax.device_put(np.asarray(imgs))
    valid = np.arange(k)[None, :] < nv_p[:, None]
    valid_d = jax.device_put(valid)

    chaos_fn = jax.jit(partial(measure_of_chaos_batch, nrows=ds.nrows, ncols=ds.ncols))
    _, t_chaos = timeit("chaos (30 levels)", chaos_fn, imgs[:, 0, :])

    corr_fn = jax.jit(isotope_image_correlation_batch)
    _, t_corr = timeit("correlation", corr_fn, imgs, ints_d, valid_d)

    pat_fn = jax.jit(lambda im, th, v: isotope_pattern_match_batch(im.sum(-1), th, v))
    _, t_pat = timeit("pattern match", pat_fn, imgs, ints_d, valid_d)

    logger.info("sum of parts: %.2f ms (full %.2f ms)",
                (t_ext + t_chaos + t_corr + t_pat) * 1e3, t_full * 1e3)


if __name__ == "__main__":
    main()
