"""Per-phase timing breakdown of the fused graph on the bench workload.

Times each stage (extraction, chaos, correlation, pattern match) as its own
jitted function with block_until_ready, on the same synthetic dataset and
batch shapes bench.py uses.  Run on the real chip to attribute cost before
optimizing (VERDICT round-1 item 2).

Uses the production flat-banded path via the backend's own batch plan
(``JaxBackend._flat_plan``), so the profiled signature can never drift from
what ``score_batch`` actually runs (ADVICE r2: the previous version kept a
private copy of the removed cube signature and crashed).
"""

from __future__ import annotations

import time
from functools import partial
from pathlib import Path

import jax
import numpy as np

from sm_distributed_tpu.io.dataset import SpectralDataset
from sm_distributed_tpu.io.fixtures import FIXTURE_FORMULAS, generate_synthetic_dataset
from sm_distributed_tpu.models.msm_basic import _slice_table
from sm_distributed_tpu.models.msm_jax import JaxBackend
from sm_distributed_tpu.ops.fdr import FDR
from sm_distributed_tpu.ops.imager_jax import extract_images_flat_banded
from sm_distributed_tpu.ops.isocalc import IsocalcWrapper
from sm_distributed_tpu.ops.metrics_jax import (
    isotope_image_correlation_batch,
    isotope_pattern_match_batch,
    measure_of_chaos_batch,
)
from sm_distributed_tpu.utils.config import DSConfig, SMConfig
from sm_distributed_tpu.utils.logger import init_logger, logger


def _force(out):
    """Force a host readback: block_until_ready through the tunneled TPU can
    report fake-fast completions; an actual value fetch cannot.  Fetch ONE
    element (a dependent tiny dispatch), not the whole array — a multi-GB
    image block takes tens of seconds through the ~130 MB/s tunnel."""
    for x in jax.tree.leaves(out):
        np.asarray(x[(0,) * getattr(x, "ndim", 0)])


def timeit(name, fn, *args, reps=5, **kwargs):
    out = fn(*args, **kwargs)
    _force(out)
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn(*args, **kwargs)
    _force(out)
    dt = (time.perf_counter() - t0) / reps
    logger.info("%-28s %8.2f ms", name, dt * 1e3)
    return out, dt


def profile(nrows=64, ncols=64, formula_batch=512, noise_peaks=200, reps=5,
            cache_dir=None, n_formulas=None, batch_index=0):
    """Run the phase breakdown; returns {phase: seconds} for assertions.

    ``n_formulas``: expand the formula list like bench.py does (None = the
    50 curated fixture formulas).  ``batch_index`` picks which formula batch
    to profile — batch 0 holds every target window (all the signal), later
    batches are decoy-dominated, so their cost profiles differ."""
    from sm_distributed_tpu.io.fixtures import expand_formula_list

    init_logger()
    cache_dir = Path(cache_dir or Path(__file__).parent.parent / ".cache")
    formulas = (expand_formula_list(n_formulas) if n_formulas
                else FIXTURE_FORMULAS)
    # n_formulas mode mirrors bench.py's exact fixture params, so reuse its
    # cached dataset (a 256x256 generation costs ~4 min)
    name = "bench_ds" if n_formulas else f"profile_ds_{nrows}x{ncols}"
    path, truth = generate_synthetic_dataset(
        cache_dir / name, nrows=nrows, ncols=ncols,
        formulas=formulas, present_fraction=0.6,
        noise_peaks=noise_peaks, seed=7, reuse=True,
    )
    ds = SpectralDataset.from_imzml(path)
    ds_config = DSConfig.from_dict(
        {"isotope_generation": {"adducts": ["+H"]}, "image_generation": {"ppm": 3.0}}
    )
    sm_config = SMConfig.from_dict(
        {"backend": "jax_tpu", "fdr": {"decoy_sample_size": 20},
         "parallel": {"formula_batch": formula_batch}}
    )

    fdr = FDR(decoy_sample_size=20, target_adducts=("+H",), seed=42)
    assignment = fdr.decoy_adduct_selection(truth.formulas)
    pairs, flags = assignment.all_ion_tuples(truth.formulas, ("+H",))
    calc = IsocalcWrapper(ds_config.isotope_generation,
                          cache_dir=str(cache_dir / "isocalc"))
    table = calc.pattern_table(pairs, flags)

    backend = JaxBackend(ds, ds_config, sm_config, restrict_table=table)
    b = backend.batch
    s0 = min(batch_index * b, max(table.n_ions - b, 0))
    sub = _slice_table(table, s0, min(s0 + b, table.n_ions))
    k = sub.max_peaks

    # the backend's own batch plan — identical host prep to score_batch
    plan = backend._flat_plan(sub)
    grid, _r_lo, _r_hi, ints_p, nv_p, chunks, pos, runs, b_eff = plan
    starts, r_lo_loc, r_hi_loc, inv, gc_width = chunks
    logger.info("batch=%d ions, k=%d, grid=%d bins, %d peaks resident, "
                "gc_width=%d, compact=%s (keep %s)",
                b, k, grid.shape[0], backend._mz_host.size, gc_width,
                backend._use_compaction(runs), runs[2] if runs else None)

    timings = {}

    # full fused graph, exactly as score_batch dispatches it
    def fused():
        out, _n = backend._dispatch(sub, plan)
        return out

    _, timings["fused_full"] = timeit("fused full", fused, reps=reps)

    # extraction only (flat-banded, the production kernel)
    ext = jax.jit(partial(extract_images_flat_banded,
                          gc_width=backend._gc_width or gc_width,
                          n_pixels=ds.n_pixels))
    args = [jax.device_put(a) for a in (pos, starts, r_lo_loc, r_hi_loc, inv)]
    imgs_flat, timings["extract"] = timeit(
        "extract (flat-banded)", ext, backend._px_s, backend._in_s, *args,
        reps=reps)
    # keep the (W, P) image block ON DEVICE — a host round-trip of this
    # multi-GB array takes minutes through the tunnel
    imgs = imgs_flat.reshape(b_eff, k, -1)
    valid_d = jax.device_put(np.arange(k)[None, :] < nv_p[:, None])
    ints_d = jax.device_put(ints_p)

    chaos_fn = jax.jit(partial(measure_of_chaos_batch, nrows=ds.nrows,
                               ncols=ds.ncols))
    _, timings["chaos"] = timeit("chaos (30 levels)", chaos_fn, imgs[:, 0, :],
                                 reps=reps)

    corr_fn = jax.jit(isotope_image_correlation_batch)
    _, timings["correlation"] = timeit("correlation", corr_fn, imgs, ints_d,
                                       valid_d, reps=reps)

    pat_fn = jax.jit(lambda im, th, v: isotope_pattern_match_batch(
        im.sum(-1), th, v))
    _, timings["pattern"] = timeit("pattern match", pat_fn, imgs, ints_d,
                                   valid_d, reps=reps)

    parts = timings["extract"] + timings["chaos"] + timings["correlation"] \
        + timings["pattern"]
    logger.info("sum of parts: %.2f ms (full %.2f ms)",
                parts * 1e3, timings["fused_full"] * 1e3)
    return timings


def main():
    profile()


if __name__ == "__main__":
    main()
