"""Per-phase timing breakdown of the fused graph on the bench workload.

Times each stage (extraction, chaos, correlation, pattern match) via the
backend's OWN probe hooks (``JaxBackend.probe_phases`` — VERDICT r3 item 5:
the previous versions re-implemented backend internals from private plan
tuples and broke whenever the plan shape changed).  Each probed phase runs
the exact arrays, static shapes, and plain/compaction variant that
``score_batch`` dispatches.  Run on the real chip to attribute cost before
optimizing.
"""

from __future__ import annotations

import time
from pathlib import Path

import jax
import numpy as np

from sm_distributed_tpu.io.dataset import SpectralDataset
from sm_distributed_tpu.io.fixtures import FIXTURE_FORMULAS, generate_synthetic_dataset
from sm_distributed_tpu.models.msm_basic import _slice_table
from sm_distributed_tpu.models.msm_jax import JaxBackend
from sm_distributed_tpu.ops.fdr import FDR
from sm_distributed_tpu.ops.isocalc import IsocalcWrapper
from sm_distributed_tpu.utils.config import DSConfig, SMConfig
from sm_distributed_tpu.utils.logger import init_logger, logger


def _force(out):
    """Force a host readback: block_until_ready through the tunneled TPU can
    report fake-fast completions; an actual value fetch cannot.  Fetch ONE
    element (a dependent tiny dispatch), not the whole array — a multi-GB
    image block takes tens of seconds through the ~130 MB/s tunnel."""
    for x in jax.tree.leaves(out):
        np.asarray(x[(0,) * getattr(x, "ndim", 0)])


def timeit(name, fn, reps=5):
    _force(fn())                          # compile + warm
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn()
    _force(out)
    dt = (time.perf_counter() - t0) / reps
    logger.info("%-28s %8.2f ms", name, dt * 1e3)
    return dt


def profile(nrows=64, ncols=64, formula_batch=512, noise_peaks=200, reps=5,
            cache_dir=None, n_formulas=None, batch_index=0):
    """Run the phase breakdown; returns {phase: seconds} for assertions.

    ``n_formulas``: expand the formula list like bench.py does (None = the
    50 curated fixture formulas).  ``batch_index`` picks which formula batch
    to profile — batch 0 holds every target window (all the signal), later
    batches are decoy-dominated, so their cost profiles differ."""
    from sm_distributed_tpu.io.fixtures import expand_formula_list

    init_logger()
    cache_dir = Path(cache_dir or Path(__file__).parent.parent / ".cache")
    formulas = (expand_formula_list(n_formulas) if n_formulas
                else FIXTURE_FORMULAS)
    # n_formulas mode mirrors bench.py's exact fixture params AND its cache
    # naming, so the profiler reuses bench datasets (a 512x512 generation
    # costs ~11 min on this host)
    name = (f"bench_ds_{nrows}x{ncols}_f{n_formulas}" if n_formulas
            else f"profile_ds_{nrows}x{ncols}")
    path, truth = generate_synthetic_dataset(
        cache_dir / name, nrows=nrows, ncols=ncols,
        formulas=formulas, present_fraction=0.6,
        noise_peaks=noise_peaks, seed=7, reuse=True,
    )
    ds = SpectralDataset.from_imzml(path)
    ds_config = DSConfig.from_dict(
        {"isotope_generation": {"adducts": ["+H"]}, "image_generation": {"ppm": 3.0}}
    )
    sm_config = SMConfig.from_dict(
        {"backend": "jax_tpu", "fdr": {"decoy_sample_size": 20},
         "parallel": {"formula_batch": formula_batch}}
    )

    fdr = FDR(decoy_sample_size=20, target_adducts=("+H",), seed=42)
    assignment = fdr.decoy_adduct_selection(truth.formulas)
    pairs, flags = assignment.all_ion_tuples(truth.formulas, ("+H",))
    calc = IsocalcWrapper(ds_config.isotope_generation,
                          cache_dir=str(cache_dir / "isocalc"))
    table = calc.pattern_table(pairs, flags)

    backend = JaxBackend(ds, ds_config, sm_config, restrict_table=table)
    b = backend.batch
    s0 = min(batch_index * b, max(table.n_ions - b, 0))
    sub = _slice_table(table, s0, min(s0 + b, table.n_ions))

    phases, info = backend.probe_phases(sub)
    logger.info("probe info: %s", info)
    timings = {name: timeit(name, fn, reps=reps)
               for name, fn in phases.items()}
    parts = sum(t for name, t in timings.items() if name != "fused_full")
    logger.info("sum of parts: %.2f ms (full %.2f ms)",
                parts * 1e3, timings["fused_full"] * 1e3)
    return timings


def main():
    profile()


if __name__ == "__main__":
    main()
