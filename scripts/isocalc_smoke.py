"""Isocalc parallel smoke gate (ISSUE 3 satellite, run by check_tier1.sh).

Generates the spheroid-fixture ion set twice — serially and through a
2-worker spawn pool with a small chunk size — and asserts the tentpole's
core guarantee mechanically: identical table values AND byte-identical
incremental cache shards (same filenames, same bytes).  Also proves a
third, cache-warm run loads the shards instead of recomputing.

Exit 0 = gate passes; 1 = any mismatch.  Runtime: a few seconds (spawn
startup dominates).
"""

from __future__ import annotations

import sys
import tempfile
from pathlib import Path

sys.path.insert(0, str(Path(__file__).parent.parent))


def main() -> int:
    import numpy as np

    import sm_distributed_tpu.ops.isocalc as iso_mod
    from sm_distributed_tpu.io.fixtures import FIXTURE_FORMULAS
    from sm_distributed_tpu.ops.isocalc import IsocalcWrapper
    from sm_distributed_tpu.utils.config import IsotopeGenerationConfig

    cfg = IsotopeGenerationConfig(adducts=("+H",))
    pairs = [(sf, a) for sf in FIXTURE_FORMULAS for a in ("+H", "+Na", "+K")]
    iso_mod._PARALLEL_THRESHOLD = 8      # force the pool on this small set

    with tempfile.TemporaryDirectory() as d_ser, \
            tempfile.TemporaryDirectory() as d_par:
        ser = IsocalcWrapper(cfg, cache_dir=d_ser, n_procs=1, chunk_size=16)
        t_ser = ser.pattern_table(pairs)
        par = IsocalcWrapper(cfg, cache_dir=d_par, n_procs=2, chunk_size=16)
        t_par = par.pattern_table(pairs)

        if par.last_stats.get("workers") != 2:
            print(f"isocalc_smoke: FAIL — pool did not engage "
                  f"({par.last_stats})", file=sys.stderr)
            return 1
        if t_ser.sfs != t_par.sfs or not (
                np.array_equal(t_ser.mzs, t_par.mzs)
                and np.array_equal(t_ser.ints, t_par.ints)
                and np.array_equal(t_ser.n_valid, t_par.n_valid)):
            print("isocalc_smoke: FAIL — parallel table != serial table",
                  file=sys.stderr)
            return 1

        s_shards = sorted(p.name for p in Path(d_ser).glob("theor_peaks_*"))
        p_shards = sorted(p.name for p in Path(d_par).glob("theor_peaks_*"))
        if not s_shards or s_shards != p_shards:
            print(f"isocalc_smoke: FAIL — shard sets differ: "
                  f"{s_shards} vs {p_shards}", file=sys.stderr)
            return 1
        for name in s_shards:
            if (Path(d_ser) / name).read_bytes() != (
                    Path(d_par) / name).read_bytes():
                print(f"isocalc_smoke: FAIL — shard {name} bytes differ",
                      file=sys.stderr)
                return 1

        # warm reload: a third wrapper must serve every ion from the shards
        warm = IsocalcWrapper(cfg, cache_dir=d_par)
        if len(warm._cache) != t_ser.n_ions:
            print(f"isocalc_smoke: FAIL — warm reload found "
                  f"{len(warm._cache)}/{t_ser.n_ions} ions", file=sys.stderr)
            return 1
        t_warm = warm.pattern_table(pairs)
        if warm.last_stats.get("cold_patterns", -1) != 0 or not (
                np.array_equal(t_warm.mzs, t_ser.mzs)):
            print("isocalc_smoke: FAIL — warm run recomputed or diverged",
                  file=sys.stderr)
            return 1

    print(f"isocalc_smoke: OK — {t_ser.n_ions} ions, {len(s_shards)} shards "
          f"byte-identical across serial/2-worker runs, warm reload clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
