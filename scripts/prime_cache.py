#!/usr/bin/env python
"""Offline AOT cache priming (ISSUE 13, docs/PERF.md "Cold start").

Reads the shape-bucket lattice manifest (``bucket_manifest.json`` next to
the persistent XLA cache — written by the jax backends as traffic records
``BucketSpec``s, see ``ops/buckets.py``) and AOT-compiles every flat-path
spec into the persistent compilation cache, so a freshly deployed replica
serves its first submit from primed executables instead of paying the
cold XLA compile.  The in-service equivalent is the scheduler-idle
``CachePrimer`` thread (``service.prime`` config); this CLI exists for
deploy pipelines and for re-priming after a jax/backend upgrade (primed
entries are environment-keyed).

Usage::

    python scripts/prime_cache.py --sm-config conf/config.json
    python scripts/prime_cache.py --work-dir /srv/sm --force
    python scripts/prime_cache.py --spec '{"kind":"flat", ...}'  # ad hoc

Prints ONE JSON summary line on stdout ({known, compiled, skipped,
errors, cache_dir}); logging goes to stderr.  Exit 0 unless a compile
errored (exit 1) or nothing was known to prime (exit 2 — run traffic or
pass --spec first).
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO_ROOT))


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(prog="prime_cache")
    ap.add_argument("--sm-config", default=None,
                    help="SMConfig json (default: env/default resolution)")
    ap.add_argument("--work-dir", default=None,
                    help="override work_dir (the default cache lives at "
                         "<work_dir>/xla_cache)")
    ap.add_argument("--force", action="store_true",
                    help="re-prime specs the prime manifest already marks "
                         "primed for this environment")
    ap.add_argument("--spec", action="append", default=[],
                    help="additional BucketSpec JSON object(s) to prime "
                         "(besides the recorded manifest)")
    args = ap.parse_args(argv)

    from sm_distributed_tpu.utils.config import SMConfig
    from sm_distributed_tpu.utils.logger import init_logger

    init_logger()
    sm = (SMConfig.set_path(args.sm_config) if args.sm_config
          else SMConfig.get_conf())
    if args.work_dir:
        import dataclasses

        sm = dataclasses.replace(sm, work_dir=args.work_dir)

    from sm_distributed_tpu.ops import buckets
    from sm_distributed_tpu.parallel.distributed import compile_cache_path
    from sm_distributed_tpu.service.primer import (
        CachePrimer,
        _env_key,
        prime_spec,
    )

    cache_dir = compile_cache_path(sm)
    if cache_dir is None:
        print(json.dumps({"error": "compile cache disabled "
                                   "(parallel.compile_cache_dir=off)"}))
        return 2
    primer = CachePrimer(sm, busy=lambda: False)
    extra = [json.loads(s) for s in args.spec]
    for spec in extra:
        buckets.record_spec(spec)
    known = primer.known_specs()
    if not known:
        print(json.dumps({"known": 0, "compiled": 0, "skipped": 0,
                          "errors": 0, "cache_dir": str(cache_dir),
                          "note": "no recorded bucket specs — run traffic "
                                  "once or pass --spec"}))
        return 2
    if args.force:
        # bypass the prime manifest: compile everything flat directly
        out = {"compiled": 0, "skipped": 0, "errors": 0}
        env = _env_key()
        for spec in known:
            try:
                status = prime_spec(spec, sm_config=sm)
            except Exception:
                from sm_distributed_tpu.utils.logger import logger

                logger.warning("prime_cache: compile failed for %s",
                               buckets.spec_key(spec), exc_info=True)
                out["errors"] += 1
                continue
            if status == "compiled":
                out["compiled"] += 1
                primer._manifest.mark(buckets.spec_key(spec), env)
            else:
                out["skipped"] += 1
    else:
        out = primer.prime_once(abort_when_busy=False)
    summary = {"known": len(known), **{k: out.get(k, 0) for k in
                                       ("compiled", "skipped", "errors")},
               "cache_dir": str(cache_dir)}
    print(json.dumps(summary))
    return 1 if out.get("errors") else 0


if __name__ == "__main__":
    sys.exit(main())
