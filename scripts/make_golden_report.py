"""Regenerate the frozen golden report (tests/data/golden_spheroid.json).

The sci-regression tier of the reference pins every ion's metrics against a
committed report (``tests/sci_test_search_job_spheroid_dataset.py`` +
``tests/reports/`` [U], SURVEY.md §4).  This is our analog: BASELINE config
#1 (32x32 spheroid fixture, 50 formulas, +H) through the numpy_ref backend.

Run ONLY when an intentional semantic change invalidates the report; commit
the diff with the rationale.  Usage: python scripts/make_golden_report.py
"""

from __future__ import annotations

import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).parent.parent))

from sm_distributed_tpu.io.dataset import SpectralDataset          # noqa: E402
from sm_distributed_tpu.io.fixtures import generate_synthetic_dataset  # noqa: E402
from sm_distributed_tpu.models.msm_basic import MSMBasicSearch     # noqa: E402
from sm_distributed_tpu.utils.config import DSConfig, SMConfig     # noqa: E402

GOLDEN_PATH = Path(__file__).parent.parent / "tests" / "data" / "golden_spheroid.json"

# fixed generation recipe == tests/test_golden_report.py (do not drift)
GEN = dict(nrows=32, ncols=32, formulas=None, present_fraction=0.6,
           noise_peaks=200, mz_jitter_ppm=0.5, seed=7)
SM = {"backend": "numpy_ref", "fdr": {"decoy_sample_size": 20, "seed": 42},
      "parallel": {"formula_batch": 256}}
# adducts live in build_bundle's signature (it overrides isotope_generation)
DS = {"image_generation": {"ppm": 3.0}}


def build_bundle(tmp_dir: str | Path, backend: str = "numpy_ref",
                 preprocessing: bool = False,
                 adducts: tuple[str, ...] = ("+H",)):
    path, truth = generate_synthetic_dataset(Path(tmp_dir), **GEN)
    ds = SpectralDataset.from_imzml(path)
    sm = dict(SM, backend=backend)
    ds_cfg = {**DS,
              "isotope_generation": {"adducts": list(adducts)},
              "image_generation": {**DS["image_generation"],
                                   "do_preprocessing": preprocessing}}
    search = MSMBasicSearch(ds, truth.formulas, DSConfig.from_dict(ds_cfg),
                            SMConfig.from_dict(sm))
    return search.search()


def _report_dict(bundle) -> dict:
    return {
        "all_metrics": [
            {"sf": r.sf, "adduct": r.adduct, "is_target": bool(r.is_target),
             "chaos": float(r.chaos), "spatial": float(r.spatial),
             "spectral": float(r.spectral), "msm": float(r.msm)}
            for r in bundle.all_metrics.itertuples()
        ],
        "annotations": [
            {"sf": r.sf, "adduct": r.adduct, "msm": float(r.msm),
             "fdr": float(r.fdr), "fdr_level": float(r.fdr_level)}
            for r in bundle.annotations.itertuples()
        ],
    }


def main() -> None:
    import tempfile

    with tempfile.TemporaryDirectory() as td:
        bundle = build_bundle(td)
        bundle_pre = build_bundle(td, preprocessing=True)
        bundle_multi = build_bundle(td, adducts=("+H", "+Na", "+K"))
    report = _report_dict(bundle)
    # hotspot-clipping variant (image_generation.do_preprocessing=true, the
    # reference's default q=99 clip) pinned alongside — VERDICT r2 item 4
    report["preprocessing"] = _report_dict(bundle_pre)
    # the reference's full default positive-mode target set (per-adduct
    # FDR ranking over 3x the ions)
    report["multi_adduct"] = _report_dict(bundle_multi)
    GOLDEN_PATH.parent.mkdir(parents=True, exist_ok=True)
    GOLDEN_PATH.write_text(json.dumps(report, indent=1))
    print(f"wrote {GOLDEN_PATH}: {len(report['all_metrics'])} ions, "
          f"{len(report['annotations'])} annotations "
          f"(+{len(report['preprocessing']['all_metrics'])} preprocessed)")


if __name__ == "__main__":
    main()
