"""Per-batch + per-phase cost attribution of the DESI bench case.

Builds EXACTLY the bench.py `desi` workload (512x512 px, 500 formulas,
m/z-ordered stream, formula_batch=256) and attributes stream time:

1. per-batch serial fused timings (dispatch + forced readback),
2. probe_phases splits (extract / chaos / correlation / pattern) on
   representative batches (first, median-width, widest band),
3. the pipelined stream rate for reference.

Run on the real chip; needs the bench fixture cache (.cache/bench_ds_*).
"""

from __future__ import annotations

import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).parent.parent))

import numpy as np

from sm_distributed_tpu.io.dataset import SpectralDataset
from sm_distributed_tpu.io.fixtures import (
    expand_formula_list,
    generate_synthetic_dataset,
)
from sm_distributed_tpu.models.msm_basic import (
    _slice_table,
    make_backend,
    maybe_order_table,
)
from sm_distributed_tpu.ops.fdr import FDR
from sm_distributed_tpu.ops.isocalc import IsocalcWrapper
from sm_distributed_tpu.utils.config import DSConfig, SMConfig
from sm_distributed_tpu.utils.logger import init_logger, logger

from profile_bench import _force, timeit  # noqa: E402  (same dir)


def build(formula_batch=256, nrows=512, ncols=512, n_formulas=500):
    cache_dir = Path(__file__).parent.parent / ".cache"
    formulas = expand_formula_list(n_formulas)
    work_dir = cache_dir / f"bench_ds_{nrows}x{ncols}_f{n_formulas}"
    path, truth = generate_synthetic_dataset(
        work_dir, nrows=nrows, ncols=ncols, formulas=formulas,
        present_fraction=0.6, noise_peaks=200, seed=7, reuse=True)
    ds = SpectralDataset.from_imzml(path)
    ds_config = DSConfig.from_dict(
        {"isotope_generation": {"adducts": ["+H"]},
         "image_generation": {"ppm": 3.0}})
    fdr = FDR(decoy_sample_size=20, target_adducts=("+H",), seed=42)
    assignment = fdr.decoy_adduct_selection(truth.formulas)
    pairs, flags = assignment.all_ion_tuples(truth.formulas, ("+H",))
    calc = IsocalcWrapper(ds_config.isotope_generation,
                          cache_dir=str(cache_dir / "isocalc"))
    table = calc.pattern_table(pairs, flags)
    table = maybe_order_table(table, "auto", formula_batch)
    b = formula_batch
    batches = [_slice_table(table, s, min(s + b, table.n_ions))
               for s in range(0, table.n_ions, b)]
    sm_config = SMConfig.from_dict(
        {"backend": "jax_tpu", "fdr": {"decoy_sample_size": 20},
         "parallel": {"formula_batch": formula_batch,
                      "compile_cache_dir": str(cache_dir / "xla_cache")}})
    backend = make_backend("jax_tpu", ds, ds_config, sm_config, table=table)
    return ds, table, batches, backend


def main(formula_batch=256):
    init_logger()
    ds, table, batches, backend = build(formula_batch=formula_batch)
    t0 = time.perf_counter()
    backend.warmup(batches)
    logger.info("warmup: %.1fs", time.perf_counter() - t0)

    # 1. serial per-batch fused timings
    per_batch = []
    for i, t in enumerate(batches):
        plan = backend._flat_plan(t)
        variant = backend._variant_for(plan[7], plan[9])
        width = plan[9][1] if plan[9] else 0
        t0 = time.perf_counter()
        out, _n = backend._dispatch(t, plan)
        _force(out)
        dt = time.perf_counter() - t0
        per_batch.append((i, variant, width, dt))
    tot = sum(p[3] for p in per_batch)
    logger.info("serial total: %.2fs over %d batches", tot, len(per_batch))
    for i, variant, width, dt in per_batch:
        logger.info("batch %2d %-7s band_w=%9d  %6.1f ms",
                    i, variant, width, dt * 1e3)

    # 2. phase splits on representative batches
    widths = [p[2] for p in per_batch]
    reps = {0, int(np.argsort(widths)[len(widths) // 2]),
            int(np.argmax(widths)), len(batches) - 1}
    for i in sorted(reps):
        phases, info = backend.probe_phases(batches[i])
        logger.info("batch %d probe info: %s", i, info)
        for name, fn in phases.items():
            timeit(f"b{i}:{name}", fn, reps=3)

    # 3. pipelined stream rate (one rep)
    t0 = time.perf_counter()
    backend.score_batches(batches)
    dt = time.perf_counter() - t0
    logger.info("pipelined stream: %.2fs -> %.1f ions/s",
                dt, table.n_ions / dt)


if __name__ == "__main__":
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--formula-batch", type=int, default=256)
    a = ap.parse_args()
    main(formula_batch=a.formula_batch)
