#!/usr/bin/env python
"""Overload-protection load sweep (ISSUE 4 proof).

Drives a REAL in-process ``AnnotationService`` — spool, scheduler, admin
API, real ``SearchJob``s on synthetic fixtures — with the traffic mixes the
admission/cancellation/degradation layer exists for, and asserts the
serving invariants after each mix:

- **burst**: 4x-capacity submit burst → queue depth stays below the
  configured bound, every shed submit gets a structured 429/503 with a
  ``Retry-After`` header and a JSON ``reason``, every accepted job reaches
  a terminal state;
- **sustained**: paced tenant-rotating traffic → bounded depth, everything
  terminal;
- **deadline**: an expired-in-queue job and a trips-mid-run job → both
  terminal with a deadline error, no partial results, no debris;
- **cancel**: ``DELETE /jobs/<id>`` on a running job → terminal
  ``cancelled``, the attempt thread unwinds (zero live ``attempt-*``
  threads), the device token is released;
- **poison**: a job that fails every attempt dead-letters with its
  traceback; a message whose persisted ``service.claims`` says it
  crash-looped its claims moves to ``quarantine/`` (the real process-crash
  loop is proven by ``scripts/chaos_sweep.py`` — here the claim counter is
  pre-stamped so the sweep stays in-process);
- **breaker** (full matrix only): with ``backend=jax_tpu`` and injected
  device errors (``backend.device_error`` failpoint), the circuit breaker
  demonstrably opens, jobs degrade to numpy scoring, and after the faults
  are healed a half-open probe closes it again;
- **device_fault** (full matrix only, ISSUE 14): a 24-job surge over an
  8-chip pool with one chip going sticky mid-sweep — the chip is
  quarantined (``service/health.py``), no later grant includes it, every
  job lands in ``done/`` exactly once, and p99 queue-wait stays bounded
  despite the 7/8 pool;
- **disk** (full matrix only, ISSUE 10): sustained traffic under a 64 MB
  disk budget already past the trace floor — jobs complete with trace
  writes dropped, deepening pressure sheds submits with a structured 507
  + ``Retry-After``, and freeing the space recovers admissions in place;
- **replicas** (full matrix only, ISSUE 8): a 10k-tenant-id traffic model
  over THREE real scheduler replica processes sharing one partitioned
  spool (``scripts/replica_chaos.py --replica-serve --bare`` — null jobs,
  the mix measures the SCHEDULING plane).  One replica is SIGKILLed
  mid-sweep; the survivors fence + take over its shards and the asserts
  are: every job terminal in ``done/`` exactly once, p99 queue-wait
  bounded, and tenant-hash-bucket fairness (no bucket's mean wait runs
  away from the global median);
- **pod** (full matrix only, ISSUE 17): a simulated 2-host pod — four
  replicas, two per named host (``SM_HOST_NAME``/``SM_PROCESS_ID``) —
  loses host h1 WHOLE mid-sweep (both its replicas SIGKILLed at once).
  All jobs terminal exactly once, p99 bounded, and the survivors' host
  watchdogs demonstrably evicted the dead host
  (``sm_pod_host_evictions_total``);
- **stream** (full matrix only, ISSUE 19): two live acquisitions chunked
  over HTTP (``mode=stream`` + ``POST /datasets/<id>/pixels``) into TWO
  replicas sharing one spool, while a batch burst contends for the
  worker pool and readers poll a published dataset; one replica is
  DRAINED mid-acquisition and its live stream hands off to the peer
  without burning an attempt — provisional re-rank coverage must keep
  pace with the instrument, every read answers 200 across the drain,
  and both streams must converge bit-identically (``check_exact``) to
  the batch report of the same spectra.

Usage::

    python scripts/load_sweep.py              # full matrix
    python scripts/load_sweep.py --smoke      # burst + poison + deadline (CI)
    python scripts/load_sweep.py --keep --work DIR

``SM_FAILPOINTS`` may be exported to combine any mix with fault injection
(raise/sleep/torn actions only — a ``crash`` action would kill the driver
itself; use the chaos sweep for process-death faults).
"""

from __future__ import annotations

import argparse
import json
import sys
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO_ROOT))

from scripts.chaos_sweep import _debris  # noqa: E402 — shared invariant
from sm_distributed_tpu.engine.daemon import annotate_callback  # noqa: E402
from sm_distributed_tpu.io.fixtures import generate_synthetic_dataset  # noqa: E402
from sm_distributed_tpu.models import breaker as breaker_mod  # noqa: E402
from sm_distributed_tpu.service import AnnotationService  # noqa: E402
from sm_distributed_tpu.utils import failpoints  # noqa: E402
from sm_distributed_tpu.utils.config import SMConfig  # noqa: E402

TERMINAL = ("done", "failed", "cancelled", "quarantined")


class SweepError(AssertionError):
    pass


def _check(cond, msg: str) -> None:
    if not cond:
        raise SweepError(msg)


# ---------------------------------------------------------------- HTTP glue
def _http(base: str, method: str, path: str, body: dict | None = None):
    """(status, headers, parsed-json) — 4xx/5xx returned, not raised."""
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(base + path, method=method, data=data,
                                 headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=30.0) as r:
            return r.status, dict(r.headers), json.loads(r.read() or b"{}")
    except urllib.error.HTTPError as e:
        raw = e.read()
        try:
            parsed = json.loads(raw or b"{}")
        except json.JSONDecodeError:
            parsed = {"raw": raw.decode(errors="replace")}
        return e.code, dict(e.headers), parsed


# ------------------------------------------------------------------ harness
class Harness:
    """One service instance + the assertion helpers every mix shares."""

    def __init__(self, base: Path, name: str, sm_overrides: dict | None = None):
        self.dir = base / name
        self.queue_dir = self.dir / "queue"
        self.root = self.queue_dir / "sm_annotate"
        sm = {
            "backend": "numpy_ref",
            "fdr": {"decoy_sample_size": 2, "seed": 1},
            "parallel": {"formula_batch": 8, "checkpoint_every": 1,
                         "resident_datasets": 2, "order_ions": "table"},
            "storage": {"results_dir": str(self.dir / "results"),
                        "store_images": False},
            "work_dir": str(self.dir / "work"),
            "service": {
                "workers": 2, "poll_interval_s": 0.02, "job_timeout_s": 30.0,
                "max_attempts": 2, "backoff_base_s": 0.05,
                "backoff_max_s": 0.2, "backoff_jitter": 0.0,
                "heartbeat_interval_s": 0.1, "stale_after_s": 2.0,
                "drain_timeout_s": 20.0, "cancel_grace_s": 10.0,
                "quarantine_after": 3, "http_port": 0,
                "admission": {"max_queue_depth": 6, "max_tenant_inflight": 4,
                              "retry_after_s": 1.0},
            },
        }
        if sm_overrides:
            sm = _merge(sm, sm_overrides)
        self.sm_config = SMConfig.from_dict(sm)
        self.service = AnnotationService(
            self.queue_dir, annotate_callback(self.sm_config),
            sm_config=self.sm_config)
        self.service.start()
        host, port = self.service.api.address
        self.base = f"http://{host}:{port}"

    # ------------------------------------------------------------- actions
    def submit(self, msg: dict):
        return _http(self.base, "POST", "/submit", msg)

    def delete(self, msg_id: str):
        return _http(self.base, "DELETE", f"/jobs/{msg_id}")

    def jobs(self) -> dict:
        _s, _h, rows = _http(self.base, "GET", "/jobs")
        return {r["msg_id"]: r for r in rows}

    def metrics_text(self) -> str:
        req = urllib.request.Request(self.base + "/metrics")
        with urllib.request.urlopen(req, timeout=30.0) as r:
            return r.read().decode()

    def wait_terminal(self, msg_ids, timeout_s: float = 120.0) -> dict:
        deadline = time.time() + timeout_s
        while time.time() < deadline:
            rows = self.jobs()
            if all(m in rows and rows[m]["state"] in TERMINAL
                   for m in msg_ids):
                return rows
            time.sleep(0.05)
        rows = self.jobs()
        missing = {m: rows.get(m, {}).get("state", "absent") for m in msg_ids
                   if rows.get(m, {}).get("state") not in TERMINAL}
        raise SweepError(f"jobs never reached a terminal state: {missing}")

    # ---------------------------------------------------------- invariants
    def sample_depth(self) -> int:
        """Admitted-but-not-terminal occupancy as seen on disk."""
        return (len(list(self.root.glob("pending/*.json")))
                + len(list(self.root.glob("running/*.json"))))

    def assert_clean(self, label: str) -> None:
        zombies = [t.name for t in threading.enumerate()
                   if t.name.startswith("attempt-") and t.is_alive()]
        _check(not zombies, f"{label}: live attempt threads leaked: {zombies}")
        token = self.service.scheduler.device_token
        got = token.acquire(timeout=1.0)
        _check(got, f"{label}: device token still held")
        if got:
            token.release()
        leftovers = _debris([self.root, self.dir / "results",
                             self.dir / "work"])
        # checkpoint shards under work/ are legitimate mid-crash resume
        # state for FAILED jobs; everything else must be gone
        leftovers = [p for p in leftovers if ".ckpt." not in p]
        _check(not leftovers, f"{label}: tmp/heartbeat debris: {leftovers}")
        _check(not list(self.root.glob("running/*")),
               f"{label}: running/ not empty after drain")

    def shutdown(self):
        self.service.shutdown()


def _merge(base: dict, over: dict) -> dict:
    out = dict(base)
    for k, v in over.items():
        if isinstance(v, dict) and isinstance(out.get(k), dict):
            out[k] = _merge(out[k], v)
        else:
            out[k] = v
    return out


# ----------------------------------------------------------------- fixtures
def build_fixtures(base: Path) -> dict:
    """One tiny dataset every job shares (the isocalc cache + resident
    backend warm after job 1, so burst jobs are fast).  Mixes that need a
    deterministically LONG job arm a ``device.score_batch=sleep:...``
    failpoint instead of guessing at a bigger fixture's duration."""
    fast_path, fast_truth = generate_synthetic_dataset(
        base / "fx_fast", nrows=8, ncols=8, formulas=None,
        present_fraction=0.5, noise_peaks=30, seed=11)
    return {
        "fast": {"input_path": str(fast_path),
                 "formulas": fast_truth.formulas[:3],
                 "ds_config": {"isotope_generation": {"adducts": ["+H"]}}},
    }


def _msg(fx: dict, kind: str, ds_id: str, **extra) -> dict:
    m = {"ds_id": ds_id, "msg_id": ds_id, **fx[kind], **extra}
    return m


# -------------------------------------------------------------------- mixes
def mix_burst(h: Harness, fx: dict, n_submit: int) -> None:
    """4x-capacity burst: bounded depth, structured sheds, all accepted
    jobs terminal."""
    cap = h.sm_config.service.admission.max_queue_depth
    accepted, shed = [], []
    max_depth = 0
    for i in range(n_submit):
        status, headers, body = h.submit(
            _msg(fx, "fast", f"burst{i}", tenant=f"t{i % 3}"))
        if status == 202:
            accepted.append(body["msg_id"])
        else:
            shed.append((status, headers, body))
        max_depth = max(max_depth, h.sample_depth())
    _check(accepted, "burst: nothing was accepted")
    _check(shed, f"burst: {n_submit} submits at capacity {cap} shed nothing")
    for status, headers, body in shed:
        _check(status in (429, 503), f"burst: shed status {status}")
        _check("Retry-After" in headers,
               f"burst: shed response missing Retry-After: {headers}")
        _check(body.get("reason") in ("queue_full", "tenant_quota",
                                      "latency_overload"),
               f"burst: unstructured shed body {body}")
        _check("retry_after_s" in body and "error" in body,
               f"burst: shed body missing fields {body}")
    rows = h.wait_terminal(accepted)
    bad = [m for m in accepted if rows[m]["state"] != "done"]
    _check(not bad, f"burst: accepted jobs not done: "
                    f"{[(m, rows[m]['state'], rows[m]['error']) for m in bad]}")
    # the depth bound: pending+running on disk never exceeded the admission
    # cap (direct spool publishes would bypass it; everything here is HTTP)
    _check(max_depth <= cap,
           f"burst: observed depth {max_depth} > configured bound {cap}")
    while h.sample_depth():
        time.sleep(0.05)
    h.assert_clean("burst")
    print(f"  burst: {len(accepted)} accepted, {len(shed)} shed "
          f"(max depth {max_depth}/{cap})")


def mix_sustained(h: Harness, fx: dict, n_submit: int, gap_s: float) -> None:
    cap = h.sm_config.service.admission.max_queue_depth
    accepted, shed = [], []
    max_depth = 0
    for i in range(n_submit):
        status, _hd, body = h.submit(
            _msg(fx, "fast", f"sus{i}", tenant=f"t{i % 4}"))
        (accepted if status == 202 else shed).append(
            body.get("msg_id", f"sus{i}"))
        max_depth = max(max_depth, h.sample_depth())
        time.sleep(gap_s)
    rows = h.wait_terminal(accepted)
    bad = [m for m in accepted if rows[m]["state"] != "done"]
    _check(not bad, f"sustained: not done: {bad}")
    _check(max_depth <= cap, f"sustained: depth {max_depth} > {cap}")
    text = h.metrics_text()
    _check("sm_admission_latency_ewma_s" in text,
           "sustained: EWMA gauge missing from /metrics")
    h.assert_clean("sustained")
    print(f"  sustained: {len(accepted)} accepted, {len(shed)} shed "
          f"(max depth {max_depth}/{cap})")


def mix_deadline(h: Harness, fx: dict) -> None:
    prev = failpoints.active_spec()
    # every checkpoint group sleeps: jobs become deterministically long, so
    # the mid-run job's deadline reliably trips BETWEEN group boundaries
    failpoints.configure("device.score_batch=sleep:0.35")
    try:
        # starts immediately on an idle worker; ~1s of scoring against a
        # 0.6s deadline → the cancel lands mid-attempt
        status, _hd, body = h.submit(
            _msg(fx, "fast", "dl_midrun", deadline_s=0.6))
        _check(status == 202, f"deadline: submit failed ({status})")
        midrun_id = body["msg_id"]
        # occupy the remaining workers so the tight-deadline job below
        # expires while still QUEUED
        occupiers = []
        for i in range(2):
            status, _hd, body = h.submit(_msg(fx, "fast", f"occupy{i}"))
            _check(status == 202, f"deadline: occupier shed ({status})")
            occupiers.append(body["msg_id"])
        status, _hd, body = h.submit(
            _msg(fx, "fast", "dl_queued", deadline_s=0.05))
        _check(status == 202, f"deadline: submit failed ({status})")
        queued_id = body["msg_id"]
        rows = h.wait_terminal(occupiers + [queued_id, midrun_id])
    finally:
        failpoints.configure(prev)
    for mid, marker in ((queued_id, "before start"),
                        (midrun_id, "deadline")):
        _check(rows[mid]["state"] == "failed",
               f"deadline: {mid} state {rows[mid]['state']} "
               f"({rows[mid]['error']!r})")
        _check("deadline" in rows[mid]["error"] and marker in rows[mid]["error"],
               f"deadline: {mid} error {rows[mid]['error']!r}")
        _check(rows[mid]["attempts"] <= 1,
               f"deadline: {mid} was retried ({rows[mid]['attempts']} attempts)")
        dl = json.loads((h.root / "failed" / f"{mid}.json").read_text())
        _check("deadline" in dl["error"], f"deadline: spool file {dl}")
    # no partial results for the mid-run expiry
    _check(not (h.dir / "results" / "dl_midrun" / "annotations.parquet").exists(),
           "deadline: cancelled job stored partial results")
    h.assert_clean("deadline")
    print(f"  deadline: queued-expiry + mid-run expiry both terminal, "
          f"occupiers {[rows[m]['state'] for m in occupiers]}")


def mix_cancel(h: Harness, fx: dict) -> None:
    prev = failpoints.active_spec()
    failpoints.configure("device.score_batch=sleep:0.35")
    try:
        status, _hd, body = h.submit(_msg(fx, "fast", "cancel_me"))
        _check(status == 202, f"cancel: submit failed ({status})")
        mid = body["msg_id"]
        deadline = time.time() + 30.0
        while time.time() < deadline:
            rows = h.jobs()
            if rows.get(mid, {}).get("state") == "running":
                break
            time.sleep(0.02)
        else:
            raise SweepError("cancel: job never started running")
        status, _hd, body = h.delete(mid)
    finally:
        failpoints.configure(prev)
    _check(status in (200, 202), f"cancel: DELETE status {status} {body}")
    rows = h.wait_terminal([mid])
    _check(rows[mid]["state"] == "cancelled",
           f"cancel: state {rows[mid]['state']} ({rows[mid]['error']!r})")
    dl = json.loads((h.root / "failed" / f"{mid}.json").read_text())
    _check(dl.get("cancelled") is True, f"cancel: spool file {dl}")
    _check(not (h.dir / "results" / "cancel_me" / "annotations.parquet").exists(),
           "cancel: cancelled job stored results")
    # second DELETE reports terminal, unknown id is a structured 404
    status, _hd, _b = h.delete(mid)
    _check(status == 409, f"cancel: re-DELETE status {status}")
    status, _hd, _b = h.delete("no_such_job")
    _check(status == 404, f"cancel: unknown-id status {status}")
    h.assert_clean("cancel")
    print("  cancel: running job cancelled cleanly, token released")


def mix_poison(h: Harness, fx: dict) -> None:
    # (a) fails every attempt → dead-letter with the traceback
    status, _hd, body = h.submit(
        {"ds_id": "poison_dl", "msg_id": "poison_dl",
         "input_path": str(h.dir / "nope.imzML")})
    _check(status == 202, f"poison: submit failed ({status})")
    dl_id = body["msg_id"]
    # (b) a crash-looper: its persisted claim counter says it has been
    # claimed quarantine_after times without a terminal outcome (the chaos
    # sweep proves the counter moves under real process crashes)
    q_after = h.sm_config.service.quarantine_after
    status, _hd, body = h.submit(
        _msg(fx, "fast", "poison_q",
             service={"claims": q_after, "last_error": "simulated crash loop"}))
    _check(status == 202, f"poison: submit failed ({status})")
    q_id = body["msg_id"]
    rows = h.wait_terminal([dl_id, q_id])
    _check(rows[dl_id]["state"] == "failed",
           f"poison: dead-letter state {rows[dl_id]['state']}")
    dl = json.loads((h.root / "failed" / f"{dl_id}.json").read_text())
    _check(dl["attempts"] == h.sm_config.service.max_attempts
           and "traceback" in dl, f"poison: dead-letter evidence {list(dl)}")
    _check(rows[q_id]["state"] == "quarantined",
           f"poison: quarantine state {rows[q_id]['state']}")
    qf = json.loads((h.root / "quarantine" / f"{q_id}.json").read_text())
    _check("quarantine_reason" in qf
           and qf["service"]["claims"] == q_after + 1,
           f"poison: quarantine evidence {qf}")
    _check("sm_jobs_quarantined_total 1" in h.metrics_text(),
           "poison: quarantine counter missing from /metrics")
    h.assert_clean("poison")
    print("  poison: dead-letter w/ traceback + quarantine/ both reached")


def mix_breaker(base: Path, fx: dict) -> None:
    """Device errors open the breaker; jobs degrade to numpy; healing +
    cooldown recovers through a half-open probe (backend=jax_tpu on
    whatever platform jax has — CPU in CI)."""
    breaker_mod.reset_device_breaker()
    h = Harness(base, "breaker", sm_overrides={
        "backend": "jax_tpu",
        "service": {"max_attempts": 3, "breaker_threshold": 2,
                    "breaker_cooldown_s": 0.5},
    })
    try:
        failpoints.configure("backend.device_error=raise:RuntimeError?1")
        ids = []
        for name in ("brk1", "brk2"):
            status, _hd, body = h.submit(_msg(fx, "fast", name))
            _check(status == 202, f"breaker: submit failed ({status})")
            ids.append(body["msg_id"])
            h.wait_terminal([body["msg_id"]])
        rows = h.jobs()
        _check(all(rows[m]["state"] == "done" for m in ids),
               f"breaker: jobs under device faults not done: "
               f"{[(m, rows[m]['state']) for m in ids]}")
        # per-chip breakers (ISSUE 14): the leased jobs answer to their
        # CHIP's breaker, not the un-leased "*" singleton
        brk = breaker_mod.breaker_for("0")
        _check(brk is not None and brk.state == "open",
               f"breaker: expected chip-0 breaker open after injected "
               f"faults, got {brk.state if brk else 'absent'}")
        # heal the device, wait out the cooldown, probe
        failpoints.configure(None)
        time.sleep(h.sm_config.service.breaker_cooldown_s + 0.1)
        status, _hd, body = h.submit(_msg(fx, "fast", "brk_probe"))
        _check(status == 202, f"breaker: probe submit failed ({status})")
        h.wait_terminal([body["msg_id"]])
        rows = h.jobs()
        _check(rows[body["msg_id"]]["state"] == "done",
               f"breaker: probe job {rows[body['msg_id']]['state']}")
        _check(brk.state == "closed",
               f"breaker: expected closed after probe, got {brk.state}")
        hops = [(f, t) for _ts, f, t in brk.transitions]
        for hop in (("closed", "open"), ("open", "half_open"),
                    ("half_open", "closed")):
            _check(hop in hops, f"breaker: transition {hop} missing: {hops}")
        text = h.metrics_text()
        _check("sm_breaker_degraded_total" in text
               and 'sm_breaker_transitions_total{device="0",to="open"}'
               in text,
               "breaker: /metrics missing breaker families (per-chip "
               "device label, ISSUE 14)")
        h.assert_clean("breaker")
        print(f"  breaker: opened, degraded to numpy, recovered "
              f"(transitions {hops})")
    finally:
        failpoints.configure(None)
        h.shutdown()
        breaker_mod.reset_device_breaker()


def mix_disk(base: Path, fx: dict) -> None:
    """Disk-pressure mix (ISSUE 10): sustained traffic under a 64 MB disk
    budget already past the trace floor — every job completes with its
    trace writes dropped, deepening the pressure to the submit floor sheds
    with a structured 507 + Retry-After, and freeing the space recovers
    admissions without a restart."""
    mb = 1 << 20
    h = Harness(base, "disk", sm_overrides={
        "resources": {"disk_budget_bytes": 64 * mb,
                      "trace_floor_bytes": 48 * mb,
                      "cache_floor_bytes": 24 * mb,
                      "submit_floor_bytes": 8 * mb,
                      "gc_interval_s": 0.2},
    })
    filler = Path(h.sm_config.work_dir) / "filler.bin"
    filler.parent.mkdir(parents=True, exist_ok=True)
    try:
        governor = h.service.resources
        filler.write_bytes(b"\0" * (20 * mb))   # past the trace floor
        deadline = time.time() + 10.0
        while governor.level() < 1 and time.time() < deadline:
            time.sleep(0.05)
        _check(governor.level() == 1, "disk: never reached trace-drop level")
        accepted = []
        for i in range(6):
            status, _hd, body = h.submit(
                _msg(fx, "fast", f"disk{i}", tenant=f"t{i % 2}"))
            _check(status == 202, f"disk: level-1 submit shed ({status})")
            accepted.append(body["msg_id"])
        rows = h.wait_terminal(accepted)
        bad = [m for m in accepted if rows[m]["state"] != "done"]
        _check(not bad, f"disk: jobs under trace-drop not done: {bad}")
        from sm_distributed_tpu.utils import tracing

        for m in accepted:
            tid = rows[m]["trace_id"]
            _check(not tracing.trace_path(h.service.trace_dir, tid).exists(),
                   f"disk: {m} wrote a trace file under pressure")
        text = h.metrics_text()
        _check('sm_disk_degraded_writes_total{kind="trace"}' in text,
               "disk: trace-drop counter missing from /metrics")
        # deepen to the submit floor: structured 507 shed
        from sm_distributed_tpu.service.resources import LEVEL_SHED_SUBMITS

        filler.write_bytes(b"\0" * (60 * mb))
        deadline = time.time() + 10.0
        while governor.level() < LEVEL_SHED_SUBMITS and \
                time.time() < deadline:
            time.sleep(0.05)
        status, headers, body = h.submit(_msg(fx, "fast", "disk_shed"))
        _check(status == 507 and body.get("reason") == "disk_exhausted",
               f"disk: expected structured 507, got {status} {body}")
        _check("Retry-After" in headers, f"disk: no Retry-After: {headers}")
        # free the space: admissions recover in place
        filler.unlink()
        deadline = time.time() + 10.0
        while governor.level() > 0 and time.time() < deadline:
            time.sleep(0.05)
        status, _hd, body = h.submit(_msg(fx, "fast", "disk_recovered"))
        _check(status == 202, f"disk: post-recovery submit shed ({status})")
        h.wait_terminal([body["msg_id"]])
        h.assert_clean("disk")
        print(f"  disk: 6 jobs golden under trace-drop, 507 at the submit "
              f"floor, recovery after free-up")
    finally:
        if filler.exists():
            filler.unlink()
        h.shutdown()


def mix_device_fault(base: Path, fx: dict, n_jobs: int = 24,
                     p99_bound_s: float = 20.0) -> None:
    """Surge mix where one chip goes sticky mid-sweep (ISSUE 14): 24 jobs
    across 3 tenants over an 8-chip pool; once the surge is in flight,
    chip 3 takes an attributed sticky fault and is quarantined.  Asserts:
    every job terminal in ``done/`` exactly once, zero lost/dup spool
    messages, NO lease granted on the quarantined chip afterwards, p99
    queue-wait bounded despite the 7/8 pool, and the quarantine visible on
    /metrics.  Jobs score on numpy_ref — the pool is a scheduling-plane
    resource here, so the mix measures placement, not kernels."""
    from sm_distributed_tpu.models import faults as faults_mod

    h = Harness(base, "device_fault", sm_overrides={
        "service": {"workers": 6, "device_pool_size": 8,
                    "devices_per_job": 1, "max_attempts": 2,
                    "admission": {"max_queue_depth": 64,
                                  "max_tenant_inflight": 32}},
    })
    pool = h.service.device_pool
    granted_on_dead: list[dict] = []
    stop = threading.Event()
    quarantined_at = [0.0]
    holder_at_quarantine = [None]

    def _watch():
        # no NEW grant may include chip 3 after its quarantine (a lease
        # already holding it when the verdict lands finishes on its own —
        # quarantine fences placement, it does not revoke)
        while not stop.wait(0.01):
            if not quarantined_at[0]:
                continue
            snap = pool.snapshot()
            holder = snap["holders"].get("3")
            if holder is not None and holder != holder_at_quarantine[0]:
                granted_on_dead.append(snap["holders"])

    watcher = threading.Thread(target=_watch, daemon=True)
    watcher.start()
    try:
        # every batch-group score sleeps, so the surge keeps the pool busy
        # long enough for the mid-sweep fault to land under load
        failpoints.configure("device.score_batch=sleep:0.1")
        ids = []
        for i in range(n_jobs):
            status, _hd, body = h.submit(
                _msg(fx, "fast", f"df{i}", tenant=f"t{i % 3}"))
            _check(status == 202,
                   f"device_fault: submit {i} shed ({status})")
            ids.append(body["msg_id"])
            if i == n_jobs // 3:
                # mid-sweep: chip 3 goes sticky (1-chip attribution —
                # quarantined outright, models/faults.py taxonomy)
                holder_at_quarantine[0] = (
                    pool.snapshot()["holders"].get("3"))
                faults_mod.report_device_fault(
                    (3,), faults_mod.FAULT_STICKY, "sweep-injected sticky")
                quarantined_at[0] = time.time()
                _check(pool.health.state_of(3) == "quarantined",
                       "device_fault: chip 3 not quarantined")
        rows = h.wait_terminal(ids, timeout_s=180.0)
        bad = [m for m in ids if rows[m]["state"] != "done"]
        _check(not bad, f"device_fault: jobs not done: "
                        f"{[(m, rows[m]['state']) for m in bad]}")
        # exactly-once: every job in done/ once, nowhere else
        done = sorted(p.stem for p in (h.root / "done").glob("df*.json"))
        _check(done == sorted(ids),
               f"device_fault: done/ census mismatch ({len(done)} vs "
               f"{len(ids)})")
        for state in ("pending", "running", "failed", "quarantine"):
            leftover = list((h.root / state).glob("df*.json"))
            _check(not leftover,
                   f"device_fault: {state}/ not empty: {leftover}")
        _check(not granted_on_dead,
               f"device_fault: quarantined chip 3 appeared in grants: "
               f"{granted_on_dead[:3]}")
        # p99 queue wait bounded despite the 7/8 pool
        waits = sorted(max(0.0, rows[m]["started_at"]
                           - rows[m]["published_at"]) for m in ids)
        p99 = waits[min(len(waits) - 1, int(0.99 * len(waits)))]
        _check(p99 <= p99_bound_s,
               f"device_fault: p99 queue wait {p99:.2f}s > {p99_bound_s}s")
        text = h.metrics_text()
        _check("sm_device_quarantines_total 1" in text
               or "sm_device_quarantines_total" in text
               and pool.health.snapshot()["quarantines_total"] >= 1,
               "device_fault: quarantine not on /metrics")
        _check('sm_device_health{device="3"} 2' in text,
               "device_fault: sm_device_health gauge missing/incorrect")
        h.assert_clean("device_fault")
        print(f"  device_fault: {n_jobs} jobs done exactly-once on the "
              f"7/8 pool (p99 queue wait {p99:.2f}s), chip 3 quarantined "
              f"and never re-leased")
    finally:
        stop.set()
        watcher.join(timeout=2.0)
        failpoints.configure(None)
        h.shutdown()


def mix_replicas(base: Path, n_jobs: int = 600, tenant_space: int = 10_000,
                 n_replicas: int = 3, p99_bound_s: float = 30.0) -> None:
    """Multi-replica, 10k-tenant scheduling-plane mix with a mid-sweep
    replica kill (ISSUE 8 satellite; ROADMAP open item 2).

    Jobs are null callbacks (``--bare``): the mix measures claim latency,
    shard partitioning, takeover, and fairness — not scoring.  Queue wait
    per message is read back from the drained spool (the scheduler stamps
    ``service.claimed_at`` at every claim)."""
    import signal as _signal
    import subprocess

    rng = __import__("random").Random(8)
    mix_dir = base / "replicas"
    queue_dir = mix_dir / "queue"
    root = queue_dir / "sm_annotate"
    sm = {
        "backend": "numpy_ref",
        "work_dir": str(mix_dir / "work"),
        "storage": {"results_dir": str(mix_dir / "results")},
        "service": {
            "workers": 4, "poll_interval_s": 0.02, "job_timeout_s": 30.0,
            "max_attempts": 2, "backoff_base_s": 0.05, "backoff_max_s": 0.2,
            "backoff_jitter": 0.0, "heartbeat_interval_s": 0.2,
            "stale_after_s": 1.0, "drain_timeout_s": 20.0, "http_port": 0,
            "replicas": n_replicas, "spool_shards": 16,
            "replica_heartbeat_interval_s": 0.25,
            "replica_stale_after_s": 1.0, "takeover_interval_s": 0.3,
        },
    }
    mix_dir.mkdir(parents=True, exist_ok=True)
    sm_conf = mix_dir / "sm.json"
    sm_conf.write_text(json.dumps(sm, indent=2))
    from sm_distributed_tpu.engine.daemon import QueuePublisher

    pub = QueuePublisher(queue_dir)
    t_publish = time.time()
    for i in range(n_jobs):
        pub.publish({
            "ds_id": f"lj{i}", "msg_id": f"lj{i:05d}",
            "input_path": "null://", "tenant": f"t{rng.randrange(tenant_space)}",
        })
    script = str(REPO_ROOT / "scripts" / "replica_chaos.py")
    env = dict(__import__("os").environ)
    env.pop("SM_FAILPOINTS", None)
    procs = {}
    logs = {}
    for i in range(n_replicas):
        rid = f"r{i}"
        log = open(mix_dir / f"{rid}.log", "w")
        logs[rid] = log
        procs[rid] = subprocess.Popen(
            [sys.executable, script, "--replica-serve", str(queue_dir),
             str(sm_conf), "--replica-id", rid, "--bare",
             "--null-sleep", "0.002", "--idle-exit", "2.0"],
            env=env, stdout=log, stderr=log, cwd=str(REPO_ROOT))
    victim = procs["r0"]
    killed = False
    deadline = time.time() + 300.0
    try:
        while time.time() < deadline:
            done = len(list((root / "done").glob("*.json")))
            if not killed and done >= n_jobs // 3:
                # mid-sweep kill: no drain, no cleanup — claims die in
                # running/ and the survivors must fence + take them over
                victim.send_signal(_signal.SIGKILL)
                killed = True
                print(f"  replicas: killed r0 at {done}/{n_jobs} done")
            if done >= n_jobs:
                break
            if all(p.poll() is not None for p in procs.values()):
                raise SweepError(
                    f"replicas: all exited at {done}/{n_jobs} done")
            time.sleep(0.1)
        else:
            raise SweepError(
                f"replicas: did not drain in time "
                f"({len(list((root / 'done').glob('*.json')))}/{n_jobs})")
        _check(killed, "replicas: kill point never reached")
        drain_s = time.time() - t_publish
        for rid, p in procs.items():
            if rid == "r0":
                continue
            p.wait(timeout=30)
    finally:
        for p in procs.values():
            if p.poll() is None:
                p.kill()
        for log in logs.values():
            log.close()
    # ---- invariants from the drained spool -----------------------------
    done_msgs = list((root / "done").glob("*.json"))
    _check(len(done_msgs) == n_jobs,
           f"replicas: {len(done_msgs)}/{n_jobs} done")
    for state in ("pending", "running", "failed", "quarantine"):
        left = list((root / state).glob("*.json"))
        _check(not left, f"replicas: {len(left)} messages left in {state}/")
    waits_by_bucket: dict[int, list[float]] = {}
    waits = []
    import zlib

    for p in done_msgs:
        msg = json.loads(p.read_text())
        svc = msg.get("service", {})
        w = float(svc.get("claimed_at", 0.0)) - float(msg["published_at"])
        _check(w >= 0, f"replicas: negative queue wait on {p.name}")
        waits.append(w)
        bucket = zlib.crc32(str(msg.get("tenant")).encode()) % 10
        waits_by_bucket.setdefault(bucket, []).append(w)
    waits.sort()
    p50 = waits[len(waits) // 2]
    p99 = waits[min(len(waits) - 1, int(len(waits) * 0.99))]
    _check(p99 <= p99_bound_s,
           f"replicas: p99 queue wait {p99:.2f}s > bound {p99_bound_s}s")
    # fairness across the 10k-tenant space: hash tenants into 10 buckets;
    # no bucket's MEAN wait may run away from the global median (a starved
    # tenant class would show up as a hot bucket)
    means = {b: sum(v) / len(v) for b, v in waits_by_bucket.items()}
    worst = max(means.values())
    _check(worst <= max(4.0 * p50, p99, 2.0),
           f"replicas: unfair bucket mean {worst:.2f}s vs p50 {p50:.2f}s "
           f"(means {means})")
    print(f"  replicas: {n_jobs} jobs / {len({json.loads(p.read_text()).get('tenant') for p in done_msgs})} "
          f"tenants over {n_replicas} replicas, r0 killed mid-sweep; "
          f"drain {drain_s:.1f}s, queue-wait p50 {p50:.2f}s p99 {p99:.2f}s, "
          f"worst bucket mean {worst:.2f}s")


def mix_pod(base: Path, n_jobs: int = 240, p99_bound_s: float = 30.0) -> None:
    """Pod host-loss wave (ISSUE 17; ROADMAP item 2).

    A simulated 2-host pod: four bare scheduler replicas over one
    partitioned spool, two per named host (``SM_HOST_NAME`` /
    ``SM_PROCESS_ID`` — the launcher env contract), every replica running
    the host watchdog over the shared registry's per-process beat groups.
    Mid-sweep BOTH of host h1's replicas are SIGKILLed at once — a whole
    host dying, not a lone replica crash.  Asserts: every job terminal in
    ``done/`` exactly once (the survivors fence + take over the dead
    host's shards), p99 queue-wait bounded despite half the pod gone, and
    the survivors' exit metrics show the watchdog saw it
    (``sm_pod_host_evictions_total`` >= 1,
    ``sm_pod_process_up{process="1"}`` == 0)."""
    import signal as _signal
    import subprocess

    rng = __import__("random").Random(17)
    mix_dir = base / "pod"
    queue_dir = mix_dir / "queue"
    root = queue_dir / "sm_annotate"
    sm = {
        "backend": "numpy_ref",
        "work_dir": str(mix_dir / "work"),
        "storage": {"results_dir": str(mix_dir / "results")},
        "service": {
            "workers": 4, "poll_interval_s": 0.02, "job_timeout_s": 30.0,
            "max_attempts": 2, "backoff_base_s": 0.05, "backoff_max_s": 0.2,
            "backoff_jitter": 0.0, "heartbeat_interval_s": 0.2,
            "stale_after_s": 1.0, "drain_timeout_s": 20.0, "http_port": 0,
            "quarantine_after": 20,
            "replicas": 4, "spool_shards": 16,
            "replica_heartbeat_interval_s": 0.25,
            "replica_stale_after_s": 1.0, "takeover_interval_s": 0.3,
            # each replica's own 2-domain pool + host watchdog: process i
            # ↔ domain i, so the survivors' watchdogs fence domain 1 when
            # h1's beat group goes stale
            "device_pool_size": 4, "device_pool_hosts": 2,
            "host_watchdog_interval_s": 0.25, "host_stale_after_s": 1.0,
        },
    }
    mix_dir.mkdir(parents=True, exist_ok=True)
    sm_conf = mix_dir / "sm.json"
    sm_conf.write_text(json.dumps(sm, indent=2))
    from sm_distributed_tpu.engine.daemon import QueuePublisher

    pub = QueuePublisher(queue_dir)
    t_publish = time.time()
    for i in range(n_jobs):
        pub.publish({
            "ds_id": f"pj{i}", "msg_id": f"pj{i:05d}",
            "input_path": "null://", "tenant": f"t{rng.randrange(500)}",
        })
    script = str(REPO_ROOT / "scripts" / "replica_chaos.py")
    placement = {"r0": ("h0", 0), "r1": ("h0", 0),
                 "r2": ("h1", 1), "r3": ("h1", 1)}
    procs = {}
    logs = {}
    for rid, (host, pid) in placement.items():
        env = dict(__import__("os").environ)
        env.pop("SM_FAILPOINTS", None)
        env["SM_HOST_NAME"] = host
        env["SM_PROCESS_ID"] = str(pid)
        log = open(mix_dir / f"{rid}.log", "w")
        logs[rid] = log
        procs[rid] = subprocess.Popen(
            [sys.executable, script, "--replica-serve", str(queue_dir),
             str(sm_conf), "--replica-id", rid, "--bare",
             "--null-sleep", "0.01", "--idle-exit", "2.0",
             "--metrics-dump", str(mix_dir / "metrics" / f"{rid}.prom")],
            env=env, stdout=log, stderr=log, cwd=str(REPO_ROOT))
    victims = [rid for rid, (host, _p) in placement.items() if host == "h1"]
    killed = False
    deadline = time.time() + 300.0
    try:
        while time.time() < deadline:
            done = len(list((root / "done").glob("*.json")))
            if not killed and done >= n_jobs // 3:
                # host h1 dies whole: every one of its replicas at once
                for rid in victims:
                    procs[rid].send_signal(_signal.SIGKILL)
                killed = True
                print(f"  pod: killed host h1 ({', '.join(victims)}) at "
                      f"{done}/{n_jobs} done")
            if done >= n_jobs:
                break
            if all(p.poll() is not None for p in procs.values()):
                raise SweepError(f"pod: all exited at {done}/{n_jobs} done")
            time.sleep(0.1)
        else:
            raise SweepError(
                f"pod: did not drain in time "
                f"({len(list((root / 'done').glob('*.json')))}/{n_jobs})")
        _check(killed, "pod: kill point never reached")
        drain_s = time.time() - t_publish
        for rid, p in procs.items():
            if rid not in victims:
                p.wait(timeout=30)
    finally:
        for p in procs.values():
            if p.poll() is None:
                p.kill()
        for log in logs.values():
            log.close()
    # ---- invariants from the drained spool -----------------------------
    done_msgs = list((root / "done").glob("*.json"))
    _check(len(done_msgs) == n_jobs, f"pod: {len(done_msgs)}/{n_jobs} done")
    for state in ("pending", "running", "failed", "quarantine"):
        left = list((root / state).glob("*.json"))
        _check(not left, f"pod: {len(left)} messages left in {state}/")
    waits = []
    for p in done_msgs:
        msg = json.loads(p.read_text())
        w = (float(msg.get("service", {}).get("claimed_at", 0.0))
             - float(msg["published_at"]))
        _check(w >= 0, f"pod: negative queue wait on {p.name}")
        waits.append(w)
    waits.sort()
    p50 = waits[len(waits) // 2]
    p99 = waits[min(len(waits) - 1, int(len(waits) * 0.99))]
    _check(p99 <= p99_bound_s,
           f"pod: p99 queue wait {p99:.2f}s > bound {p99_bound_s}s")
    # the survivors' watchdogs must have seen the host die
    evictions = 0.0
    saw_down = False
    for rid, (host, _p) in placement.items():
        if host != "h0":
            continue
        dump = mix_dir / "metrics" / f"{rid}.prom"
        _check(dump.exists(), f"pod: survivor {rid} left no metrics dump")
        text = dump.read_text()
        for line in text.splitlines():
            if line.startswith("sm_pod_host_evictions_total"):
                evictions += float(line.rsplit(" ", 1)[1])
            if line.startswith('sm_pod_process_up{process="1"} 0'):
                saw_down = True
    _check(evictions >= 1,
           "pod: no survivor recorded sm_pod_host_evictions_total")
    _check(saw_down,
           'pod: no survivor exported sm_pod_process_up{process="1"} == 0')
    print(f"  pod: {n_jobs} jobs over 2 hosts x 2 replicas, host h1 "
          f"SIGKILLed whole mid-sweep; drain {drain_s:.1f}s, queue-wait "
          f"p50 {p50:.2f}s p99 {p99:.2f}s, survivor host evictions "
          f"{evictions:.0f}")


def mix_elastic(base: Path, n_jobs: int = 420, p99_bound_s: float = 30.0) -> None:
    """Elastic-fleet wave (ISSUE 11 proof; ROADMAP item 2).

    A FleetController (in-process, lock-order-instrumented) supervises bare
    scheduler replicas (``replica_chaos.py --replica-serve --bare`` — null
    jobs; this mix measures the SCALING plane) over one partitioned spool.
    A pre-published traffic surge drives the fleet 1→4 autonomously; as the
    queue drains, cooldown-damped scale-downs *drain* replicas back — the
    mix observes the fleet at 2 before stopping.  Asserts: every job
    reaches ``done/`` exactly once (replica_chaos's exactly-once census),
    p99 queue-wait bounded, drained replicas leave zero orphaned
    leases/heartbeat/registry files, and the ``sm_fleet_*`` families are
    exposed."""
    import subprocess

    from scripts.replica_chaos import _spool_census
    from sm_distributed_tpu.engine.daemon import QUEUE_ANNOTATE, QueuePublisher
    from sm_distributed_tpu.service.fleet import FleetController
    from sm_distributed_tpu.service.metrics import MetricsRegistry
    from sm_distributed_tpu.utils.config import FleetConfig

    mix_dir = base / "elastic"
    queue_dir = mix_dir / "queue"
    root = queue_dir / QUEUE_ANNOTATE
    sm = {
        "backend": "numpy_ref",
        "work_dir": str(mix_dir / "work"),
        "storage": {"results_dir": str(mix_dir / "results")},
        "service": {
            "workers": 2, "poll_interval_s": 0.02, "job_timeout_s": 30.0,
            "max_attempts": 2, "backoff_base_s": 0.05, "backoff_max_s": 0.2,
            "backoff_jitter": 0.0, "heartbeat_interval_s": 0.2,
            "stale_after_s": 1.0, "drain_timeout_s": 20.0, "http_port": 0,
            "replicas": 4, "spool_shards": 16,
            # claim churn during membership changes bumps claim counters;
            # keep quarantine out of the way (same rationale as
            # replica_chaos's template — the mix is elasticity, not poison)
            "quarantine_after": 50,
            "replica_heartbeat_interval_s": 0.1,
            "replica_stale_after_s": 1.0, "takeover_interval_s": 0.2,
        },
    }
    mix_dir.mkdir(parents=True, exist_ok=True)
    sm_conf = mix_dir / "sm.json"
    sm_conf.write_text(json.dumps(sm, indent=2))
    pub = QueuePublisher(queue_dir)
    t_publish = time.time()
    for i in range(n_jobs):
        pub.publish({"ds_id": f"ej{i}", "msg_id": f"ej{i:05d}",
                     "input_path": "null://", "tenant": f"t{i % 97}"})
    script = str(REPO_ROOT / "scripts" / "replica_chaos.py")
    env = dict(__import__("os").environ)
    env.pop("SM_FAILPOINTS", None)
    logs = []

    def _spawn(rid: str) -> subprocess.Popen:
        log = open(mix_dir / f"{rid}.log", "w")
        logs.append(log)
        # long idle-exit: replicas retire by DRAIN, not by queue idleness
        return subprocess.Popen(
            [sys.executable, script, "--replica-serve", str(queue_dir),
             str(sm_conf), "--replica-id", rid, "--bare",
             "--null-sleep", "0.05", "--idle-exit", "120"],
            env=env, stdout=log, stderr=log, cwd=str(REPO_ROOT))

    registry = MetricsRegistry()
    from sm_distributed_tpu.utils.config import SMConfig as _SM

    fc = FleetController(
        queue_dir,
        FleetConfig(min_replicas=1, max_replicas=4, decide_interval_s=0.15,
                    cooldown_s=1.0, hysteresis_ticks=2, scale_up_burn=1.0,
                    scale_down_burn=0.5, queue_high_per_replica=20.0,
                    queue_low_per_replica=0.5, spawn_timeout_s=30.0,
                    drain_timeout_s=30.0),
        _SM.from_dict(json.loads(sm_conf.read_text())).service,
        spawn=_spawn, metrics=registry)
    max_alive = 0
    saw_two_after_peak = False
    try:
        fc.start()
        deadline = time.time() + 240.0
        while time.time() < deadline:
            alive = len(fc.alive_replicas())
            max_alive = max(max_alive, alive)
            done = len(list((root / "done").glob("*.json")))
            if done >= n_jobs and max_alive >= 4 and alive <= 2:
                saw_two_after_peak = True
                break
            time.sleep(0.05)
        _check(saw_two_after_peak,
               f"elastic: never observed surge→4→2 "
               f"(max_alive={max_alive}, "
               f"done={len(list((root / 'done').glob('*.json')))}/{n_jobs}, "
               f"status={fc.status()})")
    finally:
        fc.shutdown()
        for log in logs:
            log.close()
    st = fc.status()
    _check(st["scale_events"]["up"] >= 3,
           f"elastic: expected >=3 scale-ups, got {st['scale_events']}")
    _check(st["drains_total"] >= 2,
           f"elastic: expected >=2 completed drains, got {st}")
    _check(st["crashes_total"] == 0,
           f"elastic: controller counted crashes: {st}")
    # exactly-once: every job in done/ once, nothing anywhere else
    # (replica_chaos's census invariant)
    census = _spool_census(root)
    want = sorted(f"ej{i:05d}" for i in range(n_jobs))
    _check(census["done"] == want,
           f"elastic: done/ census mismatch "
           f"({len(census['done'])}/{n_jobs} done)")
    others = {s: v for s, v in census.items() if s != "done" and v}
    _check(not others, f"elastic: messages left outside done/: "
                       f"{ {s: len(v) for s, v in others.items()} }")
    # drained replicas must leave no orphaned leases / heartbeats /
    # registry debris — the zero-loss drain's cleanliness contract
    leases_left = sorted(p.name for p in (root / "leases").glob("*.json"))
    _check(not leases_left, f"elastic: leftover lease files: {leases_left}")
    beats_left = sorted(p.name for p in (root / "replicas").glob("*.json"))
    _check(not beats_left,
           f"elastic: drained replicas left heartbeat files: {beats_left}")
    drains_left = sorted(p.name for p in (root / "replicas").glob("*.drain"))
    _check(not drains_left,
           f"elastic: drain sentinels not cleaned: {drains_left}")
    hb_left = [str(p) for p in root.rglob("*.hb")]
    _check(not hb_left, f"elastic: claim heartbeat debris: {hb_left}")
    # queue-wait bound under the surge (scheduler stamps claimed_at)
    waits = []
    for p in (root / "done").glob("*.json"):
        msg = json.loads(p.read_text())
        w = (float(msg.get("service", {}).get("claimed_at", 0.0))
             - float(msg["published_at"]))
        _check(w >= 0, f"elastic: negative queue wait on {p.name}")
        waits.append(w)
    waits.sort()
    p50 = waits[len(waits) // 2]
    p99 = waits[min(len(waits) - 1, int(len(waits) * 0.99))]
    _check(p99 <= p99_bound_s,
           f"elastic: p99 queue wait {p99:.2f}s > bound {p99_bound_s}s")
    # the acceptance metrics are exposed by the controller's registry (on
    # the hosting replica's /metrics under serve --fleet)
    text = registry.expose()
    for fam in ("sm_fleet_replicas", "sm_fleet_scale_events_total",
                "sm_fleet_drains_total"):
        _check(fam in text, f"elastic: {fam} missing from metrics")
    drain_s = time.time() - t_publish
    print(f"  elastic: {n_jobs} jobs; fleet 1→{max_alive}→2 "
          f"({st['scale_events']['up']} ups, {st['drains_total']} drains, "
          f"0 crashes); drain {drain_s:.1f}s, queue-wait p50 {p50:.2f}s "
          f"p99 {p99:.2f}s")


def _http_raw(base: str, path: str):
    """(status, headers, raw bytes) — for read-path GETs (tiles are PNG)."""
    req = urllib.request.Request(base + path)
    try:
        with urllib.request.urlopen(req, timeout=30.0) as r:
            return r.status, dict(r.headers), r.read()
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), e.read()


def mix_read(base: Path, fx: dict, n_readers: int = 6, reads_each: int = 30,
             n_writes: int = 4, p99_bound_s: float = 1.0) -> None:
    """Read-plane mix (ISSUE 16): ~90/10 read/write over TWO in-process
    replicas sharing one spool + results tree.  Readers storm /datasets,
    annotation queries, cohorts, and tiles while a writer re-annotates one
    of the datasets (segment republish under read load); read admission is
    squeezed (``read.max_concurrent=2`` + a slowed cache-fill seam) so
    structured 429s demonstrably occur; one replica is taken out of
    rotation and shut down mid-storm.  Asserts: every read answered 200 or
    cleanly shed 429 (reason ``read_overload`` + Retry-After), p99 read
    latency bounded, cache hits visible on /metrics, every write terminal,
    and the final response exactly matches the on-disk segment — never a
    torn or stale one."""
    import random as _random

    overrides = {
        "storage": {"store_images": True},
        "service": {
            "replicas": 2, "spool_shards": 8,
            "replica_heartbeat_interval_s": 0.2,
            "replica_stale_after_s": 1.5, "takeover_interval_s": 0.3,
            "admission": {"max_queue_depth": 32},
            "read": {"max_concurrent": 2, "retry_after_s": 1.0},
        },
    }
    h1 = Harness(base, "read", sm_overrides=_merge(
        dict(overrides), {"service": {"replica_id": "r1"}}))
    h2 = Harness(base, "read", sm_overrides=_merge(
        dict(overrides), {"service": {"replica_id": "r2"}}))
    prev = failpoints.active_spec()
    try:
        # seed two datasets so cohorts span segments and tiles exist
        seeds = []
        for ds in ("read_a", "read_b"):
            status, _hd, body = h1.submit(_msg(fx, "fast", ds))
            _check(status == 202, f"read: seed submit shed ({status})")
            seeds.append(body["msg_id"])
        _wait_done(h1.root, seeds)
        seg_a = h1.dir / "results" / "read_a" / "segment.npz"
        _check(seg_a.exists(), "read: seed run published no segment")
        npz = h1.dir / "results" / "read_a" / "ion_images.npz"
        _check(npz.exists(), "read: seed run stored no ion images")
        from sm_distributed_tpu.engine.storage import SearchResultsStore

        _imgs, ions = SearchResultsStore.load_ion_images(npz)
        _check(ions, "read: empty ion-image npz")
        sf = ions[0][0]
        ion = urllib.parse.quote(f"{ions[0][0]}|{ions[0][1]}", safe="")
        paths = [
            "/datasets",
            "/datasets/read_a/annotations?order=msm&limit=2",
            "/datasets/read_a/annotations?fdr=0.5",
            "/datasets/read_b/annotations",
            f"/annotations?sf={sf}",
            f"/datasets/read_a/images/{ion}",
        ]
        # slow every cache fill so the 2-slot read admission demonstrably
        # sheds under 6 concurrent readers (sleeps only on MISSES — hits
        # stay fast, which is also what makes the p99 bound meaningful)
        failpoints.configure("read.cache_fill=sleep:0.05")
        targets = [h1.base, h2.base]
        results: list[tuple[int, dict, bytes, float]] = []
        res_lock = threading.Lock()
        writes: list[str] = []

        def _reader(seed: int) -> None:
            rng = _random.Random(seed)
            for _ in range(reads_each):
                t = rng.choice(list(targets))
                t0 = time.monotonic()
                status, headers, raw = _http_raw(t, rng.choice(paths))
                dt = time.monotonic() - t0
                with res_lock:
                    results.append((status, headers, raw, dt))
                time.sleep(0.02)      # pace the storm across the replica kill

        threads = [threading.Thread(target=_reader, args=(i,))
                   for i in range(n_readers)]
        for t in threads:
            t.start()
        # ~10% write plane: re-annotate read_a under the read storm — each
        # store atomically republishes the segment beneath the readers
        for i in range(n_writes):
            status, _hd, body = h1.submit(
                _msg(fx, "fast", "read_a", msg_id=f"rw{i}"))
            _check(status == 202, f"read: write {i} shed ({status})")
            writes.append(body["msg_id"])
            time.sleep(0.15)
            if i == n_writes // 2:
                # replica loss mid-storm: out of rotation first (what a
                # load balancer's health check does), a beat for issued
                # requests to land, then drain — every in-flight read
                # finishes, later reads route to r1
                targets[:] = [h1.base]
                time.sleep(0.3)
                h2.shutdown()
        for t in threads:
            t.join(timeout=120.0)
        failpoints.configure(None)
        _wait_done(h1.root, writes)
        # ---- asserts ----------------------------------------------------
        statuses = sorted({s for s, _h, _r, _d in results})
        _check(set(statuses) <= {200, 429},
               f"read: non-clean read outcomes {statuses}")
        sheds = [(h, r) for s, h, r, _d in results if s == 429]
        _check(sheds, "read: admission squeeze produced no 429s")
        for headers, raw in sheds:
            body = json.loads(raw)
            _check(body.get("reason") == "read_overload"
                   and "retry_after_s" in body,
                   f"read: unstructured shed body {body}")
            _check("Retry-After" in headers,
                   f"read: shed missing Retry-After: {headers}")
        lats = sorted(d for s, _h, _r, d in results if s == 200)
        _check(lats, "read: no successful reads")
        p99 = lats[min(len(lats) - 1, int(0.99 * len(lats)))]
        _check(p99 <= p99_bound_s,
               f"read: p99 read latency {p99:.3f}s > {p99_bound_s}s")
        text = h1.metrics_text()
        hits = sum(
            float(ln.rsplit(" ", 1)[1]) for ln in text.splitlines()
            if ln.startswith("sm_read_cache_hits_total{"))
        _check(hits > 0, "read: no cache hits on /metrics")
        _check("sm_read_requests_total" in text
               and "sm_read_latency_seconds" in text,
               "read: sm_read_* families missing from /metrics")
        # freshness + integrity: the final response must be exactly the
        # on-disk segment the last write published — never torn, never a
        # stale pre-republish cache entry
        from sm_distributed_tpu.engine.index import _load_file

        seg = _load_file(seg_a)
        status, _hd, raw = _http_raw(h1.base, "/datasets/read_a/annotations")
        _check(status == 200, f"read: final read failed ({status})")
        final = json.loads(raw)
        _check(final["published_at"] == seg.published_at
               and final["total"] == seg.n_rows,
               f"read: served view (job {final['job_id']} at "
               f"{final['published_at']}) != on-disk segment "
               f"(job {seg.job_id} at {seg.published_at})")
        n_reads = len(results)
        print(f"  read: {n_reads} reads ({len(sheds)} shed 429, "
              f"p99 {p99 * 1000:.0f}ms, {int(hits)} cache hits) + "
              f"{n_writes + 2} writes over 2 replicas, r2 retired "
              f"mid-storm; final view matches the on-disk segment")
    finally:
        failpoints.configure(prev)
        h1.shutdown()
        h2.shutdown()


def _wait_done(root: Path, msg_ids: list[str],
               timeout_s: float = 120.0, label: str = "read") -> None:
    """Spool-census wait (works across replicas, unlike one /jobs view)."""
    deadline = time.time() + timeout_s
    want = set(msg_ids)
    while time.time() < deadline:
        done = {p.stem for p in (root / "done").glob("*.json")}
        if want <= done:
            return
        bad = {p.stem for p in (root / "failed").glob("*.json")} & want
        if bad:
            raise SweepError(f"{label}: jobs dead-lettered: {sorted(bad)}")
        time.sleep(0.05)
    raise SweepError(f"{label}: jobs never drained: "
                     f"{sorted(want - done)}")


def mix_stream(base: Path, fx: dict, n_batch: int = 6,
               n_chunks: int = 3, n_readers: int = 2) -> None:
    """Mixed live/batch/read plane (ISSUE 19): two live acquisitions
    streamed chunk-by-chunk over HTTP into TWO in-process replicas sharing
    one spool, while a batch burst contends for the worker pool and
    readers poll the published golden dataset.  One replica is DRAINED
    mid-acquisition: its live stream hands off to the peer without
    burning an attempt (``stream.drain_handoff``) and resumes from the
    committed chunk log.  Asserts: batch traffic never starves the
    provisional re-ranks (coverage advances after every chunk group),
    every read answers 200 across the drain, both streams converge
    BIT-IDENTICALLY (``check_exact``) to the batch report of the same
    spectra, every job lands terminal in ``done/`` exactly once, and the
    sm_stream_* families + the stream-partial SLO are live."""
    import pandas as pd

    from sm_distributed_tpu.io.imzml import ImzMLReader
    from sm_distributed_tpu.service.leases import owned_shards, shard_of

    shards = 8
    overrides = {"service": {
        # 3 workers per replica: a live acquisition pins a worker for its
        # whole lifetime, the rest keep the batch burst moving
        "workers": 3,
        "replicas": 2, "spool_shards": shards,
        "replica_heartbeat_interval_s": 0.2,
        "replica_stale_after_s": 1.5, "takeover_interval_s": 0.3,
        "admission": {"max_queue_depth": 16, "max_tenant_inflight": 16},
        "stream": {"idle_timeout_s": 30.0, "poll_interval_s": 0.02,
                   "rescore_min_chunks": 1},
    }}
    h1 = Harness(base, "stream", sm_overrides=_merge(
        dict(overrides), {"service": {"replica_id": "r1"}}))
    h2 = Harness(base, "stream", sm_overrides=_merge(
        dict(overrides), {"service": {"replica_id": "r2"}}))
    try:
        with ImzMLReader(fx["fast"]["input_path"]) as rd:
            coords = rd.coordinates.tolist()
            spectra = [tuple(a.tolist() for a in rd.read_spectrum(i))
                       for i in range(rd.n_spectra)]
        n = len(coords)
        edges = [round(i * n / n_chunks) for i in range(n_chunks + 1)]
        # batch golden of the SAME spectra — the convergence target AND
        # the published dataset the read plane polls during acquisition
        status, _hd, body = h1.submit(_msg(fx, "fast", "stream_gold"))
        _check(status == 202, f"stream: golden submit shed ({status})")
        gold_id = body["msg_id"]
        _wait_done(h1.root, [gold_id], label="stream")
        batch_ids = [gold_id]
        # one acquisition shard-owned by EACH replica, so the drain below
        # demonstrably hands a live stream across the replica boundary
        r2_shards = owned_shards("r2", {"r1", "r2"}, shards)
        cands = [f"stream_{c}" for c in "abcdefghijklmnop"]
        ds_r1 = next(c for c in cands if shard_of(c, shards) not in r2_shards)
        ds_r2 = next(c for c in cands if shard_of(c, shards) in r2_shards)
        streams = (ds_r1, ds_r2)
        owner = {ds_r1: h1, ds_r2: h2}
        stream_ids = {}
        for ds in streams:
            msg = {"ds_id": ds, "msg_id": ds, "mode": "stream",
                   "formulas": fx["fast"]["formulas"],
                   "ds_config": fx["fast"]["ds_config"]}
            status, _hd, body = h1.submit(msg)
            _check(status == 202, f"stream: {ds} submit shed ({status})")
            stream_ids[ds] = body["msg_id"]
        # read plane: readers poll the golden's published annotations on
        # both replicas for the whole acquisition — every read must
        # answer 200, including across the drain
        paths = ["/datasets", "/datasets/stream_gold/annotations?limit=3",
                 "/datasets/stream_gold/annotations?order=msm"]
        targets = [h1.base, h2.base]
        stop_reads = threading.Event()
        reads: list[int] = []
        reads_lock = threading.Lock()

        def _reader(seed: int) -> None:
            i = seed
            while not stop_reads.is_set():
                ts = list(targets)
                try:
                    status, _hd, _b = _http(ts[i % len(ts)], "GET",
                                            paths[i % len(paths)])
                except OSError:
                    status = -1       # connection-level failure: fail loud
                with reads_lock:
                    reads.append(status)
                i += 1
                time.sleep(0.02)

        readers = [threading.Thread(target=_reader, args=(i,))
                   for i in range(n_readers)]
        for t in readers:
            t.start()
        drained = False
        for seq in range(n_chunks):
            lo, hi = edges[seq], edges[seq + 1]
            chunk = {"seq": seq, "coords": coords[lo:hi],
                     "mzs": [s[0] for s in spectra[lo:hi]],
                     "ints": [s[1] for s in spectra[lo:hi]]}
            for ds in streams:
                # every chunk lands on r1's ingest API — the shared work
                # dir means ingest is not pinned to the claim owner
                status, _hd, body = _http(
                    h1.base, "POST", f"/datasets/{ds}/pixels", chunk)
                _check(status == 200,
                       f"stream: {ds} chunk {seq} rejected ({status} {body})")
            # batch load lands BETWEEN chunk groups, contending for the
            # spare workers while both streams re-rank
            for _ in range(n_batch // n_chunks):
                i = len(batch_ids)
                status, _hd, body = h1.submit(
                    _msg(fx, "fast", f"smix{i}", tenant=f"t{i % 3}"))
                _check(status == 202, f"stream: batch {i} shed ({status})")
                batch_ids.append(body["msg_id"])
            # liveness under load: provisional coverage must reach this
            # chunk group on both streams before the next one is acquired
            # (polled on each stream's CLAIM OWNER — job records are
            # per-replica in-memory; the spool is what's shared)
            deadline = time.time() + 60.0
            lagging = dict(stream_ids)
            while lagging and time.time() < deadline:
                for ds, mid in list(lagging.items()):
                    _s, _hd, job = _http(owner[ds].base, "GET",
                                         f"/jobs/{mid}")
                    part = (job.get("partial") or {}).get("stream") or {}
                    if part.get("chunks", 0) >= seq + 1:
                        del lagging[ds]
                time.sleep(0.05)
            _check(not lagging,
                   f"stream: re-rank starved under batch load at chunk "
                   f"{seq}: {sorted(lagging)}")
            if not drained:
                # replica retired MID-ACQUISITION: r2 drains while its
                # live stream still has chunks to come — the stream job
                # must republish without burning an attempt and resume on
                # r1 from the committed chunk log.  Out of read rotation
                # first, a beat for issued reads to land, then drain.
                drained = True
                before = failpoints.recovery_counts().get(
                    "stream.drain_handoff", 0)
                targets[:] = [h1.base]
                time.sleep(0.3)
                h2.shutdown()
                got = failpoints.recovery_counts().get(
                    "stream.drain_handoff", 0)
                _check(got > before,
                       "stream: drain recorded no stream.drain_handoff")
                owner[ds_r2] = h1
        for ds in streams:
            status, _hd, body = _http(h1.base, "POST",
                                      f"/datasets/{ds}/finish", {})
            _check(status == 200, f"stream: {ds} finish failed "
                                  f"({status} {body})")
        _wait_done(h1.root, batch_ids + list(stream_ids.values()),
                   label="stream")
        stop_reads.set()
        for t in readers:
            t.join(timeout=30.0)
        _check(reads, "stream: read plane issued no reads")
        bad_reads = sorted({s for s in reads if s != 200})
        _check(not bad_reads,
               f"stream: read plane saw non-200 outcomes {bad_reads}")
        # bit-identity: each streamed report == the batch report of the
        # same spectra, down to the last bit (the ISSUE 19 tentpole) —
        # including the stream that crossed the replica boundary
        def _report(ds):
            out = []
            for name in ("annotations.parquet", "all_metrics.parquet"):
                df = pd.read_parquet(h1.dir / "results" / ds / name)
                out.append(df.sort_values(["sf", "adduct"])
                           .reset_index(drop=True))
            return out
        gold = _report("stream_gold")
        for ds in streams:
            got = _report(ds)
            for label, g, w in zip(("annotations", "all_metrics"),
                                   got, gold):
                try:
                    pd.testing.assert_frame_equal(g, w, check_exact=True)
                except AssertionError as e:
                    raise SweepError(
                        f"stream: {ds} {label} not bit-identical to "
                        f"batch: {str(e).splitlines()[-1]}") from e
        text = h1.metrics_text()
        chunks_total = sum(
            float(ln.rsplit(" ", 1)[1]) for ln in text.splitlines()
            if ln.startswith("sm_stream_chunks_total"))
        _check(chunks_total == len(streams) * n_chunks,
               f"stream: sm_stream_chunks_total {chunks_total} != "
               f"{len(streams) * n_chunks}")
        _check("sm_stream_reranks_total" in text
               and "sm_stream_pixels_total" in text,
               "stream: sm_stream_* families missing from /metrics")
        _s, _hd, slo = _http(h1.base, "GET", "/slo")
        _check("stream_partial" in slo.get("slos", {}),
               "stream: stream_partial SLO missing from /slo")
        h1.assert_clean("stream")
        print(f"  stream: {len(streams)} live acquisitions x {n_chunks} "
              f"chunks + {len(batch_ids)} batch jobs + {len(reads)} reads "
              f"over 2 replicas, r2 drained mid-acquisition; provisional "
              f"coverage kept pace, reports bit-identical to batch")
    finally:
        h1.shutdown()
        h2.shutdown()


# ------------------------------------------------------------------- driver
def run_sweep(work: Path, smoke: bool = False, elastic_only: bool = False,
              read_only: bool = False, pod_only: bool = False,
              stream_only: bool = False) -> int:
    # lock-order detection (ISSUE 9): instrument every lock the service
    # stack creates below and fail the sweep on an acquisition-order cycle
    # — the load mixes drive scheduler workers, dispatcher, watchdog,
    # admission, device pool, telemetry, AND the fleet controller
    # concurrently, which is exactly the thread population a lurking
    # inversion needs
    from sm_distributed_tpu.analysis import lockorder

    lockorder.enable()
    work.mkdir(parents=True, exist_ok=True)
    t0 = time.time()
    try:
        if elastic_only:
            print("load sweep (elastic-fleet stage)")
            mix_elastic(work)
        elif pod_only:
            print("load sweep (pod host-loss stage)")
            mix_pod(work)
        elif read_only:
            print("load sweep (read-plane stage)")
            mix_read(work, build_fixtures(work))
        elif stream_only:
            print("load sweep (live-acquisition stage)")
            mix_stream(work, build_fixtures(work))
        else:
            fx = build_fixtures(work)
            h = Harness(work, "main")
            try:
                print(f"load sweep ({'smoke' if smoke else 'full'}) "
                      f"at {h.base}")
                mix_burst(h, fx, n_submit=(12 if smoke else 24))
                if not smoke:
                    mix_sustained(h, fx, n_submit=10, gap_s=0.1)
                    mix_cancel(h, fx)
                mix_deadline(h, fx)
                mix_poison(h, fx)
            finally:
                h.shutdown()
            if not smoke:
                mix_breaker(work, fx)
                mix_device_fault(work, fx)
                mix_disk(work, fx)
                mix_replicas(work)
                mix_pod(work)
                mix_read(work, fx)
                mix_stream(work, fx)
                mix_elastic(work)
        rep = lockorder.assert_no_cycles("load sweep")
        print(f"lock-order: no cycles ({rep['locks_instrumented']} locks, "
              f"{rep['edges']} order edges observed)")
    finally:
        lockorder.disable()
    print(f"load sweep OK ({time.time() - t0:.1f}s)")
    return 0


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--smoke", action="store_true",
                    help="CI subset: burst + deadline + poison")
    ap.add_argument("--elastic", action="store_true",
                    help="run only the elastic-fleet mix (1→4→2 wave with "
                         "exactly-once + clean-drain asserts)")
    ap.add_argument("--read", action="store_true",
                    help="run only the read-plane mix (~90/10 read/write "
                         "over two replicas, structured 429 sheds, p99 "
                         "bound, cache-hit ratio, replica kill mid-storm)")
    ap.add_argument("--pod", action="store_true",
                    help="run only the pod host-loss mix (2 hosts x 2 "
                         "replicas, host h1 SIGKILLed whole mid-sweep, "
                         "exactly-once + p99 + watchdog-eviction asserts)")
    ap.add_argument("--stream", action="store_true",
                    help="run only the live-acquisition mix (two streams "
                         "chunked over HTTP under a batch burst, provisional "
                         "re-rank liveness, check_exact batch convergence)")
    ap.add_argument("--work", default=None)
    ap.add_argument("--keep", action="store_true")
    args = ap.parse_args(argv)
    import shutil
    import tempfile

    work = Path(args.work) if args.work else Path(
        tempfile.mkdtemp(prefix="sm_load_"))
    try:
        return run_sweep(work, smoke=args.smoke, elastic_only=args.elastic,
                         read_only=args.read, pod_only=args.pod,
                         stream_only=args.stream)
    except SweepError as exc:
        print(f"load sweep FAILED: {exc}", file=sys.stderr)
        return 1
    finally:
        if not args.keep and args.work is None:
            shutil.rmtree(work, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
