#!/usr/bin/env python
"""Resource-exhaustion smoke gate (ISSUE 10; wired into check_tier1.sh).

Runs the spheroid fixture through the REAL in-process annotation service
under a tiny 64 MB disk budget and proves the resource-governor layer end
to end:

1. a job under headroom completes WITH a trace file (baseline + golden);
2. filler pushing the budget past the trace floor flips the service to
   degrade level 1 — visible on ``/metrics``
   (``sm_disk_degrade_level``) and ``/debug/resources`` — and the next
   job completes GOLDEN with its trace writes dropped;
3. more filler reaches the cache floor (level 2), the read-cache floor
   (level 3: read-path cache fills dropped, reads still answered) and
   then the submit floor (level 4): ``POST /submit`` sheds with a
   structured **507** + ``Retry-After``;
4. freeing the space recovers the service without a restart (level 0,
   submits accepted, job completes);
5. the bounded-retention GC keeps the spool under its caps: drained
   ``done/`` messages are reaped within the retention age and
   ``sm_gc_removed_files_total`` moves;
6. the preflight fast path costs < 25 µs/call — no measurable headline
   -rate tax (perf_sentinel guards the bench numbers themselves).

Exit 0 = gate passes.
"""

from __future__ import annotations

import sys
import tempfile
import time
import urllib.request
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO_ROOT))

from scripts.load_sweep import Harness, _msg, build_fixtures  # noqa: E402

MB = 1 << 20
BUDGET = 64 * MB


def fail(msg: str) -> int:
    print(f"resource_smoke: FAIL — {msg}", file=sys.stderr)
    return 1


def _get_json(base: str, path: str) -> dict:
    with urllib.request.urlopen(base + path, timeout=10.0) as r:
        import json

        return json.loads(r.read())


def _wait_level(h: Harness, want: int, timeout_s: float = 10.0) -> dict:
    deadline = time.time() + timeout_s
    body = {}
    while time.time() < deadline:
        body = _get_json(h.base, "/debug/resources")
        if body.get("level") == want:
            return body
        time.sleep(0.05)
    raise AssertionError(
        f"governor never reached level {want}: {body}")


def run(work: Path) -> int:
    fx = build_fixtures(work)
    h = Harness(work, "resource_smoke", sm_overrides={
        "resources": {
            "disk_budget_bytes": BUDGET,
            "trace_floor_bytes": 48 * MB,
            "cache_floor_bytes": 32 * MB,
            "read_cache_floor_bytes": 24 * MB,
            "submit_floor_bytes": 16 * MB,
            "gc_interval_s": 0.2,
            "done_retention_age_s": 0.5,
            "failed_retention_age_s": 0.5,
        },
    })
    filler = Path(h.sm_config.work_dir) / "filler.bin"
    try:
        import pandas as pd

        # ---- 1. baseline job under headroom: traced + golden ------------
        status, _hd, body = h.submit(_msg(fx, "fast", "base1"))
        if status != 202:
            return fail(f"baseline submit returned {status}: {body}")
        rows = h.wait_terminal([body["msg_id"]])
        if rows[body["msg_id"]]["state"] != "done":
            return fail(f"baseline job {rows[body['msg_id']]}")
        from sm_distributed_tpu.utils import tracing

        base_trace = tracing.trace_path(h.service.trace_dir,
                                        body["trace_id"])
        if not base_trace.exists():
            return fail("baseline job has no trace file")
        golden = pd.read_parquet(
            Path(h.sm_config.storage.results_dir) / "base1"
            / "annotations.parquet")
        snap = _get_json(h.base, "/debug/resources")
        if not snap["enabled"] or snap["level"] != 0:
            return fail(f"governor not at level 0 under headroom: {snap}")

        # ---- 2. trace-drop degrade (level 1), job still golden ----------
        filler.write_bytes(b"\0" * (20 * MB))
        _wait_level(h, 1)
        status, _hd, body = h.submit(_msg(fx, "fast", "degraded1"))
        if status != 202:
            return fail(f"level-1 submit shed unexpectedly: {status} {body}")
        rows = h.wait_terminal([body["msg_id"]])
        if rows[body["msg_id"]]["state"] != "done":
            return fail(f"level-1 job failed: {rows[body['msg_id']]}")
        if tracing.trace_path(h.service.trace_dir,
                              body["trace_id"]).exists():
            return fail("level-1 job wrote a trace file — the drop order "
                        "did not engage")
        degraded_ann = pd.read_parquet(
            Path(h.sm_config.storage.results_dir) / "degraded1"
            / "annotations.parquet")
        pd.testing.assert_frame_equal(degraded_ann, golden)
        text = h.metrics_text()
        if "sm_disk_degrade_level 1" not in text:
            return fail("sm_disk_degrade_level 1 missing from /metrics")
        if 'sm_disk_degraded_writes_total{kind="trace"}' not in text:
            return fail("trace-drop counter missing from /metrics")

        # ---- 3. cache floor, read-cache floor, then 507 submit shed -----
        filler.write_bytes(b"\0" * (36 * MB))
        snap = _wait_level(h, 2)
        filler.write_bytes(b"\0" * (44 * MB))
        _wait_level(h, 3)               # read-path cache fills now dropped
        filler.write_bytes(b"\0" * (52 * MB))
        _wait_level(h, 4)
        status, headers, body = h.submit(_msg(fx, "fast", "shedme"))
        if status != 507:
            return fail(f"expected 507 at the submit floor, got {status} "
                        f"{body}")
        if body.get("reason") != "disk_exhausted" or \
                "Retry-After" not in headers:
            return fail(f"unstructured 507: {headers} {body}")

        # ---- 4. free space -> full recovery without a restart -----------
        filler.unlink()
        _wait_level(h, 0)
        status, _hd, body = h.submit(_msg(fx, "fast", "recovered1"))
        if status != 202:
            return fail(f"post-recovery submit shed: {status} {body}")
        rows = h.wait_terminal([body["msg_id"]])
        if rows[body["msg_id"]]["state"] != "done":
            return fail(f"post-recovery job: {rows[body['msg_id']]}")

        # ---- 5. retention GC drains done/ under its cap -----------------
        deadline = time.time() + 10.0
        while time.time() < deadline:
            if not list((h.root / "done").glob("*.json")):
                break
            time.sleep(0.1)
        else:
            return fail("GC never reaped drained done/ messages")
        text = h.metrics_text()
        if 'sm_gc_removed_files_total{dir="done"}' not in text:
            return fail("sm_gc_removed_files_total missing from /metrics")
        snap = _get_json(h.base, "/debug/resources")
        if snap["gc"]["runs"] < 1 or \
                snap["gc"]["classes"].get("done", {}).get("files", 0) < 3:
            return fail(f"GC evidence missing from /debug/resources: "
                        f"{snap['gc']}")

        # ---- 6. preflight cost stays negligible -------------------------
        governor = h.service.resources
        n = 20_000
        t0 = time.perf_counter()
        for _ in range(n):
            governor.preflight("smoke_bench", 0)
        per_call = (time.perf_counter() - t0) / n
        if per_call > 25e-6:
            return fail(f"preflight costs {per_call * 1e6:.1f} µs/call "
                        f"(> 25 µs budget)")
    finally:
        h.shutdown()
    print(f"resource_smoke: OK — trace-drop degrade at level 1 (golden "
          f"results), 507 shed at the submit floor, recovery after "
          f"free-up, GC under cap, preflight {per_call * 1e6:.2f} µs/call")
    return 0


def main() -> int:
    import shutil

    work = Path(tempfile.mkdtemp(prefix="sm_resource_smoke_"))
    try:
        return run(work)
    except AssertionError as exc:
        return fail(str(exc))
    finally:
        shutil.rmtree(work, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
