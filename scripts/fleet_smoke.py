#!/usr/bin/env python
"""Fleet observability smoke gate (ISSUE 20; wired into check_tier1.sh).

Three phases, all through real service stacks:

1. **Fleet aggregation under a mid-scrape death.**  Three replica
   PROCESSES (scripts/replica_chaos.py --replica-serve) over one
   partitioned spool serve a batch of real jobs.  One replica is
   SIGKILLed while still alive in the registry; a survivor's
   ``/fleet/slo`` / ``/fleet/metrics`` / ``/fleet/status`` must all
   answer **200 with partial-view evidence** naming the dead peer —
   never a 500.  After the survivors converge the remaining jobs, the
   fleet SLO report must be **bit-equal** to an independent
   recomputation from the union of the survivors' raw ``/metrics``
   buckets (this script's own parser + the documented attainment
   arithmetic — not the fleetview code under test).
2. **On-demand device profiling.**  An in-process service on the
   ``jax_tpu`` backend with the fused Pallas scoring kernel forced on
   (interpret mode off-TPU) runs real jobs; ``GET /debug/profile``
   during one must attribute device time to a *named* fused scoring
   kernel, inject correlated ``device_kernel`` spans into the running
   job's trace, and ``trace_report.py --by-replica`` must attribute
   that device time to the serving replica.
3. **Measured-roofline pins.**  The newest committed ``PROFILE_r*.json``
   artifact (the CPU-recorded profiled-roofline history — BENCH_r*.json
   stays TPU/driver-recorded) must carry non-null
   ``measured_roofline_frac`` / ``kernel_time_frac``, and a degraded
   replay must trip the perf-sentinel band on BOTH fields (regress-down
   direction).

Exit 0 = gate passes.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import tempfile
import time
import urllib.request
from pathlib import Path

os.environ.setdefault("JAX_PLATFORMS", "cpu")

REPO_ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO_ROOT))

from scripts.chaos_sweep import FIXTURE  # noqa: E402
from sm_distributed_tpu.engine.daemon import (  # noqa: E402
    QUEUE_ANNOTATE,
    QueuePublisher,
)
from sm_distributed_tpu.io.fixtures import generate_synthetic_dataset  # noqa: E402

REPLICAS = ("r0", "r1", "r2")
VICTIM = "r0"
N_JOBS = 6
SHARDS = 8

SM_TEMPLATE = {
    "backend": "numpy_ref",
    "fdr": {"decoy_sample_size": 8, "seed": 42},
    "parallel": {"formula_batch": 16, "checkpoint_every": 2,
                 "resident_datasets": 2, "order_ions": "table"},
    "storage": {"store_images": False},
    "service": {"workers": 2, "poll_interval_s": 0.05, "job_timeout_s": 60.0,
                "max_attempts": 3, "backoff_base_s": 0.05,
                "backoff_max_s": 0.2, "backoff_jitter": 0.05,
                "heartbeat_interval_s": 0.2, "stale_after_s": 2.0,
                "drain_timeout_s": 10.0, "http_port": 0,
                "quarantine_after": 20,
                "replicas": len(REPLICAS), "spool_shards": SHARDS,
                # the kill→evidence window: the victim must still look
                # ALIVE in the registry while a survivor's fleet scrape
                # hits its closed port
                "replica_heartbeat_interval_s": 0.5,
                "replica_stale_after_s": 6.0,
                "takeover_interval_s": 0.5,
                # every /fleet/* request below must be a FRESH round
                "fleetview": {"scrape_timeout_s": 2.0, "cache_ttl_s": 0.0}},
}


def fail(msg: str) -> int:
    print(f"fleet_smoke: FAIL — {msg}", file=sys.stderr)
    return 1


def _http_json(base: str, path: str, timeout: float = 30.0):
    with urllib.request.urlopen(f"{base}{path}", timeout=timeout) as r:
        return r.status, json.loads(r.read())


def _http_text(base: str, path: str, timeout: float = 30.0):
    with urllib.request.urlopen(f"{base}{path}", timeout=timeout) as r:
        return r.status, r.read().decode("utf-8", "replace")


# --------------------------------------------------- independent SLO math
def _parse_hist(text: str, family: str):
    """One UNLABELLED histogram family out of raw exposition text:
    ``(cumulative {le: count}, sum, count)``.  Deliberately a separate
    parser from service/fleetview.py — the recomputation below must not
    lean on the code under test."""
    cum: dict[float, int] = {}
    sum_, count, seen = 0.0, 0, False
    for line in text.splitlines():
        if line.startswith("#"):
            continue
        if line.startswith(family + "_bucket{"):
            le = line.split('le="', 1)[1].split('"', 1)[0]
            seen = True
            if le != "+Inf":
                cum[float(le)] = int(float(line.rsplit(" ", 1)[1]))
        elif line.startswith(family + "_sum"):
            sum_ = float(line.rsplit(" ", 1)[1])
            seen = True
        elif line.startswith(family + "_count"):
            count = int(float(line.rsplit(" ", 1)[1]))
            seen = True
    return (cum, sum_, count) if seen else None


def _recompute_sli(texts: list[str], family: str, objective_s: float,
                   target: float) -> dict:
    """Fleet attainment for one SLI from the union of raw per-replica
    buckets, mirroring the documented arithmetic: summed cumulative
    bucket counts (integers — exact), the linear-interpolation
    ``fraction_below``, the family-level ``(f*n)/n`` aggregation, and
    the report's rounding."""
    union: dict[float, int] = {}
    count = 0
    for t in texts:
        parsed = _parse_hist(t, family)
        if parsed is None:
            continue
        cum, _s, c = parsed
        count += c
        for le, v in cum.items():
            union[le] = union.get(le, 0) + v
    entry = {"objective_s": objective_s, "target": target, "count": count}
    if not count:
        entry.update(attainment=None, violations=0, error_budget_burn=None)
        return entry
    les = sorted(union)
    cum_counts = [union[le] for le in les]
    counts = [cum_counts[0]] + [cum_counts[i] - cum_counts[i - 1]
                                for i in range(1, len(cum_counts))]
    below, lo = 0.0, 0.0
    for le, n in zip(les, counts):
        if objective_s >= le:
            below += n
        elif objective_s > lo:
            below += n * (objective_s - lo) / (le - lo)
            break
        else:
            break
        lo = le
    f = min(1.0, below / count)
    attained = (f * count) / count      # the family-level aggregation step
    entry.update(
        attainment=round(attained, 6),
        violations=round((1.0 - attained) * count),
        error_budget_burn=round((1.0 - attained) / (1.0 - target), 4))
    return entry


# ------------------------------------------------------- phase 1: fleet
def _start_replica(base: Path, sm_conf: Path, rid: str):
    log = base / "logs" / f"{rid}.log"
    log.parent.mkdir(parents=True, exist_ok=True)
    cmd = [sys.executable, str(REPO_ROOT / "scripts" / "replica_chaos.py"),
           "--replica-serve", str(base / "queue"), str(sm_conf),
           "--replica-id", rid, "--idle-exit", "90.0",
           "--metrics-dump", str(base / "metrics" / f"{rid}.prom"),
           "--ports-dir", str(base / "ports")]
    env = dict(os.environ)
    env.pop("SM_FAILPOINTS", None)
    fh = open(log, "w")
    return subprocess.Popen(cmd, env=env, stdout=fh, stderr=fh,
                            cwd=str(REPO_ROOT)), log


def _port_of(base: Path, rid: str, deadline: float) -> int:
    pf = base / "ports" / f"{rid}.port"
    while time.time() < deadline:
        if pf.exists():
            try:
                return int(pf.read_text())
            except ValueError:
                pass
        time.sleep(0.05)
    raise TimeoutError(f"{rid} never wrote its port file")


def phase_fleet(work: Path) -> int:
    base = work / "fleet"
    base.mkdir(parents=True)
    sm = json.loads(json.dumps(SM_TEMPLATE))
    sm["work_dir"] = str(base / "work")
    sm["storage"] = dict(sm["storage"], results_dir=str(base / "results"))
    sm_conf = base / "sm.json"
    sm_conf.write_text(json.dumps(sm, indent=2))

    imzml_path, truth = generate_synthetic_dataset(base / "fixture", **FIXTURE)
    msgs = [{
        "ds_id": f"f{i}", "ds_name": f"f{i}", "msg_id": f"f{i}",
        "input_path": str(imzml_path), "formulas": truth.formulas,
        "tenant": f"t{i % 2}",
        "ds_config": {"isotope_generation": {"adducts": ["+H"]},
                      "image_generation": {"ppm": 3.0}},
    } for i in range(N_JOBS)]
    pub = QueuePublisher(base / "queue")
    for m in msgs:
        pub.publish(m)

    procs: dict[str, subprocess.Popen] = {}
    try:
        for rid in REPLICAS:
            procs[rid], _ = _start_replica(base, sm_conf, rid)
        deadline = time.time() + 60.0
        ports = {rid: _port_of(base, rid, deadline) for rid in REPLICAS}
        surv = f"http://127.0.0.1:{ports['r1']}"

        # all three registered, seen through a survivor
        while time.time() < deadline:
            try:
                _s, peers = _http_json(surv, "/peers", timeout=5.0)
                if {p.get("replica_id") for p in peers.get("replicas", [])} \
                        >= set(REPLICAS):
                    break
            except OSError:
                pass
            time.sleep(0.2)
        else:
            return fail("survivor /peers never listed all replicas")

        # wholeness before the kill: a fresh full fleet round merges 3/3
        _s, slo0 = _http_json(surv, "/fleet/slo", timeout=30.0)
        if slo0["fleet"]["replicas_merged"] != len(REPLICAS):
            return fail(f"pre-kill fleet round merged "
                        f"{slo0['fleet']['replicas_merged']}/3: "
                        f"{slo0['fleet']['scrape_errors']}")

        # let some jobs finish so the SLI histograms are non-empty
        done_dir = base / "queue" / QUEUE_ANNOTATE / "done"
        deadline = time.time() + 120.0
        while time.time() < deadline:
            if len(list(done_dir.glob("*.json"))) >= 2:
                break
            if any(p.poll() is not None for p in procs.values()):
                return fail("a replica exited before the kill point")
            time.sleep(0.2)
        else:
            return fail("fewer than 2 jobs finished in 120s")

        # ---- the mid-scrape death: SIGKILL between heartbeats, then
        # immediately scrape through a survivor while the victim is still
        # ALIVE in the registry (stale_after 6 s) with a closed port
        procs[VICTIM].kill()
        procs[VICTIM].wait(timeout=10)
        code, slo_p = _http_json(surv, "/fleet/slo", timeout=30.0)
        if code != 200:
            return fail(f"/fleet/slo during partial window returned {code}")
        fl = slo_p["fleet"]
        if not fl["partial"] or VICTIM not in fl["scrape_errors"]:
            return fail(f"no partial-view evidence for the killed replica: "
                        f"{fl}")
        code, mtext = _http_text(surv, "/fleet/metrics", timeout=30.0)
        if code != 200:
            return fail(f"/fleet/metrics during partial window: {code}")
        if f"# fleetview: scrape of {VICTIM} failed:" not in mtext:
            return fail("merged exposition carries no scrape-failure "
                        "evidence comment")
        if "partial=true" not in mtext.splitlines()[0]:
            return fail(f"merged exposition header not partial: "
                        f"{mtext.splitlines()[0]!r}")
        code, st = _http_json(surv, "/fleet/status", timeout=30.0)
        if code != 200 or not st["partial"]:
            return fail(f"/fleet/status during partial window: code={code} "
                        f"partial={st.get('partial')}")
        if not st["replicas"][VICTIM]["alive"]:
            return fail("victim already stale at scrape time — the "
                        "mid-scrape window was missed (vacuous evidence)")
        print(f"fleet_smoke: partial view OK — {VICTIM} evidence: "
              f"{fl['scrape_errors'][VICTIM].splitlines()[0]}")

        # ---- survivors adopt the victim's shards and converge the rest
        deadline = time.time() + 180.0
        while time.time() < deadline:
            if len(list(done_dir.glob("*.json"))) >= N_JOBS:
                break
            alive = [r for r in REPLICAS if r != VICTIM
                     and procs[r].poll() is None]
            if not alive:
                return fail("both survivors exited before convergence")
            time.sleep(0.2)
        else:
            return fail(f"jobs did not converge after the kill "
                        f"({len(list(done_dir.glob('*.json')))}/{N_JOBS})")

        # ---- quiesce: wait out the victim's staleness window (a stale
        # peer is LISTED, not scraped — no longer an error), then check
        # bit-equality: fleet /fleet/slo vs this script's own
        # recomputation from the survivors' raw buckets
        deadline = time.time() + 30.0
        while time.time() < deadline:
            _s, st2 = _http_json(surv, "/fleet/status", timeout=30.0)
            if not st2["replicas"][VICTIM]["alive"]:
                break
            time.sleep(0.5)
        else:
            return fail("victim never went stale in the registry")
        time.sleep(2.0)
        _s, raw1 = _http_text(surv, "/metrics", timeout=30.0)
        _s, raw2 = _http_text(f"http://127.0.0.1:{ports['r2']}", "/metrics",
                              timeout=30.0)
        code, slo = _http_json(surv, "/fleet/slo", timeout=30.0)
        if code != 200:
            return fail(f"post-convergence /fleet/slo returned {code}")
        fl = slo["fleet"]
        if fl["partial"]:
            return fail(f"post-convergence round still partial (victim "
                        f"should be stale, not an error): {fl}")
        if fl["replicas_merged"] != 2:
            return fail(f"expected 2 merged survivors, got "
                        f"{fl['replicas_merged']}")
        families = {
            "queue_wait": "sm_slo_queue_wait_seconds",
            "first_annotation": "sm_slo_first_annotation_seconds",
            "e2e": "sm_slo_e2e_seconds",
            "read": "sm_slo_read_seconds",
            "stream_partial": "sm_slo_stream_partial_seconds",
        }
        for sli, fam in families.items():
            got = slo["slos"][sli]
            want = _recompute_sli([raw1, raw2], fam, got["objective_s"],
                                  got["target"])
            if got != want:
                return fail(f"fleet SLO for {sli} is not bit-equal to the "
                            f"union of survivors' buckets:\n  fleet: {got}"
                            f"\n  union: {want}")
        if not slo["slos"]["e2e"]["count"]:
            return fail("e2e SLI empty after convergence — the "
                        "bit-equality check was vacuous")
        # evidence metric landed on the scraping survivor
        if f'sm_fleetview_scrape_errors_total{{replica="{VICTIM}"}}' \
                not in raw1:
            return fail("survivor carries no sm_fleetview_scrape_errors_"
                        "total evidence for the victim")
        print(f"fleet_smoke: fleet SLO bit-equal over "
              f"{slo['slos']['e2e']['count']} e2e + "
              f"{slo['slos']['queue_wait']['count']} queue-wait "
              f"observations from 2 survivors")
        return 0
    finally:
        for p in procs.values():
            if p.poll() is None:
                p.terminate()
        for p in procs.values():
            try:
                p.wait(timeout=20)
            except subprocess.TimeoutExpired:
                p.kill()


# ----------------------------------------------------- phase 2: profiling
def phase_profile(work: Path) -> int:
    from scripts.load_sweep import Harness

    base = work / "profile"
    base.mkdir(parents=True)
    fx_path, truth = generate_synthetic_dataset(
        base / "fx", nrows=24, ncols=24, formulas=None,
        present_fraction=0.5, noise_peaks=20, seed=13)
    h = Harness(base, "svc", sm_overrides={
        "backend": "jax_tpu",
        # force the fused Pallas scoring kernel (interpret mode off-TPU):
        # the capture must attribute device time to it BY NAME
        "parallel": {"formula_batch": 4, "checkpoint_every": 1,
                     "fused_metrics": "on",
                     "compile_cache_dir": str(base / "xla_cache")},
    })
    try:
        def submit(i: int) -> str:
            msg = {"ds_id": f"p{i}", "msg_id": f"p{i}",
                   "input_path": str(fx_path),
                   "formulas": truth.formulas[:4],
                   "ds_config": {"isotope_generation": {"adducts": ["+H"]}}}
            status, _hd, body = h.submit(msg)
            if status != 202:
                raise RuntimeError(f"submit {i} returned {status}: {body}")
            return body["msg_id"]

        # warm job: pays the cold compile so later captures see scoring,
        # not compilation stalls
        warm = submit(0)
        h.wait_terminal([warm], timeout_s=300.0)

        capture = None
        for i in range(1, 5):
            mid = submit(i)
            deadline = time.time() + 120.0
            while time.time() < deadline:
                row = h.jobs().get(mid) or {}
                if row.get("state") == "running":
                    break
                if row.get("state") in ("done", "failed"):
                    break
                time.sleep(0.02)
            while (h.jobs().get(mid) or {}).get("state") == "running":
                code, body = _http_json(h.base, "/debug/profile?seconds=1.0",
                                        timeout=60.0)
                if code != 200:
                    return fail(f"/debug/profile returned {code}: {body}")
                kernels = (body.get("attribution") or {}).get("kernels", [])
                fused = [k for k in kernels if "fused" in k["module"]]
                if fused and body.get("injected_spans", 0) > 0 \
                        and mid in body.get("jobs_running", []):
                    capture = (mid, body, fused)
                    break
            if capture:
                break
            h.wait_terminal([mid], timeout_s=300.0)
        if not capture:
            return fail("no profile capture attributed a named fused "
                        "scoring kernel during a running job (4 attempts)")
        mid, body, fused = capture
        by_class = body["attribution"]["by_class_frac"]
        print(f"fleet_smoke: profile capture OK — {fused[0]['module']} "
              f"({fused[0]['device_s']:.4f}s device), classes={by_class}, "
              f"{body['injected_spans']} spans injected into {mid}")

        h.wait_terminal([mid], timeout_s=300.0)
        _s, _hd2, tr = None, None, None
        with urllib.request.urlopen(
                f"{h.base}/jobs/{mid}/trace?raw=1", timeout=30.0) as r:
            tr = json.loads(r.read())
        records = tr["records"]
        dev = [rec for rec in records if rec.get("kind") == "span"
               and rec.get("name") == "device_kernel"]
        if not dev:
            return fail(f"job {mid} trace gained no device_kernel spans")
        fused_spans = [rec for rec in dev
                       if "fused" in (rec.get("attrs") or {}).get("module",
                                                                  "")]
        if not fused_spans:
            return fail("device_kernel spans carry no fused kernel")
        rid = fused_spans[0].get("replica")
        if not rid:
            return fail("injected device_kernel spans carry no replica "
                        "stamp — --by-replica attribution impossible")

        # the --by-replica satellite, end to end over the same trace
        tf = base / "trace.jsonl"
        with open(tf, "w") as f:
            for rec in records:
                f.write(json.dumps(rec) + "\n")
        out = subprocess.run(
            [sys.executable, str(REPO_ROOT / "scripts" / "trace_report.py"),
             str(tf), "--by-replica", "--json"],
            capture_output=True, text=True, cwd=str(REPO_ROOT))
        if out.returncode != 0:
            return fail(f"trace_report --by-replica failed: {out.stderr}")
        br = json.loads(out.stdout)["by_replica"]
        if br.get(rid, {}).get("device_kernel_s", 0.0) <= 0.0:
            return fail(f"--by-replica attributes no device time to {rid}: "
                        f"{br}")
        if "sm_profile_captures_total" not in h.metrics_text():
            return fail("sm_profile_captures_total missing from /metrics")
        print(f"fleet_smoke: trace attribution OK — "
              f"{len(dev)} device_kernel spans on {mid}, "
              f"{br[rid]['device_kernel_s']:.4f}s device attributed to "
              f"{rid}")
        return 0
    finally:
        h.service.shutdown()


# ------------------------------------------------ phase 3: roofline pins
def phase_roofline_pins() -> int:
    from scripts import perf_sentinel as ps

    # PROFILE_r*.json is the CPU-recorded profiled-roofline history (its
    # own namespace, like ANALYSIS_r*/NUMERICS_r*): the BENCH_r*.json
    # entries are driver-recorded on TPU, and a CPU smoke artifact mixed
    # into that history would wreck the throughput medians the perf
    # sentinel self-check replays.  TPU-recorded BENCH entries gain the
    # same keys from bench.py and band through the normal --fresh path.
    hist = sorted(REPO_ROOT.glob("PROFILE_r*.json"))
    if not hist:
        return fail("no committed PROFILE_r*.json history")
    newest = ps.load_artifact(hist[-1])
    for key in ("measured_roofline_frac", "kernel_time_frac"):
        v = newest.get(key)
        if not isinstance(v, (int, float)) or v <= 0:
            return fail(f"{hist[-1].name} pins no {key} (got {v!r}) — "
                        f"the measured roofline never landed on the bench "
                        f"artifact")
    norm = ps.normalize(newest)
    degraded = ps.degrade(norm, 0.25)
    findings, _n = ps.compare([norm], degraded, tolerance=0.25,
                              min_history=1, min_seconds=0.05)
    tripped = {f["metric"] for f in findings}
    for key in ("headline.measured_roofline_frac",
                "headline.kernel_time_frac"):
        if key not in tripped:
            return fail(f"degraded replay did not trip the sentinel on "
                        f"{key} (tripped: {sorted(tripped)})")
    print(f"fleet_smoke: roofline pins OK — {hist[-1].name} carries "
          f"measured_roofline_frac={newest['measured_roofline_frac']} "
          f"kernel_time_frac={newest['kernel_time_frac']}, degraded "
          f"replay trips both bands")
    return 0


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--work", default=None)
    ap.add_argument("--keep", action="store_true")
    ap.add_argument("--only", choices=("fleet", "profile", "pins"),
                    default=None, help="run a single phase (debugging)")
    args = ap.parse_args(argv)

    import shutil

    work = Path(args.work) if args.work else Path(
        tempfile.mkdtemp(prefix="sm_fleet_smoke_"))
    work.mkdir(parents=True, exist_ok=True)
    try:
        t0 = time.time()
        if args.only in (None, "fleet"):
            rc = phase_fleet(work)
            if rc:
                return rc
        if args.only in (None, "profile"):
            rc = phase_profile(work)
            if rc:
                return rc
        if args.only in (None, "pins"):
            rc = phase_roofline_pins()
            if rc:
                return rc
        print(f"fleet_smoke: OK ({time.time() - t0:.1f}s)")
        return 0
    finally:
        if not args.keep and args.work is None:
            shutil.rmtree(work, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
