#!/usr/bin/env python
"""Trace smoke gate (ISSUE 5 satellite; wired into scripts/check_tier1.sh).

Runs the spheroid fixture through the REAL in-process annotation service
with tracing enabled, then asserts the acceptance shape end to end:

- ``GET /jobs/<id>/trace`` returns Perfetto-loadable Chrome trace JSON;
- the raw records validate against the event schema (utils/tracing.py);
- ONE root ``submit`` span covers admission → claim → every SearchJob
  phase → ≥1 per-batch scoring span → ≥1 isocalc worker span →
  store_results (all inside the root's [ts, ts+dur] window);
- ``scripts/trace_report.py`` renders the phase/batch breakdown from it.

Exit 0 = gate passes.
"""

from __future__ import annotations

import json
import sys
import tempfile
import urllib.request
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO_ROOT))

from scripts import trace_report  # noqa: E402
from scripts.load_sweep import Harness, _msg, build_fixtures  # noqa: E402
from sm_distributed_tpu.utils import tracing  # noqa: E402

REQUIRED_SPANS = ("submit", "attempt", "stage_input", "read_dataset",
                  "score", "score_batch", "isocalc_chunk", "fdr",
                  "store_results")
REQUIRED_EVENTS = ("submit", "claim")


def fail(msg: str) -> int:
    print(f"trace_smoke: FAIL — {msg}", file=sys.stderr)
    return 1


def run(work: Path) -> int:
    fx = build_fixtures(work)
    h = Harness(work, "trace_smoke")
    try:
        status, _hd, body = h.submit(_msg(fx, "fast", "traced1"))
        if status != 202:
            return fail(f"submit returned {status}: {body}")
        if not body.get("trace_id"):
            return fail(f"submit response lacks trace_id: {body}")
        msg_id = body["msg_id"]
        rows = h.wait_terminal([msg_id])
        if rows[msg_id]["state"] != "done":
            return fail(f"job state {rows[msg_id]['state']}: "
                        f"{rows[msg_id]['error']!r}")

        # Chrome/Perfetto export from the live endpoint
        with urllib.request.urlopen(
                f"{h.base}/jobs/{msg_id}/trace", timeout=30.0) as r:
            chrome = json.loads(r.read())
        evts = chrome.get("traceEvents")
        if not isinstance(evts, list) or not evts:
            return fail("chrome trace has no traceEvents")
        bad = [e for e in evts
               if e.get("ph") not in ("X", "i", "M")
               or "name" not in e or "pid" not in e]
        if bad:
            return fail(f"malformed chrome events: {bad[:3]}")
        if chrome.get("otherData", {}).get("trace_id") != body["trace_id"]:
            return fail("otherData.trace_id mismatch")

        # raw records: schema + required span coverage under ONE root
        with urllib.request.urlopen(
                f"{h.base}/jobs/{msg_id}/trace?raw=1", timeout=30.0) as r:
            records = json.loads(r.read())["records"]
        problems = tracing.validate_records(records)
        if problems:
            return fail("schema problems: " + "; ".join(problems[:5]))
        span_names = {r["name"] for r in records if r["kind"] == "span"}
        event_names = {r["name"] for r in records if r["kind"] == "event"}
        missing = [n for n in REQUIRED_SPANS if n not in span_names]
        missing += [f"event:{n}" for n in REQUIRED_EVENTS
                    if n not in event_names]
        if missing:
            return fail(f"required spans/events missing: {missing} "
                        f"(have spans={sorted(span_names)}, "
                        f"events={sorted(event_names)})")
        roots = [r for r in records
                 if r["kind"] == "span" and r["name"] == "submit"]
        if len(roots) != 1:
            return fail(f"expected exactly one root submit span, got "
                        f"{len(roots)}")
        root = roots[0]
        if {r["trace_id"] for r in records} != {root["trace_id"]}:
            return fail("records span multiple trace_ids")
        lo, hi = root["ts"] - 0.05, root["ts"] + root["dur"] + 0.05
        stray = [r["name"] for r in records
                 if r["kind"] == "span" and not (lo <= r["ts"] <= hi)]
        if stray:
            return fail(f"spans outside the root window: {stray}")

        # the report renders from the same file the endpoint served
        trace_path = tracing.trace_path(h.service.trace_dir,
                                        body["trace_id"])
        rc = trace_report.main([str(trace_path), "--validate"])
        if rc != 0:
            return fail(f"trace_report exited {rc}")
    finally:
        h.shutdown()
    print("trace_smoke: OK — root span, phase/batch/worker spans, schema, "
          "chrome export, and trace_report all check out")
    return 0


def main() -> int:
    import shutil

    work = Path(tempfile.mkdtemp(prefix="sm_trace_smoke_"))
    try:
        return run(work)
    finally:
        shutil.rmtree(work, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
