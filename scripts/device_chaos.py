#!/usr/bin/env python
"""Device-fault survival gate (ISSUE 14; wired into scripts/check_tier1.sh).

Proves the chip-level fault-survival layer end to end on a virtual 8-chip
CPU mesh, through the REAL service stack (spool, scheduler, device pool +
health tracker, SearchJob, tracing), with a 4-chip pool:

1. **golden**: a ``devices: 4`` submit scores through the pjit-sharded
   4-chip mesh fault-free — its stored annotations are the golden report;
2. **sticky chip death mid-job**: chip 3 is marked bad through the
   probe's chaos seam (``HealthTracker.simulate_bad`` — the CPU CI analog
   of dead hardware) and a sticky fault is injected at the second scoring
   group (``backend.chip_fault`` failpoint).  The health tracker
   probe-attributes the fault, quarantines chip 3, and the scheduler's
   retry re-leases the three survivors: the job resumes from its group-0
   checkpoint on the SHRUNKEN 3-chip mesh and its stored annotations are
   **bit-identical** to the 4-chip golden (the shape-bucket lattice +
   mesh-independent metrics contract).  The quarantine is visible on
   ``/debug/devices``, ``sm_device_quarantines_total`` and
   ``sm_device_health{device="3"}`` on ``/metrics``, and no lease after
   the quarantine includes chip 3;
3. **half-open readmission**: the simulated fault is lifted; after the
   re-probe cooldown the chip is readmitted and the next 4-chip submit
   holds all four chips again.

Without ``--smoke``, two more stages run: a **transient** fault
(ConnectionError class) that retries on the same chips with NO
quarantine, and a **host eviction** where quarantining enough of one host
domain's chips fences the whole domain.

Exit 0 = gate passes.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time
import urllib.request
from pathlib import Path

# the virtual 8-chip mesh must exist BEFORE jax initializes (same dance as
# multichip_smoke.py / tests/conftest.py)
os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = [f for f in os.environ.get("XLA_FLAGS", "").split()
          if "xla_force_host_platform_device_count" not in f]
_flags.append("--xla_force_host_platform_device_count=8")
os.environ["XLA_FLAGS"] = " ".join(_flags)

REPO_ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO_ROOT))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pandas as pd  # noqa: E402

from scripts.load_sweep import Harness, _msg, build_fixtures  # noqa: E402
from sm_distributed_tpu.models import faults  # noqa: E402
from sm_distributed_tpu.utils import failpoints  # noqa: E402

POOL = 4


def fail(msg: str) -> int:
    print(f"device_chaos: FAIL — {msg}", file=sys.stderr)
    return 1


def _get(h: Harness, path: str):
    with urllib.request.urlopen(h.base + path, timeout=30.0) as r:
        return json.loads(r.read())


def _trace_records(h: Harness, msg_id: str) -> list[dict]:
    return _get(h, f"/jobs/{msg_id}/trace?raw=1")["records"]


def _stored(h: Harness, ds_id: str) -> pd.DataFrame:
    p = Path(h.sm_config.storage.results_dir) / ds_id / "annotations.parquet"
    return pd.read_parquet(p).sort_values(
        ["sf", "adduct"]).reset_index(drop=True)


def _leases(records: list[dict]) -> list[tuple[float, list[int]]]:
    """(ts, chip list) of every device_token_acquired event, in order."""
    return [(float(r["ts"]), list((r.get("attrs") or {}).get("devices", [])))
            for r in records
            if r["kind"] == "event"
            and r["name"] == "device_token_acquired"]


def run(work: Path, smoke: bool) -> int:
    if len(jax.devices()) < 8:
        return fail(f"virtual mesh failed: {len(jax.devices())} devices")
    from sm_distributed_tpu.analysis import lockorder

    lockorder.enable()
    fx = build_fixtures(work)
    h = Harness(work, "device_chaos", sm_overrides={
        "backend": "jax_tpu",
        "parallel": {"formula_batch": 2, "checkpoint_every": 1},
        "service": {"workers": 1, "max_attempts": 3,
                    "device_pool_size": POOL, "devices_per_job": POOL,
                    "health_reprobe_after_s": 0.5,
                    "backoff_base_s": 0.05, "backoff_max_s": 0.2},
    })
    health = h.service.device_pool.health
    try:
        # ---- 1. fault-free 4-chip golden --------------------------------
        status, _hd, _b = h.submit(_msg(fx, "fast", "golden4", devices=POOL))
        if status != 202:
            return fail(f"golden submit returned {status}")
        rows = h.wait_terminal(["golden4"])
        if rows["golden4"]["state"] != "done":
            return fail(f"golden job {rows['golden4']['state']}: "
                        f"{rows['golden4']['error']!r}")
        golden = _stored(h, "golden4")
        g_leases = _leases(_trace_records(h, "golden4"))
        if not g_leases or g_leases[-1][1] != [0, 1, 2, 3]:
            return fail(f"golden lease {g_leases}, wanted all {POOL} chips")
        print(f"device_chaos: golden 4-chip job OK "
              f"({len(golden)} annotations)")

        # ---- 2. sticky chip death mid-sharded-job -----------------------
        # the job is granted all 4 chips first (lease-time probes pass),
        # THEN chip 3's hardware dies mid-run: each group's scoring sleeps
        # so the fault (2nd group) lands well after the grant, and the
        # probe seam starts reporting chip 3 bad the moment the job is
        # seen holding its 4-chip lease.  The sticky fault at group 1 is
        # probe-attributed to chip 3, which is quarantined; the retry
        # re-leases the 3 survivors and resumes from the group-0 ckpt.
        failpoints.configure("device.score_batch=sleep:0.4;"
                             "backend.chip_fault=raise:RuntimeError@2")
        try:
            status, _hd, _b = h.submit(
                _msg(fx, "fast", "fault4", devices=POOL))
            if status != 202:
                return fail(f"fault submit returned {status}")
            deadline = time.time() + 60.0
            granted = False
            while time.time() < deadline and not granted:
                try:
                    granted = any(devs == [0, 1, 2, 3] for _ts, devs
                                  in _leases(_trace_records(h, "fault4")))
                except (OSError, ValueError, KeyError):
                    granted = False   # trace not started yet (404/empty)
                if not granted:
                    time.sleep(0.05)
            if not granted:
                return fail("fault job never acquired the 4-chip lease")
            health.simulate_bad({3})   # the chip dies mid-job
            rows = h.wait_terminal(["fault4"])
        finally:
            failpoints.configure(None)
        if rows["fault4"]["state"] != "done":
            return fail(f"fault job {rows['fault4']['state']}: "
                        f"{rows['fault4']['error']!r}")
        if rows["fault4"]["attempts"] < 2:
            return fail("fault job finished in one attempt — the sticky "
                        "fault never fired")
        # exactly-once completion: one done/ copy, no other spool state
        spool = {s: sorted(p.name for p in (h.root / s).glob("fault4.json"))
                 for s in ("pending", "running", "done", "failed",
                           "quarantine")}
        if spool["done"] != ["fault4.json"] or any(
                v for k, v in spool.items() if k != "done"):
            return fail(f"fault4 spool message lost/duplicated: {spool}")

        # bit-identical convergence on the shrunken mesh
        got = _stored(h, "fault4")
        try:
            pd.testing.assert_frame_equal(got, golden, check_exact=True)
        except AssertionError as exc:
            return fail("3-chip rescore diverged from the 4-chip golden: "
                        + str(exc).splitlines()[-1])

        # quarantine visible + honored by every later lease
        records = _trace_records(h, "fault4")
        quarantines = [r for r in records if r["kind"] == "event"
                       and r["name"] == "device_quarantine"]
        if not quarantines or quarantines[0]["attrs"]["device"] != 3:
            return fail(f"no device_quarantine event for chip 3: "
                        f"{[q.get('attrs') for q in quarantines]}")
        q_ts = float(quarantines[0]["ts"])
        leases = _leases(records)
        after = [devs for ts, devs in leases if ts > q_ts]
        if not after or after[-1] != [0, 1, 2]:
            return fail(f"retry lease after quarantine was {after}, wanted "
                        f"the 3 survivors [0, 1, 2]")
        if any(3 in devs for devs in after):
            return fail(f"a lease after the quarantine included chip 3: "
                        f"{after}")
        dev = _get(h, "/debug/devices")
        chip3 = next(c for c in dev["health"]["chips"] if c["device"] == 3)
        if chip3["state"] != "quarantined":
            return fail(f"/debug/devices chip 3 state {chip3['state']}")
        text = h.metrics_text()
        if "sm_device_quarantines_total 1" not in text.replace(".0", ""):
            if "sm_device_quarantines_total" not in text:
                return fail("/metrics lacks sm_device_quarantines_total")
        if 'sm_device_health{device="3"} 2' not in text:
            return fail('/metrics lacks sm_device_health{device="3"} == 2')
        resumed = [r for r in records if r["kind"] == "event"
                   and r["name"] == "device_fault"]
        if not resumed or resumed[0]["attrs"]["kind"] != "sticky":
            return fail(f"no sticky device_fault event: {resumed}")
        print("device_chaos: sticky chip 3 quarantined mid-job; job "
              "resumed from checkpoint on chips [0, 1, 2] — stored "
              "annotations BIT-IDENTICAL to the 4-chip golden")

        # ---- 3. half-open readmission -----------------------------------
        health.simulate_bad(())
        deadline = time.time() + 10.0
        readmitted = []
        while time.time() < deadline and not readmitted:
            time.sleep(0.2)
            readmitted = health.reprobe_due()
        if 3 not in readmitted:
            return fail(f"chip 3 never readmitted (got {readmitted})")
        status, _hd, _b = h.submit(_msg(fx, "fast", "after4", devices=POOL))
        if status != 202:
            return fail(f"post-readmit submit returned {status}")
        rows = h.wait_terminal(["after4"])
        if rows["after4"]["state"] != "done":
            return fail(f"post-readmit job {rows['after4']['state']}")
        leases = _leases(_trace_records(h, "after4"))
        if not leases or leases[-1][1] != [0, 1, 2, 3]:
            return fail(f"post-readmit lease {leases}, wanted all 4 chips")
        if "sm_device_readmits_total" not in h.metrics_text():
            return fail("/metrics lacks sm_device_readmits_total")
        print("device_chaos: chip 3 READMITTED after a passing re-probe; "
              "next job holds all 4 chips again")

        if not smoke:
            rc = _extra_stages(h, fx, health)
            if rc:
                return rc

        rep = lockorder.assert_no_cycles("device_chaos")
        print(f"device_chaos: lock-order clean "
              f"({rep['locks_instrumented']} locks, {rep['edges']} edges)")
        return 0
    finally:
        h.shutdown()
        lockorder.disable()


def _extra_stages(h: Harness, fx: dict, health) -> int:
    # ---- transient fault: same chips retried, nothing quarantined -------
    before = health.snapshot()["quarantines_total"]
    failpoints.configure("backend.chip_fault=raise:ConnectionError@1")
    try:
        status, _hd, _b = h.submit(_msg(fx, "fast", "transient4", devices=POOL))
        if status != 202:
            return fail(f"transient submit returned {status}")
        rows = h.wait_terminal(["transient4"])
    finally:
        failpoints.configure(None)
    if rows["transient4"]["state"] != "done":
        return fail(f"transient job {rows['transient4']['state']}")
    snap = health.snapshot()
    if snap["quarantines_total"] != before:
        return fail("a transient fault caused a quarantine")
    t_records = _trace_records(h, "transient4")
    t_faults = [r for r in t_records if r["kind"] == "event"
                and r["name"] == "device_fault"]
    if not t_faults or t_faults[0]["attrs"]["kind"] != "transient":
        return fail(f"no transient device_fault event: {t_faults}")
    print("device_chaos: transient fault retried in place — zero "
          "quarantines, job done")

    # ---- host eviction: the tracker fences a failing domain -------------
    from sm_distributed_tpu.service.health import HealthTracker

    ht = HealthTracker(8, hosts=2, host_evict_fraction=0.75,
                       probe_on_lease=False, reprobe_after_s=0.0)
    for chip in (0, 1, 2):
        ht.report_fault((chip,), faults.FAULT_STICKY, "probe says dead")
    snap = ht.snapshot()
    states = [c["state"] for c in snap["chips"]]
    if states[:4] != ["quarantined"] * 4:
        return fail(f"host 0 not fully evicted at 3/4 chips out: {states}")
    if states[4:] != ["ok"] * 4 or snap["host_evictions_total"] != 1:
        return fail(f"host eviction spilled past the domain: {snap}")
    print("device_chaos: host 0 evicted at 3/4 chips quarantined; "
          "host 1 untouched")
    return 0


def main() -> int:
    import argparse

    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--smoke", action="store_true",
                    help="CI subset: golden + sticky-quarantine + readmit")
    ap.add_argument("--work", default=None)
    ap.add_argument("--keep", action="store_true")
    args = ap.parse_args()
    if args.work:
        work = Path(args.work)
        work.mkdir(parents=True, exist_ok=True)
        return run(work, smoke=args.smoke)
    with tempfile.TemporaryDirectory(prefix="sm_device_chaos_") as d:
        rc = run(Path(d), smoke=args.smoke)
        if args.keep:
            print(f"device_chaos: work dir kept at {d}", file=sys.stderr)
        return rc


if __name__ == "__main__":
    sys.exit(main())
