#!/usr/bin/env python
"""Host-loss survival gate (ISSUE 17; wired into scripts/check_tier1.sh).

Proves the POD layer end to end on one box pretending to be a 2-host pod:
the in-process service is host ``h0`` (process 0) and a second REAL
scheduler process — spawned through scripts/replica_chaos.py
``--replica-serve --bare`` with ``SM_PROCESS_ID=1`` / ``SM_HOST_NAME=h1``
— is host ``h1``.  Both heartbeat the shared replica registry; the device
pool's two host domains map process ``i`` ↔ domain ``i``.

1. **golden**: a full-pool submit scores through the pjit-sharded mesh
   spanning both host domains fault-free — the golden report;
2. **host death mid-job**: a second full-pool job is slowed per scoring
   group, and once it holds its cross-host lease, host h1's process is
   SIGKILLed.  The host watchdog sees every process-1 registry beat go
   stale, evicts the whole host domain (``HealthTracker.evict_host`` —
   chips quarantined in one unit), and cancels the in-flight attempt
   (reason kind ``host_evicted``) into the normal retry path: the job
   resumes from its group checkpoint on the SHRUNKEN surviving-host mesh
   and its stored annotations are **bit-identical** to the full-pod
   golden.  Exactly-once spool census, no debris, bounded detection
   latency, and ``sm_pod_*`` metrics are asserted;
3. **host return**: the process is restarted; fresh process-1 beats make
   the watchdog readmit the host (re-probe cooldown zeroed — half-open),
   and the next full-pool submit holds chips on BOTH hosts again.

``--smoke`` runs the same stages on a 4-chip pool (2 chips/host); the
full gate uses 8 chips (4/host).  Exit 0 = gate passes.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import tempfile
import time
import urllib.request
from pathlib import Path

# the virtual 8-chip mesh must exist BEFORE jax initializes (same dance as
# device_chaos.py / tests/conftest.py)
os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = [f for f in os.environ.get("XLA_FLAGS", "").split()
          if "xla_force_host_platform_device_count" not in f]
_flags.append("--xla_force_host_platform_device_count=8")
os.environ["XLA_FLAGS"] = " ".join(_flags)

REPO_ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO_ROOT))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pandas as pd  # noqa: E402

from scripts.chaos_sweep import _debris  # noqa: E402
from scripts.load_sweep import Harness, _msg, build_fixtures  # noqa: E402
from sm_distributed_tpu.service.leases import (  # noqa: E402
    owned_shards,
    shard_of,
)
from sm_distributed_tpu.utils import failpoints  # noqa: E402

HOSTS = 2
SHARDS = 8
SELF_RID = "r0"            # the in-process service (host h0, process 0)
CHILD_RID = "r1"           # the victim scheduler process (host h1, process 1)
CHILD_HOST = "h1"


def fail(msg: str) -> int:
    print(f"host_chaos: FAIL — {msg}", file=sys.stderr)
    return 1


def _get(h: Harness, path: str):
    with urllib.request.urlopen(h.base + path, timeout=30.0) as r:
        return json.loads(r.read())


def _trace_records(h: Harness, msg_id: str) -> list[dict]:
    return _get(h, f"/jobs/{msg_id}/trace?raw=1")["records"]


def _stored(h: Harness, ds_id: str) -> pd.DataFrame:
    p = Path(h.sm_config.storage.results_dir) / ds_id / "annotations.parquet"
    return pd.read_parquet(p).sort_values(
        ["sf", "adduct"]).reset_index(drop=True)


def _leases(records: list[dict]) -> list[tuple[float, list[int]]]:
    return [(float(r["ts"]), list((r.get("attrs") or {}).get("devices", [])))
            for r in records
            if r["kind"] == "event"
            and r["name"] == "device_token_acquired"]


def _pick_id(base: str, owned: set[int]) -> str:
    """A msg id in the SELF replica's shard partition — the bare victim
    must never claim (and null-complete) the real jobs."""
    for i in range(1000):
        cand = f"{base}{i}" if i else base
        if shard_of(cand, SHARDS) in owned:
            return cand
    raise RuntimeError(f"no shard-local id for {base!r}")


def _spawn_child(work: Path, sm_conf: Path, queue_dir: Path,
                 tag: str) -> subprocess.Popen:
    """Host h1: a real bare scheduler process sharing the spool + registry,
    identified as pod process 1 via the launcher env contract."""
    env = dict(os.environ)
    env.pop("SM_FAILPOINTS", None)
    env["SM_PROCESS_ID"] = "1"
    env["SM_HOST_NAME"] = CHILD_HOST
    log = work / "logs" / f"{CHILD_RID}.{tag}.log"
    log.parent.mkdir(parents=True, exist_ok=True)
    cmd = [sys.executable, str(REPO_ROOT / "scripts" / "replica_chaos.py"),
           "--replica-serve", str(queue_dir), str(sm_conf),
           "--replica-id", CHILD_RID, "--bare", "--null-sleep", "0.05",
           "--idle-exit", "600"]
    return subprocess.Popen(cmd, env=env, stdout=open(log, "w"),
                            stderr=subprocess.STDOUT, cwd=str(REPO_ROOT))


def _wait_child_alive(h: Harness, deadline_s: float = 30.0) -> bool:
    deadline = time.time() + deadline_s
    while time.time() < deadline:
        try:
            peers = _get(h, "/peers")["replicas"]
        except OSError:
            peers = []
        for p in peers:
            if p.get("replica_id") == CHILD_RID and p.get("alive") \
                    and p.get("process_id") == 1:
                return True
        time.sleep(0.1)
    return False


def _metric(text: str, prefix: str) -> float:
    total = 0.0
    for line in text.splitlines():
        if line.startswith(prefix):
            try:
                total += float(line.rsplit(" ", 1)[1])
            except (IndexError, ValueError):
                pass
    return total


def run(work: Path, smoke: bool) -> int:
    pool = 4 if smoke else 8
    per_host = pool // HOSTS
    survivors = list(range(per_host))
    evict_chips = list(range(per_host, pool))
    if len(jax.devices()) < pool:
        return fail(f"virtual mesh failed: {len(jax.devices())} devices")
    from sm_distributed_tpu.analysis import lockorder

    lockorder.enable()
    fx = build_fixtures(work)
    h = Harness(work, "host_chaos", sm_overrides={
        "backend": "jax_tpu",
        "parallel": {"formula_batch": 2, "checkpoint_every": 1},
        "service": {"workers": 1, "max_attempts": 3,
                    "device_pool_size": pool, "devices_per_job": pool,
                    "device_pool_hosts": HOSTS,
                    # LONG half-open cooldown: only the watchdog's
                    # host-return path (cooldown zeroed) can readmit
                    # within this gate's runtime
                    "health_reprobe_after_s": 60.0,
                    "backoff_base_s": 0.05, "backoff_max_s": 0.2,
                    "replicas": 2, "spool_shards": SHARDS,
                    "replica_heartbeat_interval_s": 0.2,
                    "replica_stale_after_s": 1.0,
                    "takeover_interval_s": 0.3,
                    "host_watchdog_interval_s": 0.2,
                    "host_stale_after_s": 1.0},
    })
    health = h.service.device_pool.health
    # the victim's own config: numpy_ref + tiny pool (its scheduler never
    # scores anything — the published jobs live in SELF's shards)
    child_sm = {
        "backend": "numpy_ref",
        "work_dir": str(work / "child_work"),
        "storage": {"results_dir": str(work / "child_results"),
                    "store_images": False},
        "service": {"workers": 1, "poll_interval_s": 0.05,
                    "device_pool_size": 2, "quarantine_after": 20,
                    "replicas": 2, "spool_shards": SHARDS,
                    "replica_heartbeat_interval_s": 0.2,
                    "replica_stale_after_s": 1.0,
                    "takeover_interval_s": 0.3},
    }
    sm_conf = work / "child_sm.json"
    sm_conf.write_text(json.dumps(child_sm, indent=2))
    owned = owned_shards(SELF_RID, {SELF_RID, CHILD_RID}, SHARDS)
    ids = {k: _pick_id(k, owned) for k in ("golden", "fault", "after")}
    child = _spawn_child(work, sm_conf, h.queue_dir, "a")
    try:
        if not _wait_child_alive(h):
            return fail(f"host {CHILD_HOST} (process 1) never appeared "
                        "alive on /peers")
        print(f"host_chaos: 2-host pod up — process 0 (self) + process 1 "
              f"({CHILD_HOST}, pid {child.pid}); pool {pool} chips, "
              f"{per_host}/host")

        # ---- 1. fault-free full-pod golden ------------------------------
        status, _hd, _b = h.submit(_msg(fx, "fast", ids["golden"],
                                        devices=pool))
        if status != 202:
            return fail(f"golden submit returned {status}")
        rows = h.wait_terminal([ids["golden"]])
        if rows[ids["golden"]]["state"] != "done":
            return fail(f"golden job {rows[ids['golden']]['state']}: "
                        f"{rows[ids['golden']]['error']!r}")
        golden = _stored(h, ids["golden"])
        g_leases = _leases(_trace_records(h, ids["golden"]))
        if not g_leases or g_leases[-1][1] != list(range(pool)):
            return fail(f"golden lease {g_leases}, wanted all {pool} chips")
        print(f"host_chaos: golden {pool}-chip cross-host job OK "
              f"({len(golden)} annotations)")

        # ---- 2. SIGKILL host h1 mid-sharded-job -------------------------
        # each scoring group sleeps so the kill + staleness horizon +
        # watchdog pass all land while the job still runs; the cancel
        # unwinds it at a cooperative checkpoint and the retry re-leases
        # the surviving host's chips
        failpoints.configure("device.score_batch=sleep:0.8")
        t_submit = time.time()
        try:
            status, _hd, _b = h.submit(_msg(fx, "fast", ids["fault"],
                                            devices=pool))
            if status != 202:
                return fail(f"fault submit returned {status}")
            deadline = time.time() + 60.0
            granted = False
            while time.time() < deadline and not granted:
                try:
                    granted = any(devs == list(range(pool)) for _ts, devs
                                  in _leases(_trace_records(h, ids["fault"])))
                except (OSError, ValueError, KeyError):
                    granted = False
                if not granted:
                    time.sleep(0.05)
            if not granted:
                return fail("fault job never acquired the full-pod lease")
            child.send_signal(signal.SIGKILL)     # host h1 dies mid-job
            t_kill = time.time()
            deadline = time.time() + 15.0
            while time.time() < deadline and \
                    health.snapshot()["host_evictions_total"] < 1:
                time.sleep(0.05)
            detect_s = time.time() - t_kill
            if health.snapshot()["host_evictions_total"] < 1:
                return fail("watchdog never evicted the dead host")
            if detect_s > 5.0:
                return fail(f"host eviction took {detect_s:.1f}s — "
                            "unbounded detection latency")
            rows = h.wait_terminal([ids["fault"]])
        finally:
            failpoints.configure(None)
        convergence_s = time.time() - t_submit
        if rows[ids["fault"]]["state"] != "done":
            return fail(f"fault job {rows[ids['fault']]['state']}: "
                        f"{rows[ids['fault']]['error']!r}")
        if rows[ids["fault"]]["attempts"] < 2:
            return fail("fault job finished in one attempt — the host "
                        "death never interrupted it")
        if convergence_s > 90.0:
            return fail(f"fault job took {convergence_s:.1f}s — "
                        "unbounded convergence")

        # exactly-once completion: one done/ copy, no other spool state
        spool = {s: sorted(p.name for p in (h.root / s).glob(
            f"{ids['fault']}.json"))
            for s in ("pending", "running", "done", "failed", "quarantine")}
        if spool["done"] != [f"{ids['fault']}.json"] or any(
                v for k, v in spool.items() if k != "done"):
            return fail(f"fault spool message lost/duplicated: {spool}")

        # bit-identical convergence on the surviving host's mesh
        got = _stored(h, ids["fault"])
        try:
            pd.testing.assert_frame_equal(got, golden, check_exact=True)
        except AssertionError as exc:
            return fail(f"{per_host}-chip rescore diverged from the "
                        f"{pool}-chip golden: " + str(exc).splitlines()[-1])

        # the whole domain went in one unit; later leases never touch it
        snap = health.snapshot()
        bad = [c["device"] for c in snap["chips"]
               if c["state"] != "quarantined" and c["device"] in evict_chips]
        if bad:
            return fail(f"evicted host's chips not quarantined: {bad}")
        records = _trace_records(h, ids["fault"])
        cancel_ts = [float(r["ts"]) for r in records if r["kind"] == "event"
                     and r["name"] == "cancel"
                     and (r.get("attrs") or {}).get("kind") == "host_evicted"]
        if not cancel_ts:
            return fail("no host_evicted cancel event in the fault trace")
        leases = _leases(records)
        after_evict = [devs for ts, devs in leases if ts > min(cancel_ts)]
        if not after_evict or after_evict[-1] != survivors:
            return fail(f"retry lease after host eviction was "
                        f"{after_evict}, wanted survivors {survivors}")
        if any(set(devs) & set(evict_chips) for devs in after_evict):
            return fail(f"a lease after the eviction touched the dead "
                        f"host's chips: {after_evict}")
        peers = _get(h, "/peers")
        if peers.get("evicted_hosts") != [1]:
            return fail(f"/peers evicted_hosts {peers.get('evicted_hosts')}"
                        ", wanted [1]")
        text = h.metrics_text()
        if _metric(text, "sm_pod_host_evictions_total") != 1:
            return fail("/metrics sm_pod_host_evictions_total != 1")
        if _metric(text, "sm_pod_processes") != 2:
            return fail("/metrics sm_pod_processes != 2")
        if _metric(text, 'sm_pod_process_up{process="1"}') != 0:
            return fail('/metrics sm_pod_process_up{process="1"} != 0')
        if _metric(text, 'sm_jobs_cancelled_total{reason="host_evicted"}') \
                < 1:
            return fail("/metrics recorded no host_evicted cancellation")
        print(f"host_chaos: host {CHILD_HOST} SIGKILLed mid-job; watchdog "
              f"evicted chips {evict_chips} in {detect_s:.1f}s; job resumed "
              f"from checkpoint on {survivors} — stored annotations "
              f"BIT-IDENTICAL to the {pool}-chip golden "
              f"({convergence_s:.1f}s submit→done)")

        # ---- 3. host return → half-open readmission ---------------------
        child = _spawn_child(work, sm_conf, h.queue_dir, "b")
        deadline = time.time() + 20.0
        while time.time() < deadline and \
                _get(h, "/peers").get("evicted_hosts") != []:
            time.sleep(0.1)
        if _get(h, "/peers").get("evicted_hosts") != []:
            return fail("watchdog never noticed the returned host")
        readmitted: set[int] = set()
        deadline = time.time() + 15.0
        while time.time() < deadline and not readmitted >= set(evict_chips):
            time.sleep(0.2)
            readmitted |= set(health.reprobe_due())
        if not readmitted >= set(evict_chips):
            return fail(f"chips {sorted(set(evict_chips) - readmitted)} "
                        "never readmitted after the host returned (the "
                        "60s cooldown should have been zeroed)")
        status, _hd, _b = h.submit(_msg(fx, "fast", ids["after"],
                                        devices=pool))
        if status != 202:
            return fail(f"post-return submit returned {status}")
        rows = h.wait_terminal([ids["after"]])
        if rows[ids["after"]]["state"] != "done":
            return fail(f"post-return job {rows[ids['after']]['state']}")
        leases = _leases(_trace_records(h, ids["after"]))
        if not leases or leases[-1][1] != list(range(pool)):
            return fail(f"post-return lease {leases}, wanted all "
                        f"{pool} chips")
        if _metric(h.metrics_text(), 'sm_pod_process_up{process="1"}') != 1:
            return fail('/metrics sm_pod_process_up{process="1"} != 1 '
                        "after the host returned")
        print(f"host_chaos: host {CHILD_HOST} RETURNED — chips "
              f"{evict_chips} readmitted half-open; next job spans both "
              "hosts again")

        # no tmp/heartbeat/lease debris (checkpoint shards from the
        # cancelled attempt are legitimate resume state, load_sweep rule)
        debris = [p for p in _debris([h.root, h.dir / "results",
                                      h.dir / "work"])
                  if ".ckpt." not in p]
        if debris:
            return fail(f"tmp/heartbeat/lease debris: {debris}")

        rep = lockorder.assert_no_cycles("host_chaos")
        print(f"host_chaos: lock-order clean "
              f"({rep['locks_instrumented']} locks, {rep['edges']} edges)")
        return 0
    finally:
        if child.poll() is None:
            child.kill()
        h.shutdown()
        lockorder.disable()


def main() -> int:
    import argparse

    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--smoke", action="store_true",
                    help="CI subset: same stages on a 4-chip pool")
    ap.add_argument("--work", default=None)
    ap.add_argument("--keep", action="store_true")
    args = ap.parse_args()
    if args.work:
        work = Path(args.work)
        work.mkdir(parents=True, exist_ok=True)
        return run(work, smoke=args.smoke)
    with tempfile.TemporaryDirectory(prefix="sm_host_chaos_") as d:
        rc = run(Path(d), smoke=args.smoke)
        if args.keep:
            print(f"host_chaos: work dir kept at {d}", file=sys.stderr)
        return rc


if __name__ == "__main__":
    sys.exit(main())
