#!/usr/bin/env python
"""Live-acquisition failover chaos harness (ISSUE 19 proof).

Runs TWO real service replicas — separate processes sharing one
partitioned spool AND one work dir (so either can serve chunk appends
for any acquisition) — drives a live streaming acquisition over HTTP
(``POST /submit mode=stream`` + ``POST /datasets/<id>/pixels``), then
takes the claim-owning replica away mid-acquisition:

- ``kill``:  SIGKILL the owner after the first provisional re-rank.  The
  peer's takeover scan fences + requeues the stream job; the resumed job
  rebuilds its view from the committed chunk log, the instrument keeps
  posting chunks to the survivor, and ``POST finish`` converges.
- ``drain``: SIGTERM the owner (controller drain).  The drain hand-off
  republishes the live stream job WITHOUT burning an attempt
  (``sm_recovery_events_total{event="stream.drain_handoff"}``); the
  peer resumes from the same chunk-log checkpoint.

Both variants must converge to a report **bit-identical**
(``check_exact=True``) to the one-shot batch run of the same spectra,
with the exactly-once invariants of scripts/replica_chaos.py: the spool
holds the stream message in ``done/`` exactly once, the ledger carries
exactly one FINISHED row, zero tmp/lease/heartbeat debris anywhere
(committed chunk-log files are results, not debris), and an exactly-once
ingest census — every chunk committed once no matter which replica
served it or how many times the instrument retried.

Usage::

    python scripts/stream_chaos.py             # both scenarios
    python scripts/stream_chaos.py --smoke     # CI gate (same two)
    python scripts/stream_chaos.py --only kill
    python scripts/stream_chaos.py --list

The replica worker process is scripts/replica_chaos.py ``--replica-serve``
(the full AnnotationService stack); this file is only the driver.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import time
import urllib.error
import urllib.request
from dataclasses import dataclass
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO_ROOT))

from scripts.chaos_sweep import _debris, _deep_merge  # noqa: E402
from scripts.replica_chaos import _read_report  # noqa: E402
from sm_distributed_tpu.engine.daemon import (  # noqa: E402
    QUEUE_ANNOTATE,
    QueuePublisher,
    _STATES,
)
from sm_distributed_tpu.engine.storage import JobLedger  # noqa: E402
from sm_distributed_tpu.io.fixtures import (  # noqa: E402
    FIXTURE_FORMULAS,
    generate_synthetic_dataset,
)
from sm_distributed_tpu.io.imzml import ImzMLReader  # noqa: E402
from sm_distributed_tpu.service.leases import owned_shards, shard_of  # noqa: E402

REPLICAS = ("r0", "r1")       # r0 is always the owner/victim
VICTIM = "r0"
SURVIVOR = "r1"
SHARDS = 8
DS_ID = "live"
N_CHUNKS = 3

# off-lattice spheroid (odd dims force the pad/bucket path, same fixture
# shape tests/test_stream.py pins) — small enough that a scenario is seconds
FIXTURE = dict(nrows=9, ncols=11, formulas=FIXTURE_FORMULAS[:8],
               present_fraction=0.5, noise_peaks=12, mz_jitter_ppm=0.5,
               seed=41)

SM_TEMPLATE = {
    "backend": "numpy_ref",
    "fdr": {"decoy_sample_size": 8, "seed": 42},
    "parallel": {"formula_batch": 16, "checkpoint_every": 2,
                 "resident_datasets": 2, "order_ions": "table"},
    "storage": {"store_images": False},
    "service": {"workers": 2, "poll_interval_s": 0.05, "job_timeout_s": 60.0,
                "max_attempts": 3, "backoff_base_s": 0.05,
                "backoff_max_s": 0.2, "backoff_jitter": 0.05,
                "heartbeat_interval_s": 0.2, "stale_after_s": 1.0,
                "drain_timeout_s": 10.0, "http_port": 0,
                "quarantine_after": 20,
                "replicas": len(REPLICAS), "spool_shards": SHARDS,
                "replica_heartbeat_interval_s": 0.25,
                "replica_stale_after_s": 1.0,
                "takeover_interval_s": 0.3,
                "stream": {"idle_timeout_s": 60.0, "poll_interval_s": 0.05,
                           "rescore_min_chunks": 1}},
}


@dataclass
class Scenario:
    """Take the claim-owning replica away mid-acquisition."""

    name: str
    kill_sig: int                 # signal delivered to the owner
    note: str = ""
    expect_rc: int | None = None  # owner's exit code (None = -kill_sig)
    # drain republishes via the hand-off seam; a SIGKILL owner leaves its
    # claim for the survivor's takeover scan to fence + requeue
    expect_handoff_event: str | None = None


SCENARIOS: list[Scenario] = [
    Scenario("kill", signal.SIGKILL,
             "owner SIGKILLed after the first provisional re-rank; peer "
             "takeover fences + requeues, resumes from the chunk log"),
    Scenario("drain", signal.SIGTERM,
             "owner drained (controller retire); stream job republished "
             "without burning an attempt, peer resumes",
             expect_rc=0, expect_handoff_event="stream.drain_handoff"),
]

SMOKE = ("kill", "drain")


# ------------------------------------------------------------------ plumbing
def _sub_env() -> dict:
    env = dict(os.environ)
    env.pop("SM_FAILPOINTS", None)
    env.setdefault("SM_LOCK_ORDER", "raise")
    return env


def _write_sm(base: Path) -> Path:
    sm = _deep_merge(json.loads(json.dumps(SM_TEMPLATE)), {})
    sm["work_dir"] = str(base / "work")
    sm["storage"] = dict(sm["storage"], results_dir=str(base / "results"))
    p = base / "sm.json"
    p.write_text(json.dumps(sm, indent=2))
    return p


def _pick_msg_id() -> str:
    """A msg id whose spool shard the victim owns while both replicas are
    alive — guarantees the victim is the replica running the stream job."""
    mine = owned_shards(VICTIM, set(REPLICAS), SHARDS)
    for i in range(256):
        cand = f"live{i}"
        if shard_of(cand, SHARDS) in mine:
            return cand
    raise RuntimeError("no candidate msg id lands on the victim's shards")


def _run_replica(base: Path, sm_conf: Path, rid: str,
                 idle_exit: float = 2.0):
    log = base / "logs" / f"{rid}.log"
    log.parent.mkdir(parents=True, exist_ok=True)
    cmd = [sys.executable, str(REPO_ROOT / "scripts" / "replica_chaos.py"),
           "--replica-serve", str(base / "queue"), str(sm_conf),
           "--replica-id", rid, "--idle-exit", str(idle_exit),
           "--metrics-dump", str(base / "metrics" / f"{rid}.prom"),
           "--ports-dir", str(base / "ports")]
    fh = open(log, "w")
    return subprocess.Popen(cmd, env=_sub_env(), stdout=fh, stderr=fh,
                            cwd=str(REPO_ROOT)), log


def _wait_port(base: Path, rid: str, timeout_s: float = 60.0) -> int:
    pf = base / "ports" / f"{rid}.port"
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        if pf.exists():
            try:
                return int(pf.read_text())
            except ValueError:
                pass
        time.sleep(0.05)
    raise RuntimeError(f"{rid}: port file never appeared")


def _req(port: int, path: str, payload: dict | None = None,
         timeout_s: float = 10.0) -> tuple[int, dict]:
    data = json.dumps(payload).encode() if payload is not None else None
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        method="POST" if payload is not None else "GET", data=data,
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout_s) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _post_chunk(port: int, seq: int, coords, spectra,
                retries: int = 40) -> None:
    """Instrument-side chunk POST with the documented retry contract: on a
    connection error or 5xx, re-POST the SAME seq — idempotent by design."""
    body = {"seq": seq, "coords": coords,
            "mzs": [list(s[0]) for s in spectra],
            "ints": [list(s[1]) for s in spectra]}
    last = None
    for _ in range(retries):
        try:
            status, out = _req(port, f"/datasets/{DS_ID}/pixels", body)
        except OSError as exc:
            last, status = exc, -1
        if status == 200:
            return
        last = last if status == -1 else f"HTTP {status}: {out}"
        time.sleep(0.25)
    raise RuntimeError(f"chunk {seq} never accepted: {last}")


def _stream_state(port: int, msg_id: str) -> dict:
    """The acquisition's view through GET /jobs/<id>: job state + the
    provisional ``partial.stream`` coverage block."""
    try:
        status, job = _req(port, f"/jobs/{msg_id}")
    except OSError:
        return {}
    if status != 200:
        return {}
    part = (job.get("partial") or {}).get("stream") or {}
    return {"state": job.get("state"), "chunks": part.get("chunks", 0),
            "pixels": part.get("pixels", 0)}


def _wait_stream(port: int, msg_id: str, min_chunks: int,
                 timeout_s: float = 90.0) -> dict:
    deadline = time.time() + timeout_s
    last: dict = {}
    while time.time() < deadline:
        last = _stream_state(port, msg_id)
        if last.get("chunks", 0) >= min_chunks:
            return last
        time.sleep(0.1)
    raise RuntimeError(
        f"provisional coverage never reached {min_chunks} chunks: {last}")


def _spool_census(root: Path) -> dict:
    return {s: sorted(p.stem for p in (root / s).glob("*.json"))
            for s in _STATES}


def _metric_value(text: str, prefix: str) -> float:
    total = 0.0
    for line in text.splitlines():
        if line.startswith(prefix):
            try:
                total += float(line.rsplit(" ", 1)[1])
            except (IndexError, ValueError):
                pass
    return total


# -------------------------------------------------------------- fixture/golden
def build_fixture(base: Path):
    fx_dir = base / "fixture"
    imzml_path, truth = generate_synthetic_dataset(fx_dir, **FIXTURE)
    with ImzMLReader(imzml_path) as rd:
        coords = rd.coordinates.tolist()
        spectra = [tuple(a.tolist() for a in rd.read_spectrum(i))
                   for i in range(rd.n_spectra)]
    n = len(coords)
    edges = [round(i * n / N_CHUNKS) for i in range(N_CHUNKS + 1)]
    chunks = [(coords[edges[i]:edges[i + 1]],
               spectra[edges[i]:edges[i + 1]]) for i in range(N_CHUNKS)]
    return imzml_path, truth.formulas, chunks


def _msg(msg_id: str, formulas: list[str], input_path: str,
         mode: str) -> dict:
    m = {"ds_id": DS_ID, "ds_name": DS_ID, "msg_id": msg_id,
         "input_path": input_path, "formulas": formulas, "tenant": "t0",
         "ds_config": {"isotope_generation": {"adducts": ["+H"]},
                       "image_generation": {"ppm": 3.0}}}
    if mode == "stream":
        m["mode"] = "stream"
    return m


def run_golden(base: Path, imzml_path: Path, formulas: list[str]):
    """The one-shot batch run over the SAME spectra — the report every
    streaming scenario must converge to bit-identically."""
    gbase = base / "golden"
    gbase.mkdir(parents=True)
    sm_conf = _write_sm(gbase)
    QueuePublisher(gbase / "queue").publish(
        _msg("g0", formulas, str(imzml_path), mode="batch"))
    proc, log = _run_replica(gbase, sm_conf, "r0")
    rc = proc.wait(timeout=180)
    if rc != 0:
        raise RuntimeError(f"golden run failed rc={rc}:\n"
                           f"{log.read_text()[-3000:]}")
    return _read_report(gbase / "results", DS_ID)


# ------------------------------------------------------------------ invariants
def check_invariants(base: Path, golden, msg_id: str,
                     errs: list[str]) -> None:
    import pandas as pd

    root = base / "queue" / QUEUE_ANNOTATE
    census = _spool_census(root)
    if census["done"] != [msg_id]:
        errs.append(f"spool not exactly-once done: {census}")
    others = {s: v for s, v in census.items() if s != "done" and v}
    if others:
        errs.append(f"messages left outside done/: {others}")
    from sm_distributed_tpu.service.leases import LeaseStore

    LeaseStore(root, "operator").sweep_orphans(root, max_age_s=0.0)
    leftover = sorted(p.name for p in (root / "leases").glob("*.json"))
    if leftover:
        errs.append(f"lease files for terminal messages: {leftover}")
    # checkpoint shards from the pre-failover attempt are legitimate resume
    # state (replica_chaos rule); everything else must be gone — including
    # torn chunk-append tmps under work/stream
    debris = [p for p in _debris([root, base / "results", base / "work"])
              if ".ckpt." not in p]
    if debris:
        errs.append(f"tmp/heartbeat/lease debris: {debris}")
    ledger = JobLedger(base / "results")
    try:
        ledger.fail_stale_started(ds_ids=[DS_ID], before=time.time())
        jobs = ledger.jobs(DS_ID)
        if jobs.empty:
            errs.append(f"{DS_ID}: no ledger rows")
        else:
            if jobs.iloc[-1].status != "FINISHED":
                errs.append(f"{DS_ID}: newest job {jobs.iloc[-1].status}")
            n_fin = int((jobs.status == "FINISHED").sum())
            if n_fin != 1:
                errs.append(f"{DS_ID}: {n_fin} FINISHED rows (double "
                            f"completion)")
            idx = ledger._conn.execute(
                "SELECT COUNT(*) FROM annotation WHERE ds_id=?",
                (DS_ID,)).fetchone()[0]
            if idx != len(golden[0]):
                errs.append(f"{DS_ID}: index rows {idx} != golden "
                            f"{len(golden[0])}")
    finally:
        ledger.close()
    # the tentpole: bit-identical to batch, not merely close
    try:
        got = _read_report(base / "results", DS_ID)
    except Exception as exc:
        errs.append(f"{DS_ID}: unreadable results: {exc}")
        return
    for label, g, w in (("annotations", got[0], golden[0]),
                        ("all_metrics", got[1], golden[1])):
        try:
            pd.testing.assert_frame_equal(g, w, check_exact=True)
        except AssertionError as e:
            errs.append(f"{DS_ID}: {label} not bit-identical to batch: "
                        f"{str(e).splitlines()[-1]}")
    # exactly-once ingest census: committed chunk log == the acquisition,
    # no more — duplicates/retries never doubled a chunk
    stream_dir = base / "work" / "stream" / DS_ID
    man = stream_dir / "manifest.json"
    if not man.is_file():
        errs.append("chunk-log manifest missing after convergence")
    else:
        m = json.loads(man.read_text())
        if not m.get("finished"):
            errs.append(f"manifest not sealed: {m}")
        seqs = sorted(int(s) for s in m.get("chunks", {}))
        if seqs != list(range(N_CHUNKS)):
            errs.append(f"manifest seqs {seqs} != 0..{N_CHUNKS - 1}")
        on_disk = sorted(stream_dir.glob("chunk_*.npz"))
        if len(on_disk) != N_CHUNKS:
            errs.append(f"{len(on_disk)} chunk files on disk, want "
                        f"{N_CHUNKS}: {[p.name for p in on_disk]}")


def run_scenario(sc: Scenario, work: Path, chunks, formulas: list[str],
                 golden, verbose: bool = False) -> dict:
    base = work / sc.name
    base.mkdir(parents=True)
    sm_conf = _write_sm(base)
    msg_id = _pick_msg_id()
    QueuePublisher(base / "queue").publish(
        _msg(msg_id, formulas, f"stream://{DS_ID}", mode="stream"))
    procs: dict[str, subprocess.Popen] = {}
    result = {"scenario": sc.name, "ok": False}
    root = base / "queue" / QUEUE_ANNOTATE
    t0 = time.time()
    try:
        # start the victim ALONE so it deterministically claims the stream
        # job (its shard is the victim's under the 2-replica assignment, so
        # the later-joining peer never steals it)
        procs[VICTIM], victim_log = _run_replica(base, sm_conf, VICTIM,
                                                 idle_exit=3.0)
        vport = _wait_port(base, VICTIM)
        # generous: this box can be 1-core and a cold replica pays the
        # full jax import before its first dispatcher tick
        deadline = time.time() + 120.0
        while time.time() < deadline:
            if _stream_state(vport, msg_id).get("state") == "running":
                break
            if procs[VICTIM].poll() is not None:
                result["error"] = "victim exited before claiming"
                return result
            time.sleep(0.05)
        else:
            result["error"] = "victim never claimed the stream job"
            return result
        procs[SURVIVOR], _ = _run_replica(base, sm_conf, SURVIVOR,
                                          idle_exit=3.0)
        sport = _wait_port(base, SURVIVOR)
        # acquisition begins: first chunk through the victim's API, and the
        # scenario only proceeds once a provisional re-rank PUBLISHED — the
        # failover below demonstrably lands mid-acquisition, not before it
        _post_chunk(vport, 0, *chunks[0])
        _wait_stream(vport, msg_id, min_chunks=1)
        procs[VICTIM].send_signal(sc.kill_sig)
        rc_victim = procs[VICTIM].wait(timeout=60)
        result["rc_victim"] = rc_victim
        want_rc = -sc.kill_sig if sc.expect_rc is None else sc.expect_rc
        if rc_victim != want_rc:
            result["error"] = (f"victim rc {rc_victim}, want {want_rc}:\n"
                               f"{victim_log.read_text()[-2000:]}")
            return result
        # the instrument keeps acquiring: remaining chunks through the peer
        # (shared work dir — any replica serves appends for any acquisition)
        for seq in range(1, N_CHUNKS):
            _post_chunk(sport, seq, *chunks[seq])
        # peer takeover/hand-off must resume provisional re-ranking from the
        # chunk-log checkpoint and cover the full acquisition
        _wait_stream(sport, msg_id, min_chunks=N_CHUNKS)
        status, out = _req(sport, f"/datasets/{DS_ID}/finish", {})
        if status != 200:
            result["error"] = f"finish rejected: HTTP {status} {out}"
            return result
        deadline = time.time() + 120.0
        while time.time() < deadline:
            if (root / "done" / f"{msg_id}.json").exists():
                break
            if procs[SURVIVOR].poll() is not None:
                result["error"] = (f"survivor exited rc="
                                   f"{procs[SURVIVOR].poll()} before "
                                   f"convergence: {_spool_census(root)}")
                return result
            time.sleep(0.1)
        else:
            result["error"] = (f"did not converge in 120s: "
                               f"{_spool_census(root)}")
            return result
        result["converge_s"] = round(time.time() - t0, 1)
        try:
            rc = procs[SURVIVOR].wait(timeout=30)
        except subprocess.TimeoutExpired:
            procs[SURVIVOR].send_signal(signal.SIGTERM)
            rc = procs[SURVIVOR].wait(timeout=30)
        result["rc_survivor"] = rc
        errs: list[str] = []
        if rc != 0:
            errs.append(f"survivor exit rc={rc}")
        check_invariants(base, golden, msg_id, errs)
        dump = base / "metrics" / f"{SURVIVOR}.prom"
        if not dump.exists():
            errs.append("survivor left no metrics dump")
        else:
            text = dump.read_text()
            if _metric_value(text, "sm_stream_reranks_total") < 1:
                errs.append("survivor published no provisional re-rank "
                            "after failover")
        if sc.expect_handoff_event:
            needle = f'event="{sc.expect_handoff_event}"'
            vdump = base / "metrics" / f"{VICTIM}.prom"
            seen = (vdump.exists() and needle in vdump.read_text()) or \
                needle.split('"')[1] in victim_log.read_text()
            if not seen:
                errs.append(f"victim recorded no {sc.expect_handoff_event}")
        if errs:
            result["error"] = "; ".join(errs)
            return result
        result["ok"] = True
        return result
    finally:
        for p in procs.values():
            if p.poll() is None:
                p.kill()


def run_sweep(work: Path, only: list[str] | None = None,
              verbose: bool = False) -> list[dict]:
    os.environ.pop("SM_FAILPOINTS", None)
    names = {sc.name for sc in SCENARIOS}
    if only is not None and not set(only) <= names:
        raise RuntimeError(f"unknown scenario names: {set(only) - names}")
    scenarios = SCENARIOS if only is None else [
        sc for sc in SCENARIOS if sc.name in only]
    work.mkdir(parents=True, exist_ok=True)
    imzml_path, formulas, chunks = build_fixture(work)
    t0 = time.time()
    golden = run_golden(work, imzml_path, formulas)
    print(f"golden batch report: {len(golden[0])} annotations, "
          f"{len(golden[1])} scored ions ({time.time() - t0:.1f}s)")
    results = []
    for sc in scenarios:
        t0 = time.time()
        r = run_scenario(sc, work, chunks, formulas, golden, verbose=verbose)
        r["seconds"] = round(time.time() - t0, 1)
        status = "OK " if r["ok"] else "FAIL"
        print(f"[{status}] {sc.name:<8} {r['seconds']:>5.1f}s  {sc.note}")
        if not r["ok"]:
            print(f"       error: {r.get('error')}")
        results.append(r)
    n_ok = sum(r["ok"] for r in results)
    print(f"stream chaos: {n_ok}/{len(results)} failovers converged "
          f"bit-identical to batch with exactly-once outcomes")
    return results


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--work", default=None)
    ap.add_argument("--smoke", action="store_true",
                    help=f"CI subset: {', '.join(SMOKE)}")
    ap.add_argument("--only", default=None)
    ap.add_argument("--list", action="store_true", dest="list_scenarios")
    ap.add_argument("--keep", action="store_true")
    ap.add_argument("-v", "--verbose", action="store_true")
    args = ap.parse_args(argv)

    if args.list_scenarios:
        for sc in SCENARIOS:
            print(f"{sc.name:<8} {sc.note}")
        return 0
    only = list(SMOKE) if args.smoke else (
        args.only.split(",") if args.only else None)
    import shutil
    import tempfile

    work = Path(args.work) if args.work else Path(
        tempfile.mkdtemp(prefix="sm_stream_chaos_"))
    try:
        results = run_sweep(work, only=only, verbose=args.verbose)
    finally:
        if not args.keep and args.work is None:
            shutil.rmtree(work, ignore_errors=True)
    return 0 if all(r["ok"] for r in results) else 1


if __name__ == "__main__":
    sys.exit(main())
