#!/usr/bin/env python
"""smlint — project-invariant static analysis for the sm-tpu tree.

Runs the rule set in ``sm_distributed_tpu/analysis`` (docs/ANALYSIS.md has
the catalog) over the engine + scripts and exits nonzero on any NEW
finding — one not covered by the committed suppression baseline
(``conf/smlint_baseline.json``) or an inline ``# smlint: ignore[rule]``.

    python scripts/smlint.py                      # lint the default tree
    python scripts/smlint.py sm_distributed_tpu   # lint one subtree
    python scripts/smlint.py --json               # machine-readable report
    python scripts/smlint.py --self-check         # baseline minimal + every
                                                  # rule's fixture still fires
    python scripts/smlint.py --write-baseline     # re-emit the baseline from
                                                  # the current findings
    python scripts/smlint.py --list-rules

The ``--json`` report includes a ``sm_analysis_findings_total`` per-rule
summary (total findings, INCLUDING baseline-suppressed ones) so
perf_sentinel-style history diffing can flag rule-count regressions —
a growing suppressed count is drift even while the gate stays green.

Exit codes: 0 clean, 1 new findings (or self-check failure), 2 usage/IO.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO_ROOT))

from sm_distributed_tpu.analysis import core  # noqa: E402
from sm_distributed_tpu.analysis.core import (  # noqa: E402
    Project,
    RULES,
    load_baseline,
    run_lint,
    self_check,
)

DEFAULT_PATHS = ("sm_distributed_tpu", "scripts", "bench.py", "tests")
DEFAULT_BASELINE = "conf/smlint_baseline.json"

# tests/ rides the default tree for EXCEPTION HYGIENE only (ISSUE 12
# satellite): a test helper that silently swallows is how a chaos assert
# rots into a no-op, but the project-invariant rules (metrics naming,
# compile surface, fence gating, ...) are about production modules —
# synthetic registrations inside tests must not trip them.
_TESTS_RULES = {"broad-except", "parse-error"}


def _scope_tests(result):
    """Drop findings in tests/ for every rule outside _TESTS_RULES."""
    def keep(f):
        return not f.path.startswith("tests/") or f.rule in _TESTS_RULES

    result.findings = [f for f in result.findings if keep(f)]
    result.new = [f for f in result.new if keep(f)]
    result.suppressed = [f for f in result.suppressed if keep(f)]
    return result


def _write_baseline(path: Path, result) -> None:
    entries = []
    seen = set()
    for f in result.findings:
        if f.key() in seen:
            continue
        seen.add(f.key())
        entries.append({
            "rule": f.rule, "path": f.path, "anchor": f.anchor,
            "justification": "TODO: justify or fix "
                             f"({f.message[:80]})",
        })
    path.write_text(json.dumps({
        "__doc__": "smlint suppression baseline (docs/ANALYSIS.md). Every "
                   "entry matches findings by (rule, path, anchor) and MUST "
                   "carry a real justification; --self-check fails on "
                   "entries matching zero findings.",
        "suppressions": entries,
    }, indent=2) + "\n")
    print(f"smlint: wrote {len(entries)} suppression(s) to {path}")


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("paths", nargs="*", default=[],
                    help=f"files/dirs to lint (default: {DEFAULT_PATHS})")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE)
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every finding, ignoring the baseline")
    ap.add_argument("--json", action="store_true", dest="as_json")
    ap.add_argument("--rules", default=None,
                    help="comma-separated rule subset to run")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--write-baseline", action="store_true")
    ap.add_argument("--self-check", action="store_true")
    args = ap.parse_args(argv)

    # importing rules registers the shipped set
    from sm_distributed_tpu.analysis import rules as _rules  # noqa: F401

    if args.list_rules:
        for r in RULES.values():
            print(f"{r.name:<22} {r.severity:<8} {r.doc.splitlines()[0]}")
        return 0

    try:
        baseline = [] if args.no_baseline else load_baseline(
            REPO_ROOT / args.baseline)
    except (OSError, ValueError) as exc:
        print(f"smlint: bad baseline {args.baseline}: {exc}", file=sys.stderr)
        return 2

    project = Project.load(REPO_ROOT, list(args.paths) or list(DEFAULT_PATHS))
    only = set(args.rules.split(",")) if args.rules else None
    unknown = (only or set()) - set(RULES)
    if unknown:
        print(f"smlint: unknown rule(s) {sorted(unknown)}", file=sys.stderr)
        return 2
    result = _scope_tests(run_lint(project, baseline, only=only))

    if args.write_baseline:
        _write_baseline(REPO_ROOT / args.baseline, result)
        return 0

    errs = []
    if args.self_check:
        errs = self_check(project, baseline)

    if args.as_json:
        from sm_distributed_tpu.analysis.rules import (
            compile_surface_census,
            numerics_census,
        )

        surface = compile_surface_census(project)
        ncensus = numerics_census(project)
        # numlint totals (ISSUE 15): declared contracts + all findings of
        # the three numerics rules (INCLUDING baseline-suppressed ones),
        # so the analysis drift sentinel bands numerics debt like any
        # other rule-count series
        all_counts = result.counts("all")
        nviol = sum(all_counts.get(r, 0) for r in
                    ("dtype-flow", "masked-reduction", "ulp-contract"))
        print(json.dumps({
            "paths": list(args.paths) or list(DEFAULT_PATHS),
            "files": len(project.modules),
            "new": [f.to_dict() for f in result.new],
            "suppressed": [f.to_dict() for f in result.suppressed],
            "self_check_errors": errs,
            # the perf_sentinel-style history series: per-rule TOTALS
            # (new + suppressed), so baseline growth is visible drift —
            # and the static compile-surface census (jit sites, registered
            # entries), so a quietly growing compile surface diffs across
            # history the same way (ISSUE 12)
            "sm_analysis_findings_total": result.counts("all"),
            "sm_analysis_new_findings_total": result.counts("new"),
            "sm_compile_surface_sites_total": surface["sites"],
            "sm_compile_surface_entries_total": surface["entries"],
            "sm_compile_surface_modules_total": surface["modules"],
            "sm_numerics_contracts_total": ncensus["contracts"],
            "sm_numerics_modules_total": ncensus["modules"],
            "sm_numerics_violations_total": nviol,
        }, indent=2))
    else:
        for f in result.new:
            print(f.render())
        for e in errs:
            print(f"self-check: {e}", file=sys.stderr)
        sup = f", {len(result.suppressed)} baseline-suppressed" \
            if result.suppressed else ""
        counts = ", ".join(f"{k}={v}" for k, v in
                           result.counts("all").items()) or "none"
        print(f"smlint: {'FAIL' if result.new or errs else 'OK'} — "
              f"{len(result.new)} new finding(s){sup} across "
              f"{len(project.modules)} file(s) [{counts}]")
    return 1 if (result.new or errs) else 0


if __name__ == "__main__":
    sys.exit(main())
