#!/usr/bin/env python
"""Trace-driven performance report (ISSUE 5 capstone).

Renders a per-job trace (utils/tracing.py JSONL, or fetched live from a
running service) into the standard perf artifact for this repo:

- the **phase breakdown** — wall clock per pipeline phase, as a share of
  the root ``submit`` span (submit → terminal);
- the **accounting split** — queue wait (submit → first attempt), device-
  token wait (device_hold start → token acquired), device-token hold, and
  compute (the ``score`` phase), so a throughput cliff shows WHERE the
  time moved (scheduler? token contention? device?);
- the **slowest batches** — the top score_batch spans with backend/ion
  counts, the needle for per-batch regressions;
- attempts (with timeout/abandon flags) and event counts (retries,
  cancels, failpoints, breaker flips).

Every future perf PR attaches this report instead of a bare before/after
total.  Usage::

    python scripts/trace_report.py WORKDIR/traces/<trace_id>.jsonl
    python scripts/trace_report.py --url http://127.0.0.1:8685 --job MSG_ID
    python scripts/trace_report.py TRACE.jsonl --json      # machine-readable
    python scripts/trace_report.py TRACE.jsonl --validate  # schema-gate too
    python scripts/trace_report.py TRACE.jsonl --by-replica  # attribution
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO_ROOT))

from sm_distributed_tpu.utils import tracing  # noqa: E402

# phases in pipeline order (anything else traced as a phase appends after)
_PHASE_ORDER = ("stage_input", "read_dataset", "decoy_selection",
                "isotope_patterns", "score", "fdr", "store_results")
_TOP_BATCHES = 10


def load_records(args) -> list[dict]:
    if args.url:
        import urllib.request

        url = f"{args.url.rstrip('/')}/jobs/{args.job}/trace?raw=1"
        with urllib.request.urlopen(url, timeout=30.0) as r:
            body = json.loads(r.read())
        return body.get("records", [])
    return tracing.read_trace(args.trace)


def _spans(records, name=None):
    for r in records:
        if r.get("kind") == "span" and (name is None or r.get("name") == name):
            yield r


def _events(records, name=None):
    for r in records:
        if r.get("kind") == "event" and (name is None or r.get("name") == name):
            yield r


def summarize(records: list[dict]) -> dict:
    """The report's data model (also what --json prints)."""
    root = max(_spans(records, "submit"),
               key=lambda r: float(r.get("dur", 0.0)), default=None)
    total = float(root["dur"]) if root else sum(
        float(r.get("dur", 0.0)) for r in _spans(records)
        if not r.get("parent_id"))
    phases: dict[str, dict] = {}
    for r in _spans(records):
        if not (r.get("attrs") or {}).get("phase"):
            continue
        p = phases.setdefault(r["name"], {"count": 0, "seconds": 0.0})
        p["count"] += 1
        p["seconds"] += float(r["dur"])
    attempts = sorted(_spans(records, "attempt"), key=lambda r: r["ts"])
    # queue wait: submit start -> first attempt start (requeues/retries put
    # later attempts' wait inside the root too, reported via attempts[])
    queue_wait = (attempts[0]["ts"] - root["ts"]) if (root and attempts) \
        else None
    holds = list(_spans(records, "device_hold"))
    token_hold = sum(float(r["dur"]) for r in holds)
    token_wait = 0.0
    acquired = sorted(_events(records, "device_token_acquired"),
                      key=lambda r: r["ts"])
    for h in sorted(holds, key=lambda r: r["ts"]):
        acq = next((e for e in acquired
                    if h["ts"] <= e["ts"] <= h["ts"] + float(h["dur"])), None)
        if acq is not None:
            token_wait += acq["ts"] - h["ts"]
    batches = sorted(_spans(records, "score_batch"),
                     key=lambda r: float(r["dur"]), reverse=True)
    events: dict[str, int] = {}
    for r in _events(records):
        events[r["name"]] = events.get(r["name"], 0) + 1
    worker_spans = list(_spans(records, "isocalc_chunk"))
    return {
        "trace_id": records[0].get("trace_id", "") if records else "",
        "job_id": next((r["job_id"] for r in records if r.get("job_id")), ""),
        "state": (root.get("attrs") or {}).get("state", "") if root else "",
        "total_s": total,
        "phases": {k: {"count": v["count"],
                       "seconds": round(v["seconds"], 6)}
                   for k, v in phases.items()},
        "accounting": {
            "queue_wait_s": round(queue_wait, 6)
            if queue_wait is not None else None,
            "device_token_wait_s": round(token_wait, 6),
            "device_token_hold_s": round(token_hold, 6),
            "compute_s": round(phases.get("score", {}).get("seconds", 0.0), 6),
            # XLA compile split (ISSUE 13): real backend compiles vs
            # persistent-cache loads, from the retrace tracer's `compile`
            # events (analysis/retrace.py) — the cold-start cost this
            # job itself paid, and what a primed cache turned into loads
            "compile_s": round(sum(
                float((r.get("attrs") or {}).get("dur_s", 0.0))
                for r in _events(records, "compile")
                if not (r.get("attrs") or {}).get("cached")), 6),
            "compile_cache_load_s": round(sum(
                float((r.get("attrs") or {}).get("dur_s", 0.0))
                for r in _events(records, "compile")
                if (r.get("attrs") or {}).get("cached")), 6),
            # warm-start attribution (ISSUE 18): jaxpr tracing and MLIR
            # lowering run on every compile-cache miss even when the
            # executable then loads off the persistent cache — the part
            # of a "warm" start the cache cannot remove
            "compile_trace_s": round(sum(
                float((r.get("attrs") or {}).get("dur_s", 0.0))
                for r in _events(records, "compile_trace")), 6),
            "compile_lower_s": round(sum(
                float((r.get("attrs") or {}).get("dur_s", 0.0))
                for r in _events(records, "compile_lower")), 6),
            "isocalc_gen_s": round(sum(
                float(r["dur"]) for r in _spans(records, "isocalc_gen")), 6),
            # submit → first FDR-rankable annotations (the streamed
            # first-results latency, matching sm_slo_first_annotation)
            "first_annotation_s": round(
                min((e["ts"] for e in _events(records, "first_annotation")),
                    default=root["ts"] if root else 0.0)
                - (root["ts"] if root else 0.0), 6)
            if root and any(_events(records, "first_annotation")) else None,
        },
        "attempts": [{
            "attempt": (r.get("attrs") or {}).get("attempt"),
            "seconds": round(float(r["dur"]), 6),
            "timed_out": bool((r.get("attrs") or {}).get("timed_out")),
            "abandoned": bool((r.get("attrs") or {}).get("abandoned")),
        } for r in attempts],
        "slowest_batches": [{
            "seconds": round(float(r["dur"]), 6),
            "backend": (r.get("attrs") or {}).get("backend", ""),
            "ions": (r.get("attrs") or {}).get("ions"),
            "pid": r.get("pid"), "tid": r.get("tid"),
        } for r in batches[:_TOP_BATCHES]],
        "n_batches": len(batches),
        "n_isocalc_worker_spans": len(worker_spans),
        "events": events,
        "n_records": len(records),
    }


def by_replica(records: list[dict]) -> dict:
    """Per-replica attribution (ISSUE 20) from the ISSUE-8 replica stamps.

    A trace that survived a takeover (or had device_kernel spans injected
    by a profiling replica) holds records from several processes; this
    groups the work by WHO ran it.  Records emitted before replica
    identity existed (or by non-service tooling) land under "-".
    """
    out: dict[str, dict] = {}
    for r in records:
        rid = str(r.get("replica") or "-")
        b = out.setdefault(rid, {
            "spans": 0, "events": 0, "seconds": 0.0, "attempts": 0,
            "device_kernel_s": 0.0, "phases": {}, "pids": set(),
        })
        if r.get("pid") is not None:
            b["pids"].add(r["pid"])
        if r.get("kind") == "span":
            b["spans"] += 1
            dur = float(r.get("dur", 0.0))
            b["seconds"] += dur
            if r.get("name") == "attempt":
                b["attempts"] += 1
            elif r.get("name") == "device_kernel":
                b["device_kernel_s"] += dur
            if (r.get("attrs") or {}).get("phase"):
                ph = b["phases"]
                ph[r["name"]] = ph.get(r["name"], 0.0) + dur
        elif r.get("kind") == "event":
            b["events"] += 1
    for b in out.values():
        b["pids"] = sorted(b["pids"])
        b["seconds"] = round(b["seconds"], 6)
        b["device_kernel_s"] = round(b["device_kernel_s"], 6)
        b["phases"] = {k: round(v, 6) for k, v in sorted(b["phases"].items())}
    return out


def render_by_replica(br: dict) -> str:
    lines = ["", "per-replica attribution:"]
    lines.append(f"  {'replica':<14} {'spans':>6} {'events':>7} "
                 f"{'span-s':>10} {'attempts':>8} {'device-s':>10}  phases")
    for rid in sorted(br):
        b = br[rid]
        phases = ", ".join(f"{k}={v:.3f}s" for k, v in b["phases"].items())
        lines.append(f"  {rid:<14} {b['spans']:>6} {b['events']:>7} "
                     f"{b['seconds']:>10.3f} {b['attempts']:>8} "
                     f"{b['device_kernel_s']:>10.3f}  {phases or '-'}")
    return "\n".join(lines)


def _pct(part: float, total: float) -> str:
    return f"{100.0 * part / total:5.1f}%" if total > 0 else "    -"


def render(s: dict) -> str:
    lines = []
    head = f"trace {s['trace_id']}"
    if s["job_id"]:
        head += f" · job {s['job_id']}"
    if s["state"]:
        head += f" · {s['state']}"
    lines.append(head)
    lines.append(f"total (submit → terminal): {s['total_s']:.3f}s over "
                 f"{s['n_records']} records")
    lines.append("")
    lines.append("phase breakdown:")
    total = s["total_s"]
    ordered = [p for p in _PHASE_ORDER if p in s["phases"]]
    ordered += [p for p in sorted(s["phases"]) if p not in ordered]
    for p in ordered:
        v = s["phases"][p]
        lines.append(f"  {p:<22} {v['seconds']:9.3f}s "
                     f"{_pct(v['seconds'], total)}  x{v['count']}")
    if not ordered:
        lines.append("  (no phase spans)")
    lines.append("")
    a = s["accounting"]
    lines.append("accounting (where the wall went):")
    if a["queue_wait_s"] is not None:
        lines.append(f"  queue wait             {a['queue_wait_s']:9.3f}s "
                     f"{_pct(a['queue_wait_s'], total)}")
    lines.append(f"  device-token wait      {a['device_token_wait_s']:9.3f}s "
                 f"{_pct(a['device_token_wait_s'], total)}")
    lines.append(f"  device-token hold      {a['device_token_hold_s']:9.3f}s "
                 f"{_pct(a['device_token_hold_s'], total)}")
    lines.append(f"  compute (score)        {a['compute_s']:9.3f}s "
                 f"{_pct(a['compute_s'], total)}")
    lines.append(f"  xla compile            {a['compile_s']:9.3f}s "
                 f"{_pct(a['compile_s'], total)}")
    lines.append(f"  xla cache loads        {a['compile_cache_load_s']:9.3f}s "
                 f"{_pct(a['compile_cache_load_s'], total)}")
    lines.append(f"  jaxpr trace            "
                 f"{a.get('compile_trace_s', 0.0):9.3f}s "
                 f"{_pct(a.get('compile_trace_s', 0.0), total)}")
    lines.append(f"  mlir lower             "
                 f"{a.get('compile_lower_s', 0.0):9.3f}s "
                 f"{_pct(a.get('compile_lower_s', 0.0), total)}")
    if a.get("first_annotation_s") is not None:
        lines.append(f"  first annotation at    "
                     f"{a['first_annotation_s']:9.3f}s "
                     f"{_pct(a['first_annotation_s'], total)}")
    lines.append(f"  isocalc generation     {a['isocalc_gen_s']:9.3f}s "
                 f"(overlaps other phases)")
    lines.append("")
    if s["attempts"]:
        flags = ", ".join(
            f"#{at['attempt']}: {at['seconds']:.3f}s"
            + (" TIMED-OUT" if at["timed_out"] else "")
            + (" ABANDONED" if at["abandoned"] else "")
            for at in s["attempts"])
        lines.append(f"attempts ({len(s['attempts'])}): {flags}")
    if s["n_batches"]:
        lines.append(f"slowest batches (of {s['n_batches']}):")
        for b in s["slowest_batches"]:
            lines.append(f"  {b['seconds']:9.3f}s  {b['backend']:<16} "
                         f"ions={b['ions']}  pid={b['pid']}")
    lines.append(f"isocalc worker spans: {s['n_isocalc_worker_spans']}")
    if s["events"]:
        lines.append("events: " + ", ".join(
            f"{k} x{v}" for k, v in sorted(s["events"].items())))
    return "\n".join(lines)


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("trace", nargs="?", default=None,
                    help="per-job trace JSONL file")
    ap.add_argument("--url", default=None,
                    help="live service base URL (with --job)")
    ap.add_argument("--job", default=None, help="msg_id to fetch from --url")
    ap.add_argument("--json", action="store_true",
                    help="print the machine-readable summary")
    ap.add_argument("--validate", action="store_true",
                    help="also schema-validate every record (exit 1 on any "
                         "problem) — the trace smoke gate's mode")
    ap.add_argument("--by-replica", action="store_true",
                    help="append the per-replica attribution table (who ran "
                         "each span, incl. injected device_kernel time)")
    args = ap.parse_args(argv)
    if bool(args.url) == bool(args.trace):
        ap.error("give exactly one of TRACE or --url/--job")
    if args.url and not args.job:
        ap.error("--url needs --job")
    records = load_records(args)
    if not records:
        print("trace_report: no records found", file=sys.stderr)
        return 1
    if args.validate:
        problems = tracing.validate_records(records)
        if problems:
            print("trace_report: schema problems:\n  "
                  + "\n  ".join(problems), file=sys.stderr)
            return 1
    summary = summarize(records)
    if args.by_replica:
        summary["by_replica"] = by_replica(records)
    if args.json:
        print(json.dumps(summary, indent=2))
    else:
        out = render(summary)
        if args.by_replica:
            out += render_by_replica(summary["by_replica"])
        print(out)
    return 0


if __name__ == "__main__":
    sys.exit(main())
