#!/usr/bin/env python
"""Compile census gate (ISSUE 12 tentpole; wired into scripts/check_tier1.sh).

Proves the engine's OBSERVED compile surface matches the DECLARED one
(``analysis/surface.py`` COMPILE_SURFACE registries) and is CLOSED under
repeated same-shaped traffic, through the REAL service stack:

1. the spheroid fixture runs through a real in-process service on the
   ``jax_tpu`` backend (single device) with the retrace tracer on — every
   XLA compilation must be attributed to a call site whose module carries
   a ``COMPILE_SURFACE`` registration (**zero unattributed compiles**;
   driver/test frames and ``<external>`` sites fail the gate);
2. a SECOND identical-shape job (new dataset id, same geometry) re-runs —
   it may re-request compiles (fresh backend) but must add **zero new
   signatures**: the signature set is closed, which is exactly the
   property cold-start annihilation (ROADMAP item 1) needs;
3. cross-SIZE closure (ISSUE 13 shape-bucket lattice): a job on a
   DIFFERENT dataset geometry (6x8 px vs 8x8 px) that shares the lattice
   bucket (row_bucket(6) == row_bucket(8) == 8; both peak counts under
   the 4096-slot floor) must add **zero compile events** — every
   executable request resolves as a persistent-cache load
   (``cache_hits`` in the retrace census), proving the signature set is
   closed across dataset SIZES, not just identical shapes;
4. a ``devices: 2`` submit on a virtual 2-chip CPU mesh exercises the
   pjit/shard_map SHARDED path — its compiles must attribute to the
   registered ``parallel/sharded.py`` surface the same way;
4. ``sm_compile_events_total`` / ``sm_compile_signatures`` are live on
   ``/metrics``, and the per-job trace carries ≥1 ``compile`` event (the
   cold compile is visible INSIDE the job that paid for it).

Exit 0 = gate passes.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import urllib.request
from pathlib import Path

# the virtual 2-chip mesh must exist BEFORE jax initializes (same dance as
# multichip_smoke / tests/conftest.py)
os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = [f for f in os.environ.get("XLA_FLAGS", "").split()
          if "xla_force_host_platform_device_count" not in f]
_flags.append("--xla_force_host_platform_device_count=2")
os.environ["XLA_FLAGS"] = " ".join(_flags)

REPO_ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO_ROOT))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

from scripts.load_sweep import Harness, _msg, build_fixtures  # noqa: E402
from sm_distributed_tpu.analysis import retrace, surface  # noqa: E402

N_DEVICES = 2

# site files allowed WITHOUT a COMPILE_SURFACE registration: none.  The
# census is the proof that this list stays empty — a compile attributed to
# scripts/, tests/, engine/, or "<external>" means a jit escaped the
# declared surface.
_SELF = "scripts/compile_census.py"


def fail(msg: str) -> int:
    print(f"compile_census: FAIL — {msg}", file=sys.stderr)
    return 1


def _unattributed(snap: dict) -> list[str]:
    """Observed sites whose module carries no COMPILE_SURFACE entry."""
    out = []
    for site in snap["sites"]:
        path = site.split(":", 1)[0]
        if path == _SELF:
            # the census's own harness frames never dispatch jitted code;
            # seeing one here is itself an attribution bug
            out.append(site)
        elif not surface.is_registered_path(path):
            out.append(site)
    return out


def _sig_set(snap: dict) -> set[tuple[str, str]]:
    return {(site, sig) for site, ent in snap["sites"].items()
            for sig in ent["signatures"]}


def run(work: Path) -> int:
    fx = build_fixtures(work)
    h = Harness(work, "compile_census", sm_overrides={
        "backend": "jax_tpu",
        "service": {"device_pool_size": N_DEVICES},
    })
    retrace.enable()   # harness init already bound the service metrics
    try:
        # ---- phase 1: first job = the cold surface
        retrace.reset()
        status, _hd, body = h.submit(_msg(fx, "fast", "census1"))
        if status != 202:
            return fail(f"submit 1 returned {status}: {body}")
        rows = h.wait_terminal([body["msg_id"]])
        if rows[body["msg_id"]]["state"] != "done":
            return fail(f"job 1 state {rows[body['msg_id']]['state']}: "
                        f"{rows[body['msg_id']]['error']!r}")
        snap1 = retrace.snapshot()
        if snap1["events_total"] == 0:
            return fail("no compile events observed — the tracer saw "
                        "nothing (vacuous census)")
        bad = _unattributed(snap1)
        if bad:
            return fail(
                "unattributed compiles — call sites outside any "
                f"COMPILE_SURFACE-registered module: {sorted(bad)}")

        # ---- phase 2: identical-shape traffic adds ZERO new signatures
        status, _hd, body2 = h.submit(_msg(fx, "fast", "census2"))
        if status != 202:
            return fail(f"submit 2 returned {status}: {body2}")
        rows = h.wait_terminal([body2["msg_id"]])
        if rows[body2["msg_id"]]["state"] != "done":
            return fail(f"job 2 state {rows[body2['msg_id']]['state']}")
        snap2 = retrace.snapshot()
        new_sigs = _sig_set(snap2) - _sig_set(snap1)
        if new_sigs:
            return fail(
                f"signature set NOT closed — a second identical-shape job "
                f"minted {len(new_sigs)} new signature(s): "
                f"{sorted(new_sigs)[:5]}")

        # ---- phase 2b: closure across dataset SIZES sharing a bucket
        # (ISSUE 13): a 6x8 fixture row-buckets to the same 8-row lattice
        # point as the 8x8 one (and both peak counts sit under the
        # 4096-slot floor), so with the persistent cache warm from phase
        # 1 its job must pay ZERO compiles — only cache loads
        from sm_distributed_tpu.io.fixtures import generate_synthetic_dataset

        mid_path, _mid_truth = generate_synthetic_dataset(
            work / "fx_mid", nrows=6, ncols=8, formulas=None,
            present_fraction=0.5, noise_peaks=30, seed=12)
        before = retrace.snapshot()
        msg_x = dict(_msg(fx, "fast", "census_xsize"))
        msg_x["input_path"] = str(mid_path)      # same formulas, new size
        status, _hd, body_x = h.submit(msg_x)
        if status != 202:
            return fail(f"cross-size submit returned {status}: {body_x}")
        rows = h.wait_terminal([body_x["msg_id"]])
        if rows[body_x["msg_id"]]["state"] != "done":
            return fail(f"cross-size job state "
                        f"{rows[body_x['msg_id']]['state']}: "
                        f"{rows[body_x['msg_id']]['error']!r}")
        after = retrace.snapshot()
        new_events = after["events_total"] - before["events_total"]
        new_hits = after["cache_hits_total"] - before["cache_hits_total"]
        if new_events:
            return fail(
                f"signature set NOT closed across dataset sizes: the 6x8 "
                f"job (same bucket as 8x8) paid {new_events} compile(s) "
                f"instead of resolving from the persistent cache")
        if new_hits <= 0:
            return fail(
                "cross-size job neither compiled nor loaded from the "
                "persistent cache — the census saw nothing (vacuous "
                "cross-size stage)")
        print(f"compile_census: cross-size closure OK — 6x8 job resolved "
              f"{new_hits} executable(s) as cache loads, 0 compiles")

        # ---- phase 3: the sharded path attributes the same way
        status, _hd, body3 = h.submit(
            _msg(fx, "fast", "census3", devices=N_DEVICES))
        if status != 202:
            return fail(f"sharded submit returned {status}: {body3}")
        rows = h.wait_terminal([body3["msg_id"]])
        if rows[body3["msg_id"]]["state"] != "done":
            return fail(f"sharded job state {rows[body3['msg_id']]['state']}:"
                        f" {rows[body3['msg_id']]['error']!r}")
        snap3 = retrace.snapshot()
        bad = _unattributed(snap3)
        if bad:
            return fail(f"sharded path: unattributed compiles: {sorted(bad)}")
        sharded_sites = [s for s in snap3["sites"]
                         if s.startswith("sm_distributed_tpu/parallel/")]
        if not sharded_sites:
            return fail("the devices=2 job compiled nothing attributed to "
                        "parallel/ — the sharded surface went unobserved")

        # ---- phase 4: metrics + the compile trace event
        text = h.metrics_text()
        for name in ("sm_compile_events_total", "sm_compile_signatures"):
            if f"\n{name}{{" not in text and not any(
                    ln.startswith(name) for ln in text.splitlines()):
                return fail(f"{name} missing from /metrics")
        with urllib.request.urlopen(
                f"{h.base}/jobs/{body['msg_id']}/trace?raw=1",
                timeout=30.0) as r:
            records = json.loads(r.read())["records"]
        compiles = [rec for rec in records
                    if rec["kind"] == "event" and rec["name"] == "compile"]
        if not compiles:
            return fail("job 1's trace carries no `compile` event — the "
                        "cold compile is invisible to the job that paid it")

        census = {site: {"events": ent["events"],
                         "signatures": len(ent["signatures"])}
                  for site, ent in snap3["sites"].items()}
        print("compile_census: observed surface (site -> events/distinct):")
        for site, ent in sorted(census.items()):
            print(f"  {site}: {ent['events']} events, "
                  f"{ent['signatures']} signature(s)")
        print(f"compile_census: OK — {snap3['events_total']} compiles, "
              f"{snap3['signatures_total']} distinct signatures, all "
              f"attributed to {len(surface.registered())} registered "
              f"surface module(s); closed under repeat traffic; "
              f"{len(compiles)} compile event(s) on the job trace")
    finally:
        h.shutdown()
    return 0


def main() -> int:
    import shutil

    work = Path(tempfile.mkdtemp(prefix="sm_compile_census_"))
    try:
        return run(work)
    finally:
        shutil.rmtree(work, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
