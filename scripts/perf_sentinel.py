#!/usr/bin/env python
"""Perf-regression sentinel over the committed bench history (ISSUE 6).

The BENCH_r01→r05 trajectory (2,040 → 44,184 ions/s) is guarded by nothing:
a PR that halves throughput or triples compile time ships unless a human
happens to eyeball the JSON.  This tool makes the measurement discipline
mechanical:

- **history** = the committed ``BENCH_r*.json`` artifacts (the driver
  wrapper ``{"parsed": {...}}`` or a bare ``bench.py`` JSON line both
  load); a ``trace_report.py --json`` summary is also understood, so a
  service-level trace artifact can be sentineled against prior traces;
- **fresh** = one new artifact of either kind;
- each comparable metric (headline/scale/desi ions/s, ``compile_s``,
  ``isocalc_s``, the pinned per-phase splits, trace phase/accounting
  seconds) is checked against the **median of its history values**:
  rates regress when they fall below ``median * (1 - tolerance)``, times
  when they rise above ``median * (1 + tolerance)``;
- sub-``--min-seconds`` medians are skipped (a 0.02 s isocalc wobbling to
  0.04 s is timer noise, not a regression), as are metrics with fewer than
  ``--min-history`` samples;
- exit codes for CI: 0 = clean, 1 = regression(s), 2 = nothing comparable
  (wrong artifact kind / empty history — a misconfigured gate must not
  pass silently).

``--self-check`` proves the sentinel fires: the newest history artifact is
replayed as an honest fresh run (must pass), then synthetically degraded by
``2 x tolerance`` in the bad direction (must flag regressions).  Wired into
``scripts/check_tier1.sh``.

Usage::

    python scripts/perf_sentinel.py --fresh out.json            # vs BENCH_r*.json
    python scripts/perf_sentinel.py --history 'runs/*.json' --fresh out.json
    python scripts/perf_sentinel.py --fresh trace_summary.json --tolerance 0.4
    python scripts/perf_sentinel.py --self-check
"""

from __future__ import annotations

import argparse
import glob
import json
import sys
from pathlib import Path
from statistics import median

REPO_ROOT = Path(__file__).resolve().parent.parent

# bench-case keys, direction: "up" = higher is better (regression when the
# fresh value drops), "down" = lower is better (regression when it rises)
_BENCH_RATE_KEYS = ("value", "patterns_per_s", "pixels_per_s",
                    "numpy_floor_ions_per_s",
                    # multichip section (ISSUE 7): the N-chip sharded rate
                    # ("value" above), the same-run 1-chip reference, and
                    # the scaling ratio itself are all higher-is-better
                    "single_chip_ions_per_s", "speedup_vs_single_chip",
                    # ISSUE 16: the read-plane mixed cold/warm query rate
                    "reads_per_s",
                    # ISSUE 18: measured fraction of the roofline ceiling —
                    # falling further from the memory-bound floor is the
                    # regression direction
                    "roofline_frac",
                    # ISSUE 20: the profiler-MEASURED roofline (model floor
                    # over per-rep device seconds in the scoring kernels)
                    # and the scoring kernels' share of all captured device
                    # time — both fall when the kernels regress or when
                    # transfers start eating the device
                    "measured_roofline_frac", "kernel_time_frac")
_BENCH_TIME_KEYS = ("compile_s", "isocalc_s", "isocalc_cold_s",
                    "single_chip_compile_s",
                    # ISSUE 13: cleared-cache cold-start pins — the
                    # sentinel band-checks the COLD path, not just the
                    # warm headline
                    "cold_compile_s", "first_annotation_cold_s",
                    # ISSUE 16: read-plane median query latency
                    "read_p50_ms",
                    # ISSUE 18: compacted resident-cube HBM footprint —
                    # quietly growing back toward the f32 baseline is the
                    # regression direction (bytes, well past --min-seconds)
                    "resident_cube_bytes")
# nested bench cases ride along ("multichip" appears on --devices N runs)
_CASE_KEYS = ("scale", "desi", "multichip")


def load_artifact(path: str | Path) -> dict:
    """A bench JSON (bare or driver-wrapped) or trace_report summary."""
    data = json.loads(Path(path).read_text())
    if isinstance(data, dict) and isinstance(data.get("parsed"), dict):
        data = data["parsed"]           # BENCH_r*.json driver wrapper
    if not isinstance(data, dict):
        raise ValueError(f"{path}: artifact is not a JSON object")
    return data


def _num(v) -> float | None:
    return float(v) if isinstance(v, (int, float)) and not isinstance(v, bool) \
        else None


def _norm_bench_case(prefix: str, case: dict, out: dict) -> None:
    for k in _BENCH_RATE_KEYS:
        if (v := _num(case.get(k))) is not None:
            out[f"{prefix}.{k}"] = (v, "up")
    for k in _BENCH_TIME_KEYS:
        if (v := _num(case.get(k))) is not None:
            out[f"{prefix}.{k}"] = (v, "down")
    for phase, v in (case.get("phases") or {}).items():
        if (v := _num(v)) is not None:
            out[f"{prefix}.phases.{phase}"] = (v, "down")


def normalize(data: dict) -> dict[str, tuple[float, str]]:
    """Flatten an artifact into ``{metric: (value, direction)}``.  The
    artifact kinds produce disjoint namespaces (``headline.*``/``scale.*``
    vs ``trace.*`` vs ``analysis.*``), so comparing mismatched kinds yields
    zero comparable metrics — exit 2, not a silent pass."""
    out: dict[str, tuple[float, str]] = {}
    if "value" in data and "metric" in data:          # bench.py line
        _norm_bench_case("headline", data, out)
        for case in _CASE_KEYS:
            if isinstance(data.get(case), dict):
                _norm_bench_case(case, data[case], out)
    elif "total_s" in data or "accounting" in data:   # trace_report --json
        if (v := _num(data.get("total_s"))) is not None:
            out["trace.total_s"] = (v, "down")
        for phase, entry in (data.get("phases") or {}).items():
            if isinstance(entry, dict) and \
                    (v := _num(entry.get("seconds"))) is not None:
                out[f"trace.phases.{phase}"] = (v, "down")
        for k, v in (data.get("accounting") or {}).items():
            if (v := _num(v)) is not None:
                out[f"trace.accounting.{k}"] = (v, "down")
    elif "sm_analysis_findings_total" in data:        # smlint --json (ISSUE 12)
        # rule-count + compile-surface drift series: rising totals are the
        # regression direction (a growing baseline-suppressed count or a
        # quietly widening compile surface), so all are "down" metrics
        for rule, v in (data.get("sm_analysis_findings_total") or {}).items():
            if (v := _num(v)) is not None:
                out[f"analysis.findings.{rule}"] = (v, "down")
        for key in ("sm_compile_surface_sites_total",
                    "sm_compile_surface_entries_total",
                    "sm_compile_surface_modules_total",
                    # ISSUE 15: the numerics-contract census rides the same
                    # drift series — a rising violation count is lint debt,
                    # a quietly growing contract surface is reviewable drift
                    "sm_numerics_contracts_total",
                    "sm_numerics_violations_total"):
            if (v := _num(data.get(key))) is not None:
                out[f"analysis.{key[len('sm_'):]}"] = (v, "down")
    elif "sm_numerics_max_ulp" in data:               # ulp_sentinel (ISSUE 15)
        # per-MSM-component max-ULP drift vs the numpy oracle: RISING
        # drift regresses (the ulp-contract gate for ROADMAP item 3's
        # bf16/int8 compaction); rank mismatches are a hard 0
        for comp, v in (data.get("sm_numerics_max_ulp") or {}).items():
            if (v := _num(v)) is not None:
                out[f"numerics.max_ulp.{comp}"] = (v, "down")
        if (v := _num(data.get("fdr_rank_mismatches"))) is not None:
            out["numerics.fdr_rank_mismatches"] = (v, "down")
        # ISSUE 18: the fused-kernel + bf16-cube path rides the same
        # drift series — rising data-level drift regresses
        for comp, v in (data.get("sm_numerics_max_ulp_fused") or {}).items():
            if (v := _num(v)) is not None:
                out[f"numerics.max_ulp_fused.{comp}"] = (v, "down")
        if (v := _num(data.get("fdr_rank_mismatches_fused"))) is not None:
            out["numerics.fdr_rank_mismatches_fused"] = (v, "down")
    return out


def compare(history: list[dict[str, tuple[float, str]]],
            fresh: dict[str, tuple[float, str]],
            tolerance: float, min_history: int,
            min_seconds: float) -> tuple[list[dict], int]:
    """(findings, n_compared).  A finding is a regression row; metrics are
    compared only where the fresh artifact AND >= min_history history
    entries carry them."""
    findings = []
    n_compared = 0
    for name, (value, direction) in sorted(fresh.items()):
        past = [h[name][0] for h in history if name in h]
        if len(past) < min_history:
            continue
        med = median(past)
        if direction == "down" and med < min_seconds:
            continue                    # sub-noise-floor timing
        n_compared += 1
        if direction == "up":
            bound = med * (1.0 - tolerance)
            bad = value < bound
        else:
            bound = med * (1.0 + tolerance)
            bad = value > bound
        if bad:
            findings.append({
                "metric": name, "value": round(value, 4),
                "median": round(med, 4), "bound": round(bound, 4),
                "direction": direction, "n_history": len(past),
            })
    return findings, n_compared


def run_check(history_paths: list[str], fresh_norm: dict, tolerance: float,
              min_history: int, min_seconds: float,
              label: str, as_json: bool = False) -> int:
    history = []
    for p in history_paths:
        try:
            history.append(normalize(load_artifact(p)))
        except (OSError, ValueError) as exc:
            print(f"perf_sentinel: skipping unreadable history {p}: {exc}",
                  file=sys.stderr)
    findings, n_compared = compare(history, fresh_norm, tolerance,
                                   min_history, min_seconds)
    if as_json:
        print(json.dumps({"label": label, "compared": n_compared,
                          "history_files": len(history),
                          "tolerance": tolerance,
                          "regressions": findings}, indent=2))
    if n_compared == 0:
        print(f"perf_sentinel: {label}: NOTHING COMPARABLE — "
              f"{len(history)} history artifact(s), 0 shared metrics "
              f"with >= {min_history} samples", file=sys.stderr)
        return 2
    if findings:
        print(f"perf_sentinel: {label}: {len(findings)} regression(s) over "
              f"{n_compared} compared metric(s):", file=sys.stderr)
        for f in findings:
            arrow = "<" if f["direction"] == "up" else ">"
            print(f"  {f['metric']}: {f['value']} {arrow} bound "
                  f"{f['bound']} (median {f['median']} of "
                  f"{f['n_history']}, tol {tolerance:.0%})", file=sys.stderr)
        return 1
    print(f"perf_sentinel: {label}: OK — {n_compared} metric(s) within "
          f"±{tolerance:.0%} of the history median")
    return 0


def degrade(norm: dict[str, tuple[float, str]],
            tolerance: float) -> dict[str, tuple[float, str]]:
    """Synthetically regress every metric by 2x the tolerance — the
    self-check artifact that MUST trip the sentinel."""
    out = {}
    for name, (value, direction) in norm.items():
        factor = (1.0 - 2.0 * tolerance) if direction == "up" \
            else (1.0 + 2.0 * tolerance)
        out[name] = (max(0.0, value * factor), direction)
    return out


def self_check(history_paths: list[str], tolerance: float, min_history: int,
               min_seconds: float) -> int:
    """Prove the gate both passes honest runs and fires on regressions."""
    if not history_paths:
        print("perf_sentinel: self-check: no history artifacts found",
              file=sys.stderr)
        return 2
    honest = normalize(load_artifact(history_paths[-1]))
    rc = run_check(history_paths, honest, tolerance, min_history,
                   min_seconds, "self-check honest (latest history replay)")
    if rc != 0:
        print("perf_sentinel: self-check FAILED — the newest committed "
              "artifact does not pass against its own history",
              file=sys.stderr)
        return 1
    rc_bad = run_check(history_paths, degrade(honest, tolerance), tolerance,
                       min_history, min_seconds,
                       "self-check degraded (synthetic regression)")
    if rc_bad != 1:
        print("perf_sentinel: self-check FAILED — a synthetic "
              f"2x-tolerance regression did not trip the sentinel "
              f"(rc={rc_bad})", file=sys.stderr)
        return 1
    print("perf_sentinel: self-check OK — honest history passes, synthetic "
          "regression fires")
    return 0


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--history", default=None,
                    help="glob of history artifacts (default: the repo's "
                         "committed BENCH_r*.json)")
    ap.add_argument("--fresh", default=None,
                    help="the fresh bench.py / trace_report.py --json "
                         "artifact to judge")
    ap.add_argument("--tolerance", type=float, default=0.25,
                    help="allowed fractional drift off the history median "
                         "(default 0.25)")
    ap.add_argument("--min-history", type=int, default=2,
                    help="history samples a metric needs before it is "
                         "compared (default 2)")
    ap.add_argument("--min-seconds", type=float, default=0.05,
                    help="time metrics whose history median is below this "
                         "are timer noise and skipped (default 0.05)")
    ap.add_argument("--json", action="store_true",
                    help="also print a machine-readable comparison")
    ap.add_argument("--self-check", action="store_true",
                    help="replay the newest history artifact (must pass) "
                         "and a synthetically degraded copy (must fail) — "
                         "the CI gate's gate")
    args = ap.parse_args(argv)

    pattern = args.history or str(REPO_ROOT / "BENCH_r*.json")
    history_paths = sorted(glob.glob(pattern))
    if args.self_check:
        if args.fresh:
            ap.error("--self-check takes no --fresh artifact")
        return self_check(history_paths, args.tolerance, args.min_history,
                          args.min_seconds)
    if not args.fresh:
        ap.error("give --fresh ARTIFACT (or --self-check)")
    try:
        fresh = normalize(load_artifact(args.fresh))
    except (OSError, ValueError) as exc:
        print(f"perf_sentinel: cannot load fresh artifact: {exc}",
              file=sys.stderr)
        return 2
    return run_check(history_paths, fresh, args.tolerance, args.min_history,
                     args.min_seconds, f"fresh {args.fresh}",
                     as_json=args.json)


if __name__ == "__main__":
    sys.exit(main())
