#!/usr/bin/env python
"""Crash-recovery chaos sweep (ISSUE 2 tentpole).

Runs the synthetic spheroid fixture end-to-end through the real spool +
scheduler + SearchJob stack, then — for every registered failpoint
(``sm_distributed_tpu/utils/failpoints.py``) — re-runs it with that fault
injected (hard crash, torn write, typed error), restarts, and asserts the
recovery invariants:

- final annotations + all-metrics equal the fault-free golden report
- the job's spool message is neither lost nor duplicated (exactly one copy,
  in ``done/``)
- the sqlite ledger is consistent (no orphaned STARTED rows; newest job
  FINISHED)
- zero tmp/part/heartbeat debris anywhere under the queue, results, and
  work directories, and zero leftover checkpoint shards

Usage::

    python scripts/chaos_sweep.py                # full sweep, every failpoint
    python scripts/chaos_sweep.py --smoke        # 3-scenario CI subset
    python scripts/chaos_sweep.py --only ckpt.shard_write,spool.complete
    python scripts/chaos_sweep.py --list         # registered failpoints
    python scripts/chaos_sweep.py --check-docs   # names unique, documented
                                                 # (docs/RECOVERY.md), covered

Internal subcommands (the sweep's crashable subprocesses):
``--consume-one QUEUE_DIR SM_CONFIG`` drains one job through a JobScheduler;
``--publish-one QUEUE_DIR MSG_JSON`` publishes one message;
``--stream-one QUEUE_DIR SM_CONFIG`` drains one STREAMING job while playing
the instrument (chunked appends + finish) in the same crashable process.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time
from dataclasses import dataclass, field
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO_ROOT))

# import every module hosting an injection seam so the registry is complete
# (engine.index is imported lazily by storage.store, readpath only by the
# server wiring — without these the read-plane failpoints would be invisible)
import sm_distributed_tpu.engine.index  # noqa: F401,E402
import sm_distributed_tpu.engine.stream  # noqa: F401,E402
import sm_distributed_tpu.io.imzml  # noqa: F401,E402
import sm_distributed_tpu.models.msm_basic  # noqa: F401,E402
import sm_distributed_tpu.service.fleet  # noqa: F401,E402
import sm_distributed_tpu.service.readpath  # noqa: F401,E402
import sm_distributed_tpu.service.scheduler  # noqa: F401,E402
from sm_distributed_tpu.engine.daemon import (  # noqa: E402
    QUEUE_ANNOTATE,
    QueueConsumer,
    QueuePublisher,
    _STATES,
)
from sm_distributed_tpu.engine.storage import JobLedger  # noqa: E402
from sm_distributed_tpu.io.fixtures import (  # noqa: E402
    FIXTURE_FORMULAS,
    generate_synthetic_dataset,
)
from sm_distributed_tpu.utils import failpoints  # noqa: E402

CRASH_RC = 21                 # failpoints' default os._exit code
DS_ID = "chaos"
MSG_ID = "chaosmsg"
MAX_RUNS = 6                  # fault run + recovery attempts per scenario

# fixture + engine shaping: small enough that a scenario is seconds, batched
# enough that checkpoint groups, resume, and mid-search faults are real
FIXTURE = dict(nrows=12, ncols=12, formulas=FIXTURE_FORMULAS[:8],
               present_fraction=0.6, noise_peaks=40, mz_jitter_ppm=0.5, seed=7)
SM_TEMPLATE = {
    "backend": "numpy_ref",
    "fdr": {"decoy_sample_size": 8, "seed": 42},
    "parallel": {"formula_batch": 16, "checkpoint_every": 2,
                 "resident_datasets": 0, "order_ions": "table"},
    "storage": {"store_images": False},
    "service": {"workers": 1, "poll_interval_s": 0.05, "job_timeout_s": 60.0,
                "max_attempts": 3, "backoff_base_s": 0.05,
                "backoff_max_s": 0.2, "backoff_jitter": 0.05,
                "heartbeat_interval_s": 0.2, "stale_after_s": 1.0,
                "drain_timeout_s": 10.0, "http_port": 0},
}


@dataclass
class Scenario:
    """One chaos experiment: inject ``spec`` (SM_FAILPOINTS grammar; may arm
    several failpoints to reach a deep seam), crash/fail, restart, converge.
    ``primary`` names the failpoint under test; ``tag`` distinguishes a
    SECOND scenario on the same failpoint (e.g. the ENOSPC variant of a
    seam whose base scenario crashes) — ``key`` is the selection name."""

    primary: str
    phase: str                # "consume" (fault in the worker) | "publish"
    spec: str
    note: str = ""
    tag: str = ""
    # how many consume runs carry the fault env: seams that only execute on
    # RESTART (checkpoint resume) need the fault still armed after the first
    # crash; later runs are always clean so every scenario can converge
    spec_runs: int = 1
    # extra env for the subprocess (all runs): e.g. SM_ISOCALC_CHUNK so the
    # 72-pair fixture generates in several chunks and mid-generation crashes
    # leave a real shard prefix to resume from
    env: dict = field(default_factory=dict)
    # per-scenario SMConfig overrides, deep-merged over SM_TEMPLATE: e.g. a
    # 1s job_timeout_s so the cancel-delivery seam actually executes, or
    # backend=jax_tpu + breaker_threshold=1 for the breaker-open scenario
    sm: dict = field(default_factory=dict)
    # True = converge to a fault-free golden run under THIS scenario's sm
    # overrides (see GoldenCache); False = the base (numpy) golden
    golden_sm: bool = False
    # substring that must appear in the combined run output (beyond the
    # FAILPOINT-FIRED line): scenarios whose proof is an in-process check
    # (e.g. the read-path probe) print a marker the driver asserts on
    expect: str = ""

    @property
    def key(self) -> str:
        return f"{self.primary}+{self.tag}" if self.tag else self.primary


# Every registered failpoint has exactly one scenario (enforced by
# --check-docs and the sweep preamble).  Comments say what each one proves.
SCENARIOS: list[Scenario] = [
    Scenario("io.imzml_parse", "consume", "io.imzml_parse=crash@1",
             "crash mid-parse; restart requeues and re-reads"),
    Scenario("io.ibd_read", "consume", "io.ibd_read=crash@3",
             "crash mid-ingest after some spectra"),
    Scenario("workdir.fetch", "consume", "workdir.fetch=crash@2",
             "crash between staged files; per-file resume refetches the rest"),
    Scenario("workdir.stage_rename", "consume", "workdir.stage_rename=torn@1",
             "torn fetch; size verify rejects it and the retry refetches"),
    Scenario("ckpt.shard_write", "consume",
             "ckpt.shard_write=torn@1;device.score_batch=crash@3",
             "torn committed shard; resume detects the checksum and recomputes"),
    Scenario("ckpt.shard_load", "consume",
             "device.score_batch=crash@2;ckpt.shard_load=raise:OSError@1",
             "shard read error on resume degrades to recompute, not a crash",
             spec_runs=2),   # the load seam only runs on the restart
    Scenario("device.score_batch", "consume", "device.score_batch=crash@2",
             "device preemption mid-search; resume from the shard prefix"),
    Scenario("storage.results_rename", "consume", "storage.results_rename=crash@1",
             "crash before results commit; rerun sweeps the tmp debris"),
    Scenario("storage.index_commit", "consume", "storage.index_commit=crash@1",
             "crash inside the index replace; sqlite rolls back, rerun commits"),
    Scenario("ledger.finish_job", "consume", "ledger.finish_job=crash@1",
             "results durable but job row STARTED; idempotent rerun"),
    Scenario("spool.publish_rename", "publish", "spool.publish_rename=crash@1",
             "publisher dies pre-rename; orphan tmp swept, client republish"),
    Scenario("spool.complete", "consume", "spool.complete=crash@1",
             "job done but message stuck in running/; requeue + idempotent rerun"),
    Scenario("spool.heartbeat", "consume", "spool.heartbeat=raise:OSError@1",
             "heartbeat touch fails; claim survives and the job completes"),
    Scenario("sched.retry_publish", "consume",
             "device.score_batch=raise:RuntimeError@1;sched.retry_publish=crash@1",
             "crash mid retry-republish; stale requeue recovers the claim"),
    # --- isocalc cold-path seams (ISSUE 3; chunked so faults land mid-run)
    Scenario("isocalc.worker", "consume", "isocalc.worker=crash@2",
             "crash mid pattern generation; the committed chunk-shard prefix "
             "survives and the rerun resumes from it",
             env={"SM_ISOCALC_CHUNK": "32"}),
    Scenario("isocalc.shard_save", "consume",
             "isocalc.shard_save=torn@1;isocalc.worker=crash@3",
             "torn committed cache shard; the CRC rejects it on restart and "
             "only that chunk recomputes",
             env={"SM_ISOCALC_CHUNK": "32"}),
    Scenario("isocalc.shard_load", "consume",
             "isocalc.worker=crash@2;isocalc.shard_load=raise:OSError@1",
             "cache shard read error degrades to recompute, not a crash",
             spec_runs=2, env={"SM_ISOCALC_CHUNK": "32"}),
    # --- multi-replica lease/fencing seams (ISSUE 8) -------------------
    Scenario("lease.renew", "consume", "lease.renew=raise:OSError@1",
             "lease renewal I/O fault; the claim survives the beat and the "
             "job completes"),
    Scenario("lease.fence_reject", "consume", "lease.fence_reject=raise@1",
             "simulated peer fence-out at the first write gate; the holder "
             "abandons ALL writes, the claim is recovered and rerun cleanly"),
    Scenario("replica.heartbeat", "consume",
             "replica.heartbeat=raise:OSError@2",
             "registry beat write fault; the replica loop survives and the "
             "job completes (the register-time beat is hit 1)"),
    Scenario("takeover.scan", "consume", "takeover.scan=crash@1",
             "crash inside the startup takeover/orphan scan; restart "
             "re-adopts the shards and drains the spool"),
    # --- overload/cancellation seams (ISSUE 4) -------------------------
    Scenario("sched.cancel_deliver", "consume",
             "sched.cancel_deliver=crash@1;device.score_batch=sleep:5",
             "crash mid-cancellation (attempt timed out, cancel not yet "
             "delivered); restart requeues the claim and reruns cleanly",
             sm={"service": {"job_timeout_s": 1.0, "cancel_grace_s": 2.0}}),
    Scenario("backend.device_error", "consume",
             "backend.device_error=raise:RuntimeError@1",
             "device error opens the breaker mid-job; scoring degrades to "
             "the numpy oracle in place and still matches golden",
             sm={"backend": "jax_tpu",
                 "service": {"breaker_threshold": 1,
                             "breaker_cooldown_s": 0.05}}),
    # --- resource-exhaustion scenarios (ISSUE 10) ----------------------
    Scenario("backend.device_error", "consume",
             "backend.device_error=raise:MemoryError@1",
             "HBM OOM mid-group: batch backoff halves and rescores in "
             "place — no breaker trip, no numpy degrade, golden results",
             tag="oom", golden_sm=True,
             sm={"backend": "jax_tpu",
                 "service": {"breaker_threshold": 1,
                             "breaker_cooldown_s": 0.05}}),
    Scenario("ckpt.shard_write", "consume", "ckpt.shard_write=enospc@1",
             "ENOSPC mid-checkpoint: the attempt fails before a torn "
             "write; the retry rewrites the shard and converges",
             tag="enospc"),
    Scenario("storage.results_rename", "consume",
             "storage.results_rename=enospc@1",
             "ENOSPC at the results commit: tmp debris swept by the "
             "rerun, previous results never clobbered",
             tag="enospc"),
    Scenario("isocalc.shard_save", "consume", "isocalc.shard_save=enospc@1",
             "ENOSPC at a cache-shard commit: the rerun resumes from the "
             "committed shard prefix",
             tag="enospc", env={"SM_ISOCALC_CHUNK": "32"}),
    Scenario("trace.append", "consume", "trace.append=raise:OSError@1",
             "trace-file write fault (ENOSPC family) is swallowed — "
             "observability degrades, the job completes golden"),
    # --- device-fault survival seams (ISSUE 14) ------------------------
    # the exception CLASS at the chip-fault seam selects the taxonomy
    # (models/faults.py): RuntimeError = sticky (chip quarantined out of
    # the 2-chip simulated pool; the retry re-leases the survivor),
    # ConnectionError = transient (retry same chip, no quarantine)
    Scenario("backend.chip_fault", "consume",
             "backend.chip_fault=raise:RuntimeError@1",
             "sticky chip fault mid-job: the chip is quarantined, the "
             "retry re-leases the surviving chip and converges to golden",
             golden_sm=True,
             sm={"backend": "jax_tpu",
                 "service": {"device_pool_size": 2}}),
    Scenario("backend.chip_fault", "consume",
             "backend.chip_fault=raise:ConnectionError@1",
             "transient chip fault (collective-timeout class): retry on "
             "the SAME chip after backoff — no quarantine, no breaker "
             "count, golden results",
             tag="transient", golden_sm=True,
             sm={"backend": "jax_tpu",
                 "service": {"device_pool_size": 2}}),
    Scenario("device.probe", "consume", "device.probe=raise:OSError@1",
             "fault during the lease-time health probe: the probed chip "
             "is quarantined BEFORE the job touches it; the grant retries "
             "on the survivor and the job completes golden",
             golden_sm=True,
             sm={"backend": "jax_tpu",
                 "service": {"device_pool_size": 2}}),
    # --- elastic-fleet drain seams (ISSUE 11) --------------------------
    # SM_CHAOS_DRAIN=1 makes the consume subprocess request a drain on
    # ITSELF once a claim exists, driving the zero-loss drain protocol
    # through the same scheduler a fleet controller would
    Scenario("drain.handoff", "consume", "drain.handoff=crash@1",
             "victim killed mid-drain while holding a claim; takeover "
             "fences + requeues it and the work completes exactly once",
             env={"SM_CHAOS_DRAIN": "1"},
             # fast replica-loop ticks: the drain is noticed (and the crash
             # lands) while the claim is demonstrably still in flight
             sm={"service": {"replica_heartbeat_interval_s": 0.1,
                             "takeover_interval_s": 0.1}}),
    Scenario("fleet.retire_ack", "consume", "fleet.retire_ack=crash@1",
             "drained replica dies before its retire ack; the job is "
             "already terminal — the controller falls back to process-exit "
             "evidence and nothing is lost or doubled",
             env={"SM_CHAOS_DRAIN": "1"},
             sm={"service": {"replica_heartbeat_interval_s": 0.1,
                             "takeover_interval_s": 0.1}}),
    Scenario("fleet.spawn", "fleet", "fleet.spawn=crash@1",
             "fleet controller killed mid-spawn (no replica launched); the "
             "restarted controller repairs the fleet and the job completes "
             "exactly once"),
    # --- pod-layer seams (ISSUE 17) ------------------------------------
    # SM_DIST_SIMULATE=1 exercises the whole managed multi-host init path
    # (settings resolution, retry ladder, identity) without a real
    # coordinator — the raise at the first attempt is the coordinator-not-
    # yet-up launch race; the backoff ladder retries and the job completes
    # on the (simulated) pod runtime.  The real 2-process init is covered
    # by tests/test_distributed.py.
    Scenario("dist.initialize", "consume",
             "dist.initialize=raise:ConnectionError@1",
             "multi-host init loses the coordinator launch race; the "
             "backoff ladder retries and converges to golden",
             golden_sm=True,
             env={"SM_DIST_SIMULATE": "1",
                  "SM_COORDINATOR": "127.0.0.1:12355",
                  "SM_NUM_PROCESSES": "2", "SM_PROCESS_ID": "0"},
             sm={"backend": "jax_tpu",
                 "parallel": {"init_backoff_s": 0.01}}),
    Scenario("host.heartbeat", "consume", "host.heartbeat=raise:OSError@1",
             "heartbeat-read fault inside the host watchdog's freshness "
             "pass: remote beats count as missed for that pass but the "
             "replica loop survives and the job completes golden "
             "(whole-host eviction itself is proven by scripts/"
             "host_chaos.py)",
             sm={"service": {"host_watchdog_interval_s": 0.05,
                             "host_stale_after_s": 0.5}}),
    # --- result read-plane seams (ISSUE 16) ----------------------------
    Scenario("index.segment_commit", "consume",
             "index.segment_commit=crash@1",
             "crash between the read-segment tmp write and its atomic "
             "swap: readers keep the previous complete segment (never a "
             "torn one), the rerun republishes and sweeps the tmp"),
    # SM_CHAOS_READ=1 makes the consume subprocess drive the governed read
    # path over the freshly published segment IN the faulted process: the
    # cache-fill fault must degrade to a source read, never a failed GET
    Scenario("read.cache_fill", "consume", "read.cache_fill=raise:OSError@1",
             "cache-fill fault on the first read: the read still answers "
             "from the source segment and the retry warms the cache",
             env={"SM_CHAOS_READ": "1"}, expect="CHAOS-READ-OK"),
    # --- live-acquisition streaming seams (ISSUE 19) -------------------
    # phase "stream": the crashable subprocess claims a mode=stream job
    # AND plays the instrument, appending the fixture's spectra in 3
    # chunks + finish; every restart replays all chunks from seq 0, so
    # the duplicate-delivery (lost-ack) path is exercised on EVERY
    # recovery and exactly-once is proven by golden equality (a doubled
    # pixel would change the scores)
    Scenario("stream.chunk_append", "stream", "stream.chunk_append=crash@2",
             "crash between the chunk tmp write and its rename "
             "mid-acquisition; the unacked chunk is re-posted after "
             "restart, lands exactly once, and the stream converges to "
             "the batch golden",
             sm={"service": {"stream": {"idle_timeout_s": 60.0,
                                        "poll_interval_s": 0.05}}}),
    Scenario("stream.manifest_commit", "stream",
             "stream.manifest_commit=crash@2",
             "crash after the chunk rename but before the manifest commit "
             "(the lost-ack window); the duplicate re-delivery after "
             "restart overwrites the stranded file idempotently — "
             "exactly once, no doubled pixels",
             sm={"service": {"stream": {"idle_timeout_s": 60.0,
                                        "poll_interval_s": 0.05}}}),
    Scenario("stream.finish", "stream", "stream.finish=crash@1",
             "crash inside finish before the finished flag commits; the "
             "re-posted finish is idempotent and the one-shot batch "
             "scoring runs exactly once",
             sm={"service": {"stream": {"idle_timeout_s": 60.0,
                                        "poll_interval_s": 0.05}}}),
]

SMOKE = ("ckpt.shard_write", "spool.complete", "storage.results_rename")


# --------------------------------------------------------------- subcommands
def cmd_consume_one(queue_dir: str, sm_config_path: str) -> int:
    """Drain one job through the real service scheduler (crashable)."""
    # lock-order detection (ISSUE 9): the driver arms SM_LOCK_ORDER=raise,
    # so every consumer child runs its scheduler/job stack instrumented —
    # an acquisition-order cycle raises mid-job and fails the scenario.
    # Enabled BEFORE the service imports so instance locks are in scope.
    from sm_distributed_tpu.analysis import lockorder

    lockorder.enable_from_env()
    from sm_distributed_tpu.engine.daemon import annotate_callback
    from sm_distributed_tpu.service.scheduler import JobScheduler
    from sm_distributed_tpu.utils.config import SMConfig

    sm = SMConfig.set_path(sm_config_path)
    # trace files on (ISSUE 10): the trace.append seam only executes when
    # per-job JSONL sinks exist, and every scenario proving convergence
    # WITH tracing active is strictly stronger than without
    sched = JobScheduler(queue_dir, annotate_callback(sm), config=sm.service,
                         trace_dir=sm.trace_dir)
    sched.start()
    drain_mode = os.environ.get("SM_CHAOS_DRAIN") == "1"
    if drain_mode:
        # elastic-fleet drain seams (ISSUE 11): once this replica holds a
        # claim, ask it to drain — exactly what a fleet controller's
        # scale-down does — so drain.handoff / fleet.retire_ack execute
        # with real in-flight work
        deadline = time.time() + 30.0
        while time.time() < deadline and sched.live_claims() == 0:
            time.sleep(0.02)
        sched.registry.request_drain(sched.replica_id, by="chaos")
    ok = sched.wait_for_terminal(1, timeout_s=60.0)
    if ok and os.environ.get("SM_CHAOS_READ") == "1":
        # read-plane chaos (ISSUE 16): query the just-published segment
        # twice through a real ReadPath while the cache-fill seam is
        # faulted — both reads MUST answer (the fill failure only costs
        # cache warmth); the driver asserts on the CHAOS-READ-OK marker
        from sm_distributed_tpu.service.readpath import ReadPath

        rp = ReadPath(sm.storage.results_dir, sm.service.read)
        body = None
        for _ in range(2):
            status, body, _hdrs = rp.handle_annotations(DS_ID, {})
            if status != 200:
                print(f"CHAOS-READ-FAIL status={status} body={body}",
                      flush=True)
                sched.shutdown()
                return 4
        print(f"CHAOS-READ-OK rows={body['total']}", flush=True)
    if drain_mode:
        # hold the process open through the ack so the fleet.retire_ack
        # seam executes before shutdown tears the replica loop down
        deadline = time.time() + 15.0
        while time.time() < deadline and not sched.drain_complete():
            time.sleep(0.05)
    sched.shutdown()
    return 0 if ok else 3


def cmd_fleet_one(queue_dir: str, sm_config_path: str) -> int:
    """Drain one job through a FleetController-supervised replica: the
    controller (THIS process — crashable at ``fleet.spawn``) spawns one
    ``--consume-one`` subprocess as its fleet and waits for the job."""
    from sm_distributed_tpu.analysis import lockorder

    lockorder.enable_from_env()
    from sm_distributed_tpu.service.fleet import FleetController
    from sm_distributed_tpu.utils.config import FleetConfig, SMConfig

    sm = SMConfig.set_path(sm_config_path)
    root = Path(queue_dir) / QUEUE_ANNOTATE

    def _spawn(rid: str) -> subprocess.Popen:
        # the child is a plain consume-one replica; it inherits the armed
        # spec harmlessly (it never reaches the controller's spawn seam)
        return subprocess.Popen(
            [sys.executable, str(Path(__file__).resolve()),
             "--consume-one", queue_dir, sm_config_path],
            cwd=str(REPO_ROOT))

    fc = FleetController(
        queue_dir, FleetConfig(min_replicas=1, max_replicas=1,
                               decide_interval_s=0.2, cooldown_s=0.0,
                               hysteresis_ticks=1, spawn_timeout_s=30.0,
                               drain_timeout_s=10.0),
        sm.service, spawn=_spawn)
    fc.start()
    try:
        deadline = time.time() + 90.0
        while time.time() < deadline:
            if list((root / "done").glob("*.json")):
                return 0
            time.sleep(0.1)
        return 3
    finally:
        fc.shutdown(drain=False, timeout_s=5.0)


def cmd_stream_one(queue_dir: str, sm_config_path: str) -> int:
    """Drain one STREAMING job: the scheduler claims the mode=stream
    message while THIS process (crashable at the stream.* seams) plays
    the instrument — appending the fixture's spectra chunk by chunk into
    the chunk log, then posting finish.  Each restart replays every chunk
    from seq 0: the duplicate-delivery path the CRC idempotency absorbs."""
    from sm_distributed_tpu.analysis import lockorder

    lockorder.enable_from_env()
    import threading

    from sm_distributed_tpu.engine.daemon import annotate_callback
    from sm_distributed_tpu.engine.stream import StreamIngest, stream_root
    from sm_distributed_tpu.io.imzml import ImzMLReader
    from sm_distributed_tpu.service.scheduler import JobScheduler
    from sm_distributed_tpu.utils.config import SMConfig

    sm = SMConfig.set_path(sm_config_path)
    sched = JobScheduler(queue_dir, annotate_callback(sm), config=sm.service,
                         trace_dir=sm.trace_dir)
    sched.start()

    def _feed():
        with ImzMLReader(os.environ["SM_CHAOS_STREAM_SRC"]) as rd:
            coords = rd.coordinates.tolist()
            spectra = [rd.read_spectrum(i) for i in range(rd.n_spectra)]
        n = len(coords)
        edges = [0, n // 3, 2 * n // 3, n]
        ingest = StreamIngest(stream_root(sm))
        for seq in range(3):
            lo, hi = edges[seq], edges[seq + 1]
            ingest.append_chunk(DS_ID, seq, coords[lo:hi], spectra[lo:hi])
            time.sleep(0.2)    # let a provisional re-rank start in between
        ingest.finish(DS_ID)

    threading.Thread(target=_feed, daemon=True).start()
    ok = sched.wait_for_terminal(1, timeout_s=120.0)
    sched.shutdown()
    return 0 if ok else 3


def cmd_publish_one(queue_dir: str, msg_path: str) -> int:
    msg = json.loads(Path(msg_path).read_text())
    QueuePublisher(queue_dir).publish(msg)
    return 0


# ------------------------------------------------------------------- driver
def _sub_env(spec: str | None, extra: dict | None = None) -> dict:
    env = dict(os.environ)
    env.pop("SM_FAILPOINTS", None)
    if spec:
        env["SM_FAILPOINTS"] = spec
    # children run the lock-order detector in raise mode (ISSUE 9): a
    # cycle anywhere in the instrumented scheduler stack fails the
    # scenario instead of lurking until a production interleaving
    env.setdefault("SM_LOCK_ORDER", "raise")
    if extra:
        env.update(extra)
    return env


def _run_sub(args: list[str], spec: str | None,
             extra_env: dict | None = None) -> tuple[int, str]:
    proc = subprocess.run(
        [sys.executable, str(Path(__file__).resolve()), *args],
        env=_sub_env(spec, extra_env), capture_output=True, text=True,
        timeout=240, cwd=str(REPO_ROOT))
    return proc.returncode, proc.stdout + proc.stderr


def _deep_merge(base: dict, over: dict) -> dict:
    out = dict(base)
    for k, v in over.items():
        if isinstance(v, dict) and isinstance(out.get(k), dict):
            out[k] = _deep_merge(out[k], v)
        else:
            out[k] = v
    return out


@dataclass
class Context:
    """Per-scenario sandbox: its own spool, results, and work dirs."""

    base: Path
    msg: dict
    sm_overrides: dict = field(default_factory=dict)
    sm_conf: Path = field(init=False)
    queue_dir: Path = field(init=False)
    root: Path = field(init=False)
    results: Path = field(init=False)
    work: Path = field(init=False)

    def __post_init__(self):
        self.queue_dir = self.base / "queue"
        self.root = self.queue_dir / QUEUE_ANNOTATE
        self.results = self.base / "results"
        self.work = self.base / "work"
        self.base.mkdir(parents=True, exist_ok=True)
        sm = _deep_merge(json.loads(json.dumps(SM_TEMPLATE)),
                         self.sm_overrides)
        sm["work_dir"] = str(self.work)
        sm["storage"]["results_dir"] = str(self.results)
        self.sm_conf = self.base / "sm.json"
        self.sm_conf.write_text(json.dumps(sm, indent=2))

    def done_msg(self) -> Path:
        return self.root / "done" / f"{MSG_ID}.json"

    def recover(self) -> None:
        """What an operator/orchestrator does after a process death: requeue
        dead claims, sweep orphan tmps, redrive dead letters, reconcile the
        ledger.  Every step is also what the daemon does on startup, with the
        age gates at zero because the crashed process is known dead."""
        consumer = QueueConsumer(self.queue_dir, callback=None)
        consumer.requeue_stale(max_age_s=0.0)
        consumer.sweep_orphans(max_age_s=0.0)
        # lease/registry debris from a crashed scheduler (ISSUE 8): orphan
        # leases and torn tmp writes have no live writer after the crash
        from sm_distributed_tpu.service.leases import LeaseStore

        LeaseStore(self.root, "recovery").sweep_orphans(
            self.root, max_age_s=0.0)
        for p in (self.root / "failed").glob("*.json"):
            msg = json.loads(p.read_text())
            for k in ("error", "traceback", "attempts", "service"):
                msg.pop(k, None)
            (self.root / "pending" / p.name).write_text(json.dumps(msg, indent=2))
            p.unlink()
        if (self.results / "engine.sqlite").exists():
            ledger = JobLedger(self.results)
            ledger.fail_stale_started(DS_ID)
            ledger.close()


def _read_report(results: Path) -> tuple:
    import pandas as pd

    out = []
    for name in ("annotations.parquet", "all_metrics.parquet"):
        df = pd.read_parquet(results / DS_ID / name)
        out.append(df.sort_values(["sf", "adduct"]).reset_index(drop=True))
    return tuple(out)


def _assert_frames_equal(got, want, label: str, errs: list[str]) -> None:
    import pandas as pd

    try:
        pd.testing.assert_frame_equal(got, want, rtol=1e-9, atol=1e-12)
    except AssertionError as e:
        errs.append(f"{label} differs from golden: {str(e).splitlines()[-1]}")


def _debris(paths: list[Path]) -> list[str]:
    out = []
    for base in paths:
        if not base.exists():
            continue
        for p in base.rglob("*"):
            n = p.name
            if ".tmp" in n or n.endswith((".part", ".hb")) or ".ckpt." in n:
                out.append(str(p))
    return out


def check_invariants(ctx: Context, golden) -> list[str]:
    errs: list[str] = []
    msgs = {s: sorted(p.name for p in (ctx.root / s).glob("*.json"))
            for s in _STATES}
    total = sum(len(v) for v in msgs.values())
    if msgs["done"] != [f"{MSG_ID}.json"] or total != 1:
        errs.append(f"spool message lost/duplicated: {msgs}")
    debris = _debris([ctx.root, ctx.results, ctx.work])
    if debris:
        errs.append(f"tmp/heartbeat/checkpoint debris: {debris}")
    ledger = JobLedger(ctx.results)
    try:
        jobs = ledger.jobs(DS_ID)
        if jobs.empty:
            errs.append("ledger has no job rows")
        else:
            if (jobs.status == "STARTED").any():
                errs.append(f"ledger kept STARTED rows: {jobs.status.tolist()}")
            if jobs.iloc[-1].status != "FINISHED":
                errs.append(f"newest job not FINISHED: {jobs.status.tolist()}")
        idx_rows = ledger._conn.execute(
            "SELECT COUNT(*) FROM annotation WHERE ds_id=?", (DS_ID,)).fetchone()[0]
        if idx_rows != len(golden[0]):
            errs.append(f"index has {idx_rows} rows, golden {len(golden[0])}")
    finally:
        ledger.close()
    # read-segment invariant (ISSUE 16): after convergence the dataset's
    # columnar read segment must exist, load cleanly (readers can never
    # see a torn file under the atomic-swap protocol), and carry exactly
    # the golden row count
    from sm_distributed_tpu.engine.index import (SEGMENT_NAME, SegmentError,
                                                 _load_file)

    seg_path = ctx.results / DS_ID / SEGMENT_NAME
    if not seg_path.exists():
        errs.append("no published read segment")
    else:
        try:
            seg = _load_file(seg_path)
            if seg.n_rows != len(golden[0]):
                errs.append(f"read segment has {seg.n_rows} rows, "
                            f"golden {len(golden[0])}")
        except SegmentError as exc:
            errs.append(f"torn/unreadable read segment: {exc}")
    got = _read_report(ctx.results)
    _assert_frames_equal(got[0], golden[0], "annotations", errs)
    _assert_frames_equal(got[1], golden[1], "all_metrics", errs)
    return errs


def run_scenario(sc: Scenario, base: Path, msg: dict, golden,
                 verbose: bool = False) -> dict:
    ctx = Context(base / sc.key.replace(".", "_").replace("+", "_"),
                  msg, sc.sm)
    outputs: list[str] = []
    result = {"scenario": sc.key, "spec": sc.spec, "runs": 0, "ok": False}

    env = dict(sc.env)
    if sc.phase == "stream":
        # the subprocess plays the instrument from the fixture file; the
        # spooled message itself carries only the stream:// sentinel
        env["SM_CHAOS_STREAM_SRC"] = msg["input_path"]
        msg = dict(msg, mode="stream", input_path=f"stream://{DS_ID}")

    if sc.phase == "publish":
        msg_file = ctx.base / "msg.json"
        msg_file.write_text(json.dumps(msg))
        rc, out = _run_sub(
            ["--publish-one", str(ctx.queue_dir), str(msg_file)], sc.spec,
            sc.env)
        outputs.append(out)
        if rc != CRASH_RC:
            result["error"] = f"publisher expected crash rc={CRASH_RC}, got {rc}"
            return result
        consumer = QueueConsumer(ctx.queue_dir, callback=None)
        if consumer.sweep_orphans(max_age_s=0.0) < 1:
            result["error"] = "crashed publish left no orphan tmp to sweep"
            return result
        QueuePublisher(ctx.queue_dir).publish(msg)   # the client's retry
    else:
        QueuePublisher(ctx.queue_dir).publish(msg)

    while result["runs"] < MAX_RUNS:
        armed = sc.phase in ("consume", "fleet", "stream") and \
            result["runs"] < sc.spec_runs
        spec = sc.spec if armed else None
        sub = {"fleet": "--fleet-one",
               "stream": "--stream-one"}.get(sc.phase, "--consume-one")
        rc, out = _run_sub(
            [sub, str(ctx.queue_dir), str(ctx.sm_conf)], spec,
            env)
        outputs.append(out)
        result["runs"] += 1
        if verbose:
            print(f"  run {result['runs']}: rc={rc}")
        if ctx.done_msg().exists():
            break
        ctx.recover()
    else:
        result["error"] = f"did not converge within {MAX_RUNS} runs"
        result["output_tail"] = outputs[-1][-2000:]
        return result

    blob = "".join(outputs)
    if f"FAILPOINT-FIRED name={sc.primary}" not in blob:
        result["error"] = f"failpoint {sc.primary} never fired"
        return result
    if sc.expect and sc.expect not in blob:
        result["error"] = f"expected marker {sc.expect!r} never appeared"
        result["output_tail"] = outputs[-1][-2000:]
        return result
    # one final operator pass so crash-specific ledger rows are reconciled
    ctx.recover()
    errs = check_invariants(ctx, golden)
    if errs:
        result["error"] = "; ".join(errs)
        result["output_tail"] = outputs[-1][-2000:]
        return result
    result["ok"] = True
    return result


def build_fixture(base: Path) -> dict:
    fx_dir = base / "fixture"
    imzml_path, truth = generate_synthetic_dataset(fx_dir, **FIXTURE)
    return {
        "ds_id": DS_ID, "ds_name": DS_ID, "msg_id": MSG_ID,
        "input_path": str(imzml_path),
        "formulas": truth.formulas,
        "ds_config": {"isotope_generation": {"adducts": ["+H"]},
                      "image_generation": {"ppm": 3.0}},
    }


def run_golden(base: Path, msg: dict, sm_overrides: dict | None = None,
               name: str = "golden"):
    ctx = Context(base / name, msg, sm_overrides or {})
    QueuePublisher(ctx.queue_dir).publish(msg)
    rc, out = _run_sub(
        ["--consume-one", str(ctx.queue_dir), str(ctx.sm_conf)], None)
    if rc != 0 or not ctx.done_msg().exists():
        raise RuntimeError(f"golden (fault-free) run failed rc={rc}:\n{out[-3000:]}")
    return _read_report(ctx.results)


class GoldenCache:
    """Fault-free reports keyed by a scenario's SMConfig overrides, for
    scenarios that opt in with ``golden_sm=True``: one that completes on a
    CHANGED scoring config (the OOM backoff stays on the jax backend) must
    converge to the fault-free report of that same config — the float32
    device pipeline and the float64 numpy oracle agree only to ~1e-7, far
    looser than the 1e-9 golden-equality gate.  The breaker scenario
    deliberately stays on the base golden: its degrade path IS numpy."""

    def __init__(self, base: Path, msg: dict, default):
        self.base = base
        self.msg = msg
        self._by_key: dict[str, tuple] = {"": default}

    def for_scenario(self, sc: Scenario):
        if not sc.golden_sm:
            return self._by_key[""]
        key = json.dumps(sc.sm, sort_keys=True)
        if key not in self._by_key:
            name = "golden_" + sc.key.replace(".", "_").replace("+", "_")
            self._by_key[key] = run_golden(self.base, self.msg, sc.sm, name)
        return self._by_key[key]


def run_sweep(work: Path, only: list[str] | None = None,
              verbose: bool = False) -> list[dict]:
    os.environ.pop("SM_FAILPOINTS", None)   # the driver must never crash
    failpoints.reset()
    registered = set(failpoints.registered_failpoints())
    primaries = {sc.primary for sc in SCENARIOS}
    uncovered = registered - primaries
    if uncovered:
        raise RuntimeError(f"registered failpoints without a chaos scenario: "
                           f"{sorted(uncovered)}")
    scenarios = SCENARIOS if only is None else [
        sc for sc in SCENARIOS if sc.key in only]
    if only is not None:
        known = {sc.key for sc in SCENARIOS}
        unknown = [name for name in only if name not in known]
        if unknown:
            raise RuntimeError(f"unknown scenario names {unknown} "
                               f"(valid: {sorted(known)})")
    work.mkdir(parents=True, exist_ok=True)
    msg = build_fixture(work)
    t0 = time.time()
    golden = run_golden(work, msg)
    goldens = GoldenCache(work, msg, golden)
    print(f"golden report: {len(golden[0])} annotations, "
          f"{len(golden[1])} scored ions ({time.time() - t0:.1f}s)")
    results = []
    for sc in scenarios:
        t0 = time.time()
        r = run_scenario(sc, work, msg, goldens.for_scenario(sc),
                         verbose=verbose)
        r["seconds"] = round(time.time() - t0, 1)
        status = "OK " if r["ok"] else "FAIL"
        print(f"[{status}] {sc.key:<24} runs={r['runs']} "
              f"{r['seconds']:>5.1f}s  {sc.note}")
        if not r["ok"]:
            print(f"       spec: {sc.spec}\n       error: {r.get('error')}")
            if verbose and r.get("output_tail"):
                print(r["output_tail"])
        results.append(r)
    n_ok = sum(r["ok"] for r in results)
    print(f"chaos sweep: {n_ok}/{len(results)} scenarios converged to golden")
    return results


# ---------------------------------------------------------------- doc check
def check_docs(doc_path: Path | None = None) -> list[str]:
    """SUPERSEDED by the smlint ``failpoint-registry`` rule (ISSUE 9,
    docs/ANALYSIS.md): documentation coverage, dead entries, and unresolved
    call sites are now checked by the shared static implementation, which
    this gate delegates to so the sweep CLI and ``scripts/smlint.py`` can
    never disagree.  Kept here on top: the RUNTIME cross-check between the
    imported failpoint registry and this module's scenario table (the
    static rule only sees source text, not what actually registered)."""
    from sm_distributed_tpu.analysis.core import Project, run_lint

    proj = Project.load(REPO_ROOT, ["sm_distributed_tpu", "scripts"])
    if doc_path is not None:
        p = Path(doc_path)
        proj.aux["docs/RECOVERY.md"] = p.read_text() if p.exists() else ""
    result = run_lint(proj, only={"failpoint-registry"})
    errs = [f.render() for f in result.new]
    # runtime registry <-> scenario table cross-check
    registered = set(failpoints.registered_failpoints())
    primaries = {sc.primary for sc in SCENARIOS}
    for name in sorted(registered - primaries):
        errs.append(f"failpoint {name} has no chaos scenario")
    for name in sorted(primaries - registered):
        errs.append(f"scenario {name} names an unregistered failpoint")
    return errs


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--work", default=None,
                    help="sweep directory (default: a fresh temp dir)")
    ap.add_argument("--smoke", action="store_true",
                    help=f"fast CI subset: {', '.join(SMOKE)}")
    ap.add_argument("--only", default=None,
                    help="comma-separated scenario (failpoint) names")
    ap.add_argument("--list", action="store_true", dest="list_fps")
    ap.add_argument("--check-docs", action="store_true")
    ap.add_argument("--keep", action="store_true",
                    help="keep the sweep directory for inspection")
    ap.add_argument("-v", "--verbose", action="store_true")
    ap.add_argument("--consume-one", nargs=2, metavar=("QUEUE_DIR", "SM_CONFIG"))
    ap.add_argument("--publish-one", nargs=2, metavar=("QUEUE_DIR", "MSG_JSON"))
    ap.add_argument("--fleet-one", nargs=2, metavar=("QUEUE_DIR", "SM_CONFIG"))
    ap.add_argument("--stream-one", nargs=2, metavar=("QUEUE_DIR", "SM_CONFIG"))
    args = ap.parse_args(argv)

    if args.consume_one:
        return cmd_consume_one(*args.consume_one)
    if args.publish_one:
        return cmd_publish_one(*args.publish_one)
    if args.fleet_one:
        return cmd_fleet_one(*args.fleet_one)
    if args.stream_one:
        return cmd_stream_one(*args.stream_one)
    if args.list_fps:
        for name, desc in sorted(failpoints.registered_failpoints().items()):
            print(f"{name:<26} {desc}")
        return 0
    if args.check_docs:
        errs = check_docs()
        for e in errs:
            print(f"check-docs: {e}", file=sys.stderr)
        print(f"check-docs: {'FAIL' if errs else 'OK'} "
              f"({len(failpoints.registered_failpoints())} failpoints)")
        return 1 if errs else 0

    only = list(SMOKE) if args.smoke else (
        args.only.split(",") if args.only else None)
    import shutil
    import tempfile

    work = Path(args.work) if args.work else Path(
        tempfile.mkdtemp(prefix="sm_chaos_"))
    try:
        results = run_sweep(work, only=only, verbose=args.verbose)
    finally:
        if not args.keep and args.work is None:
            shutil.rmtree(work, ignore_errors=True)
    return 0 if all(r["ok"] for r in results) else 1


if __name__ == "__main__":
    sys.exit(main())
