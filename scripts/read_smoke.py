#!/usr/bin/env python
"""Read-path smoke gate (ISSUE 16; wired into check_tier1.sh).

Annotates the synthetic spheroid fixture through the REAL in-process
annotation service (ion images stored), then proves the production read
plane end to end over HTTP:

1. ``GET /datasets`` lists the published dataset with its publish
   metadata;
2. a cold annotation query misses the cache and answers from the
   columnar segment; the identical warm query is a cache **hit**
   (``sm_read_cache_hits_total`` moves) and 20 warm repeats hold
   **p50 < 50 ms**;
3. the query result matches a brute-force pandas scan of the stored
   ``annotations.parquet`` — same rows, same msm ordering (the segment
   is a projection of the parquet, never a divergent copy);
4. ``GET /datasets/<id>/images/<sf|adduct>`` returns bytes bit-identical
   to a direct ``engine/png.py`` render of the stored npz array;
5. ``GET /slo`` carries the ``read`` SLI with live attainment;
6. a cross-dataset cohort query answers for the fixture's top formula.

Exit 0 = gate passes.
"""

from __future__ import annotations

import json
import sys
import tempfile
import time
import urllib.parse
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO_ROOT))

from scripts.load_sweep import (  # noqa: E402
    Harness,
    _http_raw,
    _msg,
    build_fixtures,
)

WARM_REPEATS = 20
WARM_P50_BOUND_S = 0.050


def fail(msg: str) -> int:
    print(f"read_smoke: FAIL — {msg}", file=sys.stderr)
    return 1


def _get_json(base: str, path: str):
    status, _hd, raw = _http_raw(base, path)
    return status, json.loads(raw)


def run(work: Path) -> int:
    fx = build_fixtures(work)
    h = Harness(work, "read_smoke",
                sm_overrides={"storage": {"store_images": True}})
    try:
        # ---- annotate through the real service --------------------------
        status, _hd, body = h.submit(_msg(fx, "fast", "spheroid"))
        if status != 202:
            return fail(f"submit returned {status}: {body}")
        rows = h.wait_terminal([body["msg_id"]])
        if rows[body["msg_id"]]["state"] != "done":
            return fail(f"annotate job: {rows[body['msg_id']]}")

        # ---- 1. dataset listing ----------------------------------------
        status, listing = _get_json(h.base, "/datasets")
        if status != 200 or [d["ds_id"] for d in listing["datasets"]] \
                != ["spheroid"]:
            return fail(f"/datasets: {status} {listing}")
        if listing["datasets"][0]["n_rows"] < 1:
            return fail(f"empty published segment: {listing}")

        # ---- 2. cold miss, warm hit, warm p50 ---------------------------
        q = "/datasets/spheroid/annotations?order=msm&dir=desc"
        status, cold = _get_json(h.base, q)
        if status != 200 or cold["total"] < 1:
            return fail(f"cold query: {status} {cold}")
        status, warm = _get_json(h.base, q)
        if status != 200 or warm != cold:
            return fail("warm query disagrees with cold query")
        text = h.metrics_text()
        if 'sm_read_cache_hits_total{kind="annotations"}' not in text:
            return fail("warm query did not hit the cache "
                        "(sm_read_cache_hits_total missing)")
        lats = []
        for _ in range(WARM_REPEATS):
            t0 = time.perf_counter()
            status, _w = _get_json(h.base, q)
            lats.append(time.perf_counter() - t0)
            if status != 200:
                return fail(f"warm repeat returned {status}")
        p50 = sorted(lats)[len(lats) // 2]
        if p50 >= WARM_P50_BOUND_S:
            return fail(f"warm p50 {p50 * 1000:.1f} ms >= "
                        f"{WARM_P50_BOUND_S * 1000:.0f} ms bound")

        # ---- 3. parity vs a brute-force pandas scan ---------------------
        import pandas as pd

        parquet = pd.read_parquet(
            Path(h.sm_config.storage.results_dir) / "spheroid"
            / "annotations.parquet")
        got = [(r["sf"], r["adduct"], round(r["msm"], 9))
               for r in cold["rows"]]
        want = sorted(
            ((r.sf, r.adduct, round(float(r.msm), 9))
             for r in parquet.itertuples()),
            key=lambda t: (t[2], t[0], t[1]), reverse=True)
        if cold["total"] != len(parquet) or got != want[:len(got)]:
            return fail(f"segment diverges from the parquet scan: "
                        f"served {got[:3]}... expected {want[:3]}...")

        # ---- 4. tile bytes bit-identical to a direct render -------------
        from sm_distributed_tpu.engine.png import PngGenerator
        from sm_distributed_tpu.engine.storage import SearchResultsStore

        npz = Path(h.sm_config.storage.results_dir) / "spheroid" \
            / "ion_images.npz"
        if not npz.exists():
            return fail("service stored no ion_images.npz")
        images, ions = SearchResultsStore.load_ion_images(npz)
        sf, adduct = ions[0]
        ion_q = urllib.parse.quote(f"{sf}|{adduct}", safe="")
        status, headers, png = _http_raw(
            h.base, f"/datasets/spheroid/images/{ion_q}?k=0")
        if status != 200:
            return fail(f"tile GET returned {status}")
        if headers.get("Content-Type") != "image/png":
            return fail(f"tile Content-Type: {headers.get('Content-Type')}")
        direct = PngGenerator().render(images[0, 0])
        if png != direct:
            return fail(f"tile bytes differ from the direct render "
                        f"({len(png)} vs {len(direct)} bytes)")

        # ---- 5. the read SLO is live ------------------------------------
        status, slo = _get_json(h.base, "/slo")
        read_slo = slo.get("slos", {}).get("read")
        if status != 200 or read_slo is None:
            return fail(f"/slo has no read SLI: {slo}")
        if read_slo["count"] < WARM_REPEATS or \
                read_slo.get("attainment") is None:
            return fail(f"read SLI not accumulating: {read_slo}")

        # ---- 6. cohort answers ------------------------------------------
        status, cohort = _get_json(
            h.base, f"/annotations?sf={urllib.parse.quote(sf)}")
        if status != 200 or cohort["n_datasets"] != 1:
            return fail(f"cohort query: {status} {cohort}")
    finally:
        h.shutdown()
    print(f"read_smoke: OK — cold->warm cache hit, warm p50 "
          f"{p50 * 1000:.1f} ms, parity vs parquet scan "
          f"({cold['total']} rows), tile bit-identical "
          f"({len(png)} bytes), read SLO attainment "
          f"{read_slo['attainment']:.3f} over {read_slo['count']} reads")
    return 0


def main() -> int:
    import shutil

    work = Path(tempfile.mkdtemp(prefix="sm_read_smoke_"))
    try:
        return run(work)
    except AssertionError as exc:
        return fail(str(exc))
    finally:
        shutil.rmtree(work, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
