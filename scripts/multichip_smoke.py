#!/usr/bin/env python
"""Multichip smoke gate (ISSUE 7 satellite; wired into scripts/check_tier1.sh).

Proves the device-pool + pjit-sharded scale-out shape end to end on a
virtual 8-chip CPU mesh, through the REAL service stack (spool, scheduler,
admission, SearchJob, tracing):

1. a ``devices: 8`` submit claims the whole pool as one contiguous
   sub-mesh and scores through the GSPMD-sharded pixels×formulas path —
   its STORED annotations are oracle-checked against an in-process
   ``numpy_ref`` search of the same dataset/formulas (same FDR seed; msm
   to 1e-6, the documented sharded parity contract);
2. two 1-chip submits run concurrently: their traces must show device
   holds on DISTINCT chips with OVERLAPPING hold windows — the
   single-token serialization the pool replaced is provably gone;
3. the pool drains clean (no held chips, no waiters) and /metrics +
   /debug/timeseries expose per-chip in-use and the pool-wide ratio.

Exit 0 = gate passes.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time
import urllib.request
from pathlib import Path

# the virtual 8-chip mesh must exist BEFORE jax initializes (scripts run
# outside tests/conftest.py, which does this same dance for pytest)
os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = [f for f in os.environ.get("XLA_FLAGS", "").split()
          if "xla_force_host_platform_device_count" not in f]
_flags.append("--xla_force_host_platform_device_count=8")
os.environ["XLA_FLAGS"] = " ".join(_flags)

REPO_ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO_ROOT))

import jax  # noqa: E402

# the axon TPU plugin's sitecustomize forces jax_platforms at boot;
# force CPU back before any backend initializes (same as tests/conftest.py)
jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

from scripts.load_sweep import Harness, _msg, build_fixtures  # noqa: E402
from sm_distributed_tpu.utils import failpoints  # noqa: E402

N_DEVICES = 8


def fail(msg: str) -> int:
    print(f"multichip_smoke: FAIL — {msg}", file=sys.stderr)
    return 1


def _get(h: Harness, path: str):
    with urllib.request.urlopen(h.base + path, timeout=30.0) as r:
        return json.loads(r.read())


def _trace_records(h: Harness, msg_id: str) -> list[dict]:
    return _get(h, f"/jobs/{msg_id}/trace?raw=1")["records"]


def _hold_window(records: list[dict], msg_id: str):
    """(devices, t_acquired, t_release) from a job's trace: the acquired
    event marks the grant; the device_hold span's end marks the release."""
    acq = [r for r in records
           if r["kind"] == "event" and r["name"] == "device_token_acquired"]
    hold = [r for r in records
            if r["kind"] == "span" and r["name"] == "device_hold"]
    if not acq or not hold:
        raise AssertionError(
            f"{msg_id}: trace lacks device hold evidence "
            f"(acquired={len(acq)}, hold={len(hold)})")
    devices = (acq[-1].get("attrs") or {}).get("devices")
    h = hold[-1]
    return devices, float(acq[-1]["ts"]), float(h["ts"]) + float(h["dur"])


def _numpy_oracle(h: Harness, fx: dict):
    """The same search on the same fixture, scored by the numpy_ref
    backend in-process — the golden annotations the sharded job must
    reproduce."""
    import dataclasses

    from sm_distributed_tpu.io.dataset import SpectralDataset
    from sm_distributed_tpu.models.msm_basic import MSMBasicSearch
    from sm_distributed_tpu.utils.config import DSConfig

    sm_np = dataclasses.replace(h.sm_config, backend="numpy_ref")
    ds = SpectralDataset.from_imzml(fx["fast"]["input_path"])
    search = MSMBasicSearch(
        ds, fx["fast"]["formulas"],
        DSConfig.from_dict(fx["fast"]["ds_config"]), sm_np)
    return search.search().annotations


def run(work: Path) -> int:
    if len(jax.devices()) < N_DEVICES:
        return fail(f"virtual mesh failed: {len(jax.devices())} devices")
    # lock-order detection (ISSUE 9): the multi-chip overlap scenario is
    # the densest lock population in the tree (pool cond + scheduler maps
    # + admission + metrics + telemetry, two jobs on distinct chips) —
    # instrument everything built below and fail on a cycle at the end
    from sm_distributed_tpu.analysis import lockorder

    lockorder.enable()
    fx = build_fixtures(work)
    h = Harness(work, "multichip_smoke", sm_overrides={
        "backend": "jax_tpu",
        "parallel": {"checkpoint_every": 0},
        "service": {"workers": 2, "device_pool_size": N_DEVICES,
                    "devices_per_job": 1},
    })
    try:
        # ---- 1. sub-mesh job over the whole pool, oracle-checked --------
        status, _hd, body = h.submit(
            _msg(fx, "fast", "mesh8", devices=N_DEVICES))
        if status != 202:
            return fail(f"mesh submit returned {status}: {body}")
        rows = h.wait_terminal(["mesh8"])
        if rows["mesh8"]["state"] != "done":
            return fail(f"mesh job state {rows['mesh8']['state']}: "
                        f"{rows['mesh8']['error']!r}")
        records = _trace_records(h, "mesh8")
        devices, _t0, _t1 = _hold_window(records, "mesh8")
        if devices != list(range(N_DEVICES)):
            return fail(f"mesh job lease devices {devices}, wanted all "
                        f"{N_DEVICES} chips")
        sharded_spans = [
            r for r in records if r["kind"] == "span"
            and r["name"] == "score_batch"
            and (r.get("attrs") or {}).get("backend") == "jax_tpu_sharded"]
        if not sharded_spans:
            return fail("mesh job trace has no jax_tpu_sharded score spans "
                        "— it did not take the pjit-sharded path")
        syncs = [r for r in records if r["kind"] == "span"
                 and r["name"] == "device_sync"
                 and (r.get("attrs") or {}).get("devices")]
        if not syncs or sorted(syncs[-1]["attrs"]["devices"]) != \
                list(range(N_DEVICES)):
            return fail(f"device_sync span lacks the {N_DEVICES} sub-mesh "
                        f"chip ids: {[s.get('attrs') for s in syncs][:2]}")

        from sm_distributed_tpu.engine.storage import AnnotationIndex, JobLedger

        stored = AnnotationIndex(
            JobLedger(h.sm_config.storage.results_dir)).search(ds_id="mesh8")
        golden = _numpy_oracle(h, fx)
        if stored.empty or golden.empty:
            return fail(f"no annotations to compare (stored={len(stored)}, "
                        f"golden={len(golden)})")
        g = golden.set_index(["sf", "adduct"]).sort_index()
        s = stored.set_index(["sf", "adduct"]).sort_index()
        if set(g.index) != set(s.index):
            return fail(f"annotation ion sets differ: sharded {set(s.index)}"
                        f" vs oracle {set(g.index)}")
        if not np.allclose(s["msm"].to_numpy(),
                           g.loc[s.index, "msm"].to_numpy(),
                           rtol=0, atol=1e-6):
            return fail("sharded msm scores diverge from the numpy oracle "
                        "beyond the 1e-6 parity contract")
        print(f"multichip_smoke: mesh job OK — {len(stored)} annotations "
              f"oracle-checked over mesh devices {devices}")

        # ---- 2. two 1-chip jobs hold DISTINCT chips CONCURRENTLY --------
        # deterministic overlap: every batch-group score sleeps, so each
        # job's device hold lasts >= the submit skew
        failpoints.configure("device.score_batch=sleep:0.6")
        try:
            for mid in ("one_a", "one_b"):
                status, _hd, body = h.submit(_msg(fx, "fast", mid))
                if status != 202:
                    return fail(f"{mid} submit returned {status}: {body}")
            rows = h.wait_terminal(["one_a", "one_b"])
        finally:
            failpoints.configure(None)
        bad = {m: (rows[m]["state"], rows[m]["error"])
               for m in ("one_a", "one_b") if rows[m]["state"] != "done"}
        if bad:
            return fail(f"1-chip jobs not done: {bad}")
        win = {m: _hold_window(_trace_records(h, m), m)
               for m in ("one_a", "one_b")}
        (dev_a, a0, a1), (dev_b, b0, b1) = win["one_a"], win["one_b"]
        if not dev_a or not dev_b or len(dev_a) != 1 or len(dev_b) != 1:
            return fail(f"1-chip leases wrong: {dev_a} / {dev_b}")
        if set(dev_a) & set(dev_b):
            return fail(f"both jobs granted chip(s) {set(dev_a) & set(dev_b)}"
                        " — the pool failed to pack them")
        if not (a0 < b1 and b0 < a1):
            return fail(f"holds did not overlap: a=[{a0:.3f},{a1:.3f}] "
                        f"b=[{b0:.3f},{b1:.3f}]")
        print(f"multichip_smoke: 1-chip jobs OK — chips {dev_a} and {dev_b} "
              f"held concurrently ({min(a1, b1) - max(a0, b0):.2f}s overlap)")

        # ---- 3. pool drained + occupancy surfaced ------------------------
        pool = h.service.device_pool
        if pool.in_use_count() or pool.waiters():
            return fail(f"pool not drained: {pool.snapshot()}")
        text = h.metrics_text()
        for needle in ("sm_device_pool_in_use", "sm_device_pool_grants_total",
                       "sm_device_pool_wait_seconds"):
            if needle not in text:
                return fail(f"/metrics lacks {needle}")
        h.service.telemetry.sample()     # don't wait for the 5 s cadence
        samples = _get(h, "/debug/timeseries")["samples"]
        if not any("device_pool_ratio" in s for s in samples):
            return fail("/debug/timeseries lacks device_pool_ratio")
        print("multichip_smoke: pool drained; per-chip + pool-wide "
              "occupancy on /metrics and /debug/timeseries")

        # ---- 4. lock-order graph over the whole smoke is acyclic ---------
        rep = lockorder.assert_no_cycles("multichip_smoke")
        print(f"multichip_smoke: lock-order clean "
              f"({rep['locks_instrumented']} locks, {rep['edges']} order "
              f"edges observed)")
        return 0
    finally:
        h.shutdown()
        lockorder.disable()


def main() -> int:
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--work", default=None,
                    help="working dir (default: a fresh tempdir)")
    ap.add_argument("--keep", action="store_true")
    args = ap.parse_args()
    if args.work:
        work = Path(args.work)
        work.mkdir(parents=True, exist_ok=True)
        return run(work)
    with tempfile.TemporaryDirectory(prefix="sm_multichip_smoke_") as d:
        rc = run(Path(d))
        if args.keep:
            print(f"multichip_smoke: work dir kept at {d}", file=sys.stderr)
        return rc


if __name__ == "__main__":
    sys.exit(main())
