#!/usr/bin/env bash
# Tier-1 regression gate (ISSUE 1 satellite): runs the ROADMAP.md tier-1
# command and fails if DOTS_PASSED drops below the seed baseline, so test
# regressions are caught mechanically instead of by eyeballing pytest output.
#
# Usage: scripts/check_tier1.sh [BASELINE] [--chaos] [--load]  (default baseline: 137)
#
#   --chaos   also run the fast chaos smoke stage (3-failpoint subset of
#             scripts/chaos_sweep.py) after the test gate (ISSUE 2 satellite)
#             AND the load-sweep smoke gate (small burst + one poison job +
#             one deadline job through the real service; ISSUE 4 satellite)
#   --load    run only the load-sweep smoke gate after the test gate
#
# Always runs the smlint stage first (ISSUE 9): the static-analysis rule
# set (docs/ANALYSIS.md) over the tree plus its --self-check (baseline
# minimality + every rule's firing fixture).  Then the failpoint registry
# gate: registered names must be unique (duplicate registration raises at
# import), documented in docs/RECOVERY.md, and covered by a chaos scenario.  Then the isocalc
# parallel smoke gate (scripts/isocalc_smoke.py): a 2-worker spheroid run
# must produce byte-identical cache shards vs the serial run.  Then the
# trace smoke gate (scripts/trace_smoke.py): a traced spheroid job through
# the real service must emit a schema-valid, Perfetto-loadable trace that
# trace_report.py renders.  Then the perf-sentinel self-check
# (scripts/perf_sentinel.py): the committed BENCH_r*.json history must pass
# against itself and a synthetic regression must trip the gate.
#
# Exit codes: 0 = all gates pass, 1 = regression / gate failure.
# Note: pytest's own exit code is nonzero while the 32 pre-existing
# failures/6 errors remain, so the GATE is the dots count, not pytest's rc.
set -u -o pipefail

BASELINE="137"
RUN_CHAOS=0
RUN_LOAD=0
for arg in "$@"; do
    case "$arg" in
        --chaos) RUN_CHAOS=1; RUN_LOAD=1 ;;
        --load) RUN_LOAD=1 ;;
        *) BASELINE="$arg" ;;
    esac
done
REPO_ROOT="$(cd "$(dirname "$0")/.." && pwd)"
LOG="$(mktemp /tmp/check_tier1.XXXXXX.log)"
trap 'rm -f "$LOG"' EXIT

cd "$REPO_ROOT"

# smlint stage (ISSUE 9, always on): project-invariant static analysis —
# fence-gated write seams, failpoint registry, metric conventions, config
# drift, guarded-by locking, exception hygiene — must report zero NEW
# findings, and --self-check proves the committed suppression baseline is
# minimal and every rule's firing fixture still fires
if ! env JAX_PLATFORMS=cpu python scripts/smlint.py; then
    echo "check_tier1: FAIL — smlint found new findings" >&2
    exit 1
fi
if ! env JAX_PLATFORMS=cpu python scripts/smlint.py --self-check; then
    echo "check_tier1: FAIL — smlint self-check failed" >&2
    exit 1
fi

# analysis drift sentinel (ISSUE 12): the smlint --json totals (per-rule
# finding counts + the static compile-surface census) are band-checked
# against the committed ANALYSIS_r*.json history, so a quietly growing
# suppressed count or compile surface diffs across rounds like a perf
# regression would
SMLINT_JSON="$(mktemp /tmp/smlint_fresh.XXXXXX.json)"
trap 'rm -f "$LOG" "$SMLINT_JSON"' EXIT
if ! env JAX_PLATFORMS=cpu python scripts/smlint.py --json > "$SMLINT_JSON"; then
    echo "check_tier1: FAIL — smlint --json artifact generation failed" >&2
    exit 1
fi
if ! env JAX_PLATFORMS=cpu python scripts/perf_sentinel.py \
        --history "$REPO_ROOT/ANALYSIS_r*.json" --fresh "$SMLINT_JSON" \
        --min-history 1; then
    echo "check_tier1: FAIL — analysis drift sentinel tripped" >&2
    exit 1
fi

# ULP-contract numerics sentinel (ISSUE 15): score the off-lattice
# spheroid fixture on the lattice-bucketed jax backend AND the numpy
# oracle — FDR-rank identity is a HARD gate, per-MSM-component max-ULP
# drift must stay inside the declared COMPONENT_CONTRACTS ceilings, and
# the drift is band-checked against the committed NUMERICS_r*.json
# history (rising drift regresses).  This is the correctness backstop
# for ROADMAP item 3's bf16/int8 compaction work.
if ! env JAX_PLATFORMS=cpu python scripts/ulp_sentinel.py; then
    echo "check_tier1: FAIL — ULP-contract numerics sentinel tripped" >&2
    exit 1
fi
if ! env JAX_PLATFORMS=cpu python scripts/ulp_sentinel.py --self-check; then
    echo "check_tier1: FAIL — ulp_sentinel self-check failed" >&2
    exit 1
fi

# roofline probe gate (ISSUE 18): the tiny bench shape through the FUSED
# scoring path (interpret-mode Pallas off-TPU) timed against the
# fused-variant cost-model floor on this host's measured peaks.  The
# --min-frac band is deliberately loose on CPU (tiny shapes are
# dispatch-dominated and interpret-mode Pallas replays the grid serially;
# the measured tiny fused fraction here is ~2e-4) — the gate catches
# catastrophic fused-path regressions (an order of magnitude off the
# model), and proves the fused variant + cost model stay runnable end to
# end on every CI run
if ! env JAX_PLATFORMS=cpu python scripts/roofline_probe.py --tiny \
        --fused on --min-frac 0.00002; then
    echo "check_tier1: FAIL — roofline probe gate failed" >&2
    exit 1
fi

# compile census gate (ISSUE 12): the spheroid fixture through the real
# service on the jax backend — every XLA compilation attributed to a
# COMPILE_SURFACE-registered call site, the signature set closed under a
# second identical-shape job, the sharded path attributed the same way,
# and sm_compile_* live on /metrics with a `compile` trace event
if ! env JAX_PLATFORMS=cpu python scripts/compile_census.py; then
    echo "check_tier1: FAIL — compile census gate failed" >&2
    exit 1
fi

# failpoint registry gate (now DELEGATES to the smlint failpoint-registry
# rule + the runtime scenario-table cross-check the static rule can't see)
if ! env JAX_PLATFORMS=cpu python scripts/chaos_sweep.py --check-docs; then
    echo "check_tier1: FAIL — failpoint registry check failed" >&2
    exit 1
fi

# isocalc parallel smoke gate (ISSUE 3): 2-worker generation on the spheroid
# fixture must merge to byte-identical cache shards vs the serial run
if ! env JAX_PLATFORMS=cpu python scripts/isocalc_smoke.py; then
    echo "check_tier1: FAIL — isocalc parallel smoke gate failed" >&2
    exit 1
fi

# trace smoke gate (ISSUE 5): the spheroid fixture through the real
# in-process service with tracing on must yield a schema-valid,
# Perfetto-loadable trace that scripts/trace_report.py renders.  Then the
# multichip smoke (ISSUE 7) below proves the device-pool scale-out shape.
if ! env JAX_PLATFORMS=cpu python scripts/trace_smoke.py; then
    echo "check_tier1: FAIL — trace smoke gate failed" >&2
    exit 1
fi

# multichip smoke gate (ISSUE 7): a devices=8 submit through the real
# scheduler must claim the whole simulated pool, score through the
# pjit-sharded sub-mesh path, and match the numpy oracle; two 1-chip jobs
# must hold DISTINCT chips concurrently (no single-token serialization)
if ! env JAX_PLATFORMS=cpu python scripts/multichip_smoke.py; then
    echo "check_tier1: FAIL — multichip smoke gate failed" >&2
    exit 1
fi

# device-fault survival gate (ISSUE 14): a 4-chip sharded job on the
# virtual mesh survives a sticky chip death mid-job — the chip is
# probe-attributed and quarantined, the retry resumes from checkpoint on
# the 3 surviving chips with BIT-IDENTICAL stored annotations, the
# quarantine is visible on /debug/devices + /metrics, no later lease
# includes the fenced chip, and a passing re-probe readmits it
if ! env JAX_PLATFORMS=cpu python scripts/device_chaos.py --smoke; then
    echo "check_tier1: FAIL — device-fault survival gate failed" >&2
    exit 1
fi

# cold-start smoke gate (ISSUE 13): a cleared-persistent-cache 64x64
# submit through the real service must deliver its first FDR-rankable
# annotations in < 5 s (proven via /slo attainment), with the trace
# pinning the compile/queue/compute split + first_annotation ordering,
# the streamed `partial` results field populated, and the recorded
# shape-bucket lattice primeable in one pass
if ! env JAX_PLATFORMS=cpu python scripts/coldstart_smoke.py; then
    echo "check_tier1: FAIL — cold-start smoke gate failed" >&2
    exit 1
fi

# resource-exhaustion smoke gate (ISSUE 10): the spheroid fixture through
# the real service under a 64 MB disk budget — trace-drop degrade visible
# on /metrics with golden results, 507 shed at the submit floor, recovery
# after free-up, retention GC keeps done/ under its cap, and the preflight
# fast path stays microseconds-cheap
if ! env JAX_PLATFORMS=cpu python scripts/resource_smoke.py; then
    echo "check_tier1: FAIL — resource-exhaustion smoke gate failed" >&2
    exit 1
fi

# read-path smoke gate (ISSUE 16): the spheroid fixture annotated through
# the real service, then read back over HTTP — cold query answers from the
# columnar segment, the warm repeat is a cache hit with p50 < 50 ms, the
# result matches a brute-force parquet scan, tile bytes are bit-identical
# to a direct engine/png.py render, and /slo carries the read SLI
if ! env JAX_PLATFORMS=cpu python scripts/read_smoke.py; then
    echo "check_tier1: FAIL — read-path smoke gate failed" >&2
    exit 1
fi

# host-loss survival gate (ISSUE 17): a 2-host simulated pod (self +
# one real child process as host h1) loses the whole child host SIGKILL
# mid-sharded-job — the host watchdog evicts its chip range in one unit,
# the in-flight job resumes from checkpoint on the surviving host with
# BIT-IDENTICAL stored annotations, /peers + sm_pod_* metrics show the
# eviction, and the returning host is readmitted half-open immediately
if ! env JAX_PLATFORMS=cpu python scripts/host_chaos.py --smoke; then
    echo "check_tier1: FAIL — host-loss survival gate failed" >&2
    exit 1
fi

# replica failover smoke gate (ISSUE 8): 3 real scheduler replica
# processes over one partitioned spool; killing one mid-score (and pausing
# one into a fence race) must converge every job exactly-once to the
# golden report, with survivors' sm_replica_* metrics proving the takeover
if ! env JAX_PLATFORMS=cpu python scripts/replica_chaos.py --smoke; then
    echo "check_tier1: FAIL — replica failover smoke gate failed" >&2
    exit 1
fi

# live-acquisition failover gate (ISSUE 19): two replicas over one shared
# spool + work dir; SIGKILL and controller drain of the claim-owning
# replica mid-acquisition must both hand the live stream job to the peer,
# which resumes from the chunk-log checkpoint and converges BIT-IDENTICAL
# (check_exact) to the one-shot batch report — exactly-once spool census,
# exactly-once chunk ingest, zero debris
if ! env JAX_PLATFORMS=cpu python scripts/stream_chaos.py --smoke; then
    echo "check_tier1: FAIL — live-acquisition failover gate failed" >&2
    exit 1
fi

# fleet observability gate (ISSUE 20): a 3-replica fleet over one shared
# work dir, one replica SIGKILLed mid-scrape — /fleet/slo must stay a 200
# partial view with per-replica scrape-error evidence (never a 500), and
# once the victim goes stale the merged SLO must be BIT-EQUAL to a
# recomputation from the union of the survivors' raw histogram buckets.
# Then an on-demand /debug/profile capture during a running sharded job
# must attribute device time to the fused Pallas scoring kernel BY NAME
# and inject correlated device_kernel spans into the job trace; finally
# the committed PROFILE_r*.json must carry the measured-roofline pins and a
# degraded replay must trip both perf_sentinel bands.
if ! env JAX_PLATFORMS=cpu python scripts/fleet_smoke.py; then
    echo "check_tier1: FAIL — fleet observability gate failed" >&2
    exit 1
fi

# elastic-fleet smoke gate (ISSUE 11): a lock-order-instrumented
# FleetController over bare replica subprocesses must scale 1→4 under a
# traffic surge and drain back to 2 under cooldown, with every job done/
# exactly once, bounded p99 queue-wait, zero orphaned leases/heartbeats
# from drained replicas, and sm_fleet_* metric families exposed
if ! env JAX_PLATFORMS=cpu python scripts/load_sweep.py --elastic; then
    echo "check_tier1: FAIL — elastic-fleet smoke gate failed" >&2
    exit 1
fi

# perf-sentinel self-check (ISSUE 6): the regression gate itself is gated —
# the newest committed BENCH_r*.json must pass against its own history AND
# a synthetically degraded copy must trip the sentinel
if ! env JAX_PLATFORMS=cpu python scripts/perf_sentinel.py --self-check; then
    echo "check_tier1: FAIL — perf sentinel self-check failed" >&2
    exit 1
fi

# the ROADMAP.md tier-1 command, verbatim flags
timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' \
    --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly \
    2>&1 | tee "$LOG"
pytest_rc=${PIPESTATUS[0]}

if [ "$pytest_rc" -ge 124 ]; then
    echo "check_tier1: FAIL — tier-1 run timed out or was killed (rc=$pytest_rc)" >&2
    exit 1
fi

PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' "$LOG" | tr -cd . | wc -c)
echo "DOTS_PASSED=$PASSED (baseline $BASELINE)"

if [ "$PASSED" -lt "$BASELINE" ]; then
    echo "check_tier1: FAIL — $PASSED passed < baseline $BASELINE" >&2
    exit 1
fi
echo "check_tier1: OK — $PASSED passed >= baseline $BASELINE"

if [ "$RUN_CHAOS" -eq 1 ]; then
    echo "check_tier1: running chaos smoke stage (--chaos)"
    if ! env JAX_PLATFORMS=cpu python scripts/chaos_sweep.py --smoke; then
        echo "check_tier1: FAIL — chaos smoke stage failed" >&2
        exit 1
    fi
    echo "check_tier1: chaos smoke OK"
fi

if [ "$RUN_LOAD" -eq 1 ]; then
    echo "check_tier1: running load-sweep smoke stage"
    if ! env JAX_PLATFORMS=cpu python scripts/load_sweep.py --smoke; then
        echo "check_tier1: FAIL — load-sweep smoke stage failed" >&2
        exit 1
    fi
    echo "check_tier1: load-sweep smoke OK"
fi
