#!/usr/bin/env python
"""ULP-contract numerics sentinel (ISSUE 15 — the runtime half of numlint).

ROADMAP item 3 (fused Pallas scoring + bf16/int8 intensity compaction) is
gated on FDR ranks staying bit-identical — or within a *declared*
tolerance — to the fp32/numpy oracle.  The static half of that gate is
the ``NUMERICS`` contract registries + the three numlint rules; this
script is the measurement:

1. score the spheroid fixture (the same deliberately off-lattice 9x11
   geometry tests/test_buckets.py pins: real row padding, real resident
   padding, targets + sampled decoys) on the lattice-bucketed jax
   backend AND the numpy oracle;
2. record per-MSM-component max-ULP drift (chaos, image correlation,
   pattern match, msm — ``analysis/numerics.component_drift``) and
   FDR-rank agreement into a ``NUMERICS_r*.json`` artifact;
3. gate three ways:
   - **rank identity** is HARD: any jax-vs-numpy FDR order or level
     difference fails the run outright;
   - **contract ceilings**: each component's measured drift must stay
     within ``analysis/numerics.COMPONENT_CONTRACTS`` (chaos is
     bit_exact = 0 ULPs);
   - **history banding** (perf_sentinel-style, rising drift regresses):
     the fresh drift is compared against the committed ``NUMERICS_r*``
     history medians — so a PR that moves spatial from 0 to 3 ULPs
     trips the sentinel even while the declared ceiling still holds.

``--self-check`` replays the newest committed artifact (must pass) and a
synthetically ceiling-busting copy (must fail) — the gate's gate.  Wired
into ``scripts/check_tier1.sh`` (always on).

Usage::

    python scripts/ulp_sentinel.py                    # measure + gate
    python scripts/ulp_sentinel.py --write NUMERICS_r01.json
    python scripts/ulp_sentinel.py --fresh art.json   # gate an artifact
    python scripts/ulp_sentinel.py --self-check
"""

from __future__ import annotations

import argparse
import glob
import json
import sys
import tempfile
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO_ROOT))

from scripts import perf_sentinel  # noqa: E402

# the committed fixture identity: every parameter that shapes the scored
# arrays rides in the artifact, so a drifted fixture can't masquerade as
# drifted numerics
FIXTURE = {"nrows": 9, "ncols": 11, "present_fraction": 0.5,
           "noise_peaks": 12, "seed": 41, "n_formulas": 10,
           "decoy_sample_size": 2, "formula_batch": 8}


def measure(workdir: str | Path | None = None) -> dict:
    """Score the spheroid fixture on both backends and return the
    NUMERICS artifact (pure measurement — gating is :func:`gate`)."""
    import numpy as np
    import pandas as pd

    from sm_distributed_tpu.analysis import numerics
    from sm_distributed_tpu.io.dataset import SpectralDataset
    from sm_distributed_tpu.io.fixtures import generate_synthetic_dataset
    from sm_distributed_tpu.models.msm_basic import NumpyBackend, _slice_table
    from sm_distributed_tpu.models.msm_jax import JaxBackend
    from sm_distributed_tpu.ops.fdr import FDR
    from sm_distributed_tpu.ops.isocalc import IsocalcWrapper
    from sm_distributed_tpu.utils.config import (
        DSConfig,
        IsotopeGenerationConfig,
        SMConfig,
    )

    fx = FIXTURE
    workdir = Path(workdir or tempfile.mkdtemp(prefix="ulp_sentinel_"))
    path, truth = generate_synthetic_dataset(
        workdir / "ds", nrows=fx["nrows"], ncols=fx["ncols"], formulas=None,
        present_fraction=fx["present_fraction"],
        noise_peaks=fx["noise_peaks"], seed=fx["seed"])
    ds = SpectralDataset.from_imzml(path)

    # a REAL search table: targets + sampled decoys, exactly the
    # population the FDR ranking runs over (mirrors MSMBasicSearch)
    formulas = truth.formulas[: fx["n_formulas"]]
    fdr = FDR(decoy_sample_size=fx["decoy_sample_size"],
              target_adducts=("+H",), seed=1)
    assignment = fdr.decoy_adduct_selection(formulas)
    pairs, flags = assignment.all_ion_tuples(formulas, ("+H",))
    calc = IsocalcWrapper(IsotopeGenerationConfig(adducts=("+H",)))
    table = calc.pattern_table(pairs, flags)

    dc = DSConfig.from_dict({"isotope_generation": {"adducts": ["+H"]}})
    sm = SMConfig.from_dict({
        "backend": "jax_tpu",
        "parallel": {"formula_batch": fx["formula_batch"]}})
    batch = fx["formula_batch"]

    def score_all(backend) -> np.ndarray:
        outs = backend.score_batches(
            [_slice_table(table, s, min(s + batch, table.n_ions))
             for s in range(0, table.n_ions, batch)])
        return np.concatenate(outs)

    import jax

    jx = JaxBackend(ds, dc, sm)
    got = score_all(jx)                  # lattice-bucketed jax scoring
    want = score_all(NumpyBackend(ds, dc))   # the fp32/numpy oracle
    drift = numerics.component_drift(got, want)

    # fused+compacted path (ISSUE 18): the fused Pallas scoring kernel
    # (interpret-mode off-TPU) over the bf16-compacted resident cube.
    # Its drift vs the plain-f32 jax path is DATA-level (the cube lost
    # mantissa bits), so it gates against ops/quantize.py's declared
    # compact_cube contract — not the same-data COMPONENT_CONTRACTS —
    # plus the same HARD FDR-rank-identity bar vs the numpy oracle.
    from sm_distributed_tpu.ops.quantize import NUMERICS as _QN

    cube_ulps = numerics.contract_ulps(
        numerics.parse_policy(_QN["compact_cube"])["contract"])
    sm_fused = SMConfig.from_dict({
        "backend": "jax_tpu",
        "parallel": {"formula_batch": fx["formula_batch"],
                     "fused_metrics": "on", "cube_dtype": "bf16"}})
    got_fused = score_all(JaxBackend(ds, dc, sm_fused))
    drift_fused = numerics.component_drift(got_fused, got)

    def ranks(metrics: np.ndarray):
        df = pd.DataFrame({"sf": table.sfs, "adduct": table.adducts,
                           "msm": metrics[:, 3]})
        ann = fdr.estimate_fdr(df, assignment)
        return ann.sort_values(["msm", "sf"], ascending=False)

    def rank_mismatches(r_got, r_ref) -> int:
        order = int(sum(
            a != b for a, b in zip(r_got.sf.tolist(), r_ref.sf.tolist())))
        levels_equal = bool(
            (r_got.fdr.to_numpy() == r_ref.fdr.to_numpy()).all() and
            (r_got.fdr_level.to_numpy() == r_ref.fdr_level.to_numpy()).all())
        return order if order else (0 if levels_equal else 1)

    r_np = ranks(want)
    mismatches = rank_mismatches(ranks(got), r_np)
    mismatches_fused = rank_mismatches(ranks(got_fused), r_np)

    reg = numerics.registered()
    return {
        "kind": "numerics",
        "fixture": dict(fx),
        "backend": jax.default_backend(),
        "n_ions": int(table.n_ions),
        "lattice_rows": int(jx._nrows_b),        # proves padding engaged
        "dataset_rows": int(ds.nrows),
        "sm_numerics_max_ulp": drift,
        "fdr_rank_mismatches": mismatches,
        "fdr_ranks_identical": mismatches == 0,
        # fused Pallas kernel + bf16 cube (ISSUE 18): drift vs plain-f32
        # jax, gated by the compact_cube data-level contract; rank
        # identity vs the numpy oracle stays the HARD bar
        "fused_metrics": "on",
        "cube_dtype": "bf16",
        "cube_contract_ulps": int(cube_ulps),
        "sm_numerics_max_ulp_fused": drift_fused,
        "fdr_rank_mismatches_fused": mismatches_fused,
        "fdr_ranks_identical_fused": mismatches_fused == 0,
        "component_contracts": dict(numerics.COMPONENT_CONTRACTS),
        "declared_contracts": sum(len(e) for e in reg.values()),
        "declared_modules": len(reg),
    }


def gate(artifact: dict, history_paths: list[str], tolerance: float,
         min_history: int, label: str) -> int:
    """The three-way gate over one NUMERICS artifact: hard rank identity,
    declared per-component ceilings, then history banding.  0 clean, 1
    violation/regression, 2 nothing comparable."""
    from sm_distributed_tpu.analysis import numerics

    rc = 0
    if artifact.get("fdr_rank_mismatches", 0) != 0 or \
            not artifact.get("fdr_ranks_identical", False):
        print(f"ulp_sentinel: {label}: FAIL — jax-vs-numpy FDR ranks "
              f"diverge ({artifact.get('fdr_rank_mismatches')} "
              f"mismatch(es)); rank identity is the HARD contract",
              file=sys.stderr)
        rc = 1
    if artifact.get("fdr_rank_mismatches_fused", 0) != 0 or \
            not artifact.get("fdr_ranks_identical_fused", True):
        print(f"ulp_sentinel: {label}: FAIL — fused+compacted-vs-numpy "
              f"FDR ranks diverge "
              f"({artifact.get('fdr_rank_mismatches_fused')} mismatch(es)); "
              f"rank identity is the HARD contract", file=sys.stderr)
        rc = 1
    ceilings = {**numerics.COMPONENT_CONTRACTS,
                **artifact.get("component_contracts", {})}
    for comp, ulps in (artifact.get("sm_numerics_max_ulp") or {}).items():
        ceiling = ceilings.get(comp)
        if ceiling is not None and ulps > ceiling:
            print(f"ulp_sentinel: {label}: FAIL — {comp} drift {ulps} "
                  f"ULPs exceeds its declared contract of {ceiling}",
                  file=sys.stderr)
            rc = 1
    # fused+bf16 drift is data-level — its ceiling is the compact_cube
    # contract the artifact itself carries (ops/quantize.py NUMERICS)
    cube_ceiling = artifact.get("cube_contract_ulps")
    for comp, ulps in (artifact.get("sm_numerics_max_ulp_fused")
                       or {}).items():
        if cube_ceiling is not None and ulps > cube_ceiling:
            print(f"ulp_sentinel: {label}: FAIL — fused+compacted {comp} "
                  f"drift {ulps} ULPs exceeds the compact_cube contract "
                  f"of {cube_ceiling}", file=sys.stderr)
            rc = 1
    band_rc = perf_sentinel.run_check(
        history_paths, perf_sentinel.normalize(artifact), tolerance,
        min_history, 0.0, f"ulp_sentinel {label}")
    if band_rc == 2 and not history_paths:
        # first run of a fresh checkout: ceilings + rank identity still
        # gate; banding starts once NUMERICS_r01.json is committed
        print("ulp_sentinel: no committed history — banding skipped "
              "(ceilings and rank identity still gated)", file=sys.stderr)
        band_rc = 0
    return rc or band_rc


def degrade(artifact: dict) -> dict:
    """A synthetically broken copy for --self-check: every component
    busts its ceiling and the rank contract breaks."""
    bad = json.loads(json.dumps(artifact))
    ulp = bad.get("sm_numerics_max_ulp") or {}
    ceilings = bad.get("component_contracts") or {}
    for comp in ulp:
        ulp[comp] = 2 * int(ceilings.get(comp, 0)) + 8
    ulp_fused = bad.get("sm_numerics_max_ulp_fused") or {}
    for comp in ulp_fused:
        ulp_fused[comp] = 2 * int(bad.get("cube_contract_ulps", 0)) + 8
    bad["fdr_rank_mismatches"] = 1
    bad["fdr_ranks_identical"] = False
    if "fdr_ranks_identical_fused" in bad:
        bad["fdr_rank_mismatches_fused"] = 1
        bad["fdr_ranks_identical_fused"] = False
    return bad


def self_check(history_paths: list[str], tolerance: float,
               min_history: int) -> int:
    """Newest committed artifact must pass its own history; a degraded
    copy must fail — proving the sentinel can actually fire."""
    if not history_paths:
        print("ulp_sentinel: self-check: no NUMERICS_r*.json history",
              file=sys.stderr)
        return 2
    honest = perf_sentinel.load_artifact(history_paths[-1])
    rc = gate(honest, history_paths, tolerance, min_history,
              "self-check honest (latest history replay)")
    if rc != 0:
        print("ulp_sentinel: self-check FAILED — the newest committed "
              "artifact does not pass its own gate", file=sys.stderr)
        return 1
    rc_bad = gate(degrade(honest), history_paths, tolerance, min_history,
                  "self-check degraded (synthetic contract bust)")
    if rc_bad != 1:
        print(f"ulp_sentinel: self-check FAILED — a synthetic "
              f"ceiling-busting regression did not trip the gate "
              f"(rc={rc_bad})", file=sys.stderr)
        return 1
    print("ulp_sentinel: self-check OK — honest history passes, synthetic "
          "contract bust fires")
    return 0


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--history", default=None,
                    help="glob of NUMERICS history artifacts (default: "
                         "the repo's committed NUMERICS_r*.json)")
    ap.add_argument("--fresh", default=None,
                    help="gate an existing artifact instead of measuring")
    ap.add_argument("--write", default=None,
                    help="write the measured artifact to this path (the "
                         "committed-history workflow)")
    ap.add_argument("--tolerance", type=float, default=0.5,
                    help="banding tolerance off the history median "
                         "(default 0.5 — ULP drift doubling flags)")
    ap.add_argument("--min-history", type=int, default=1)
    ap.add_argument("--json", action="store_true", dest="as_json")
    ap.add_argument("--self-check", action="store_true",
                    help="replay newest history honest + degraded — the "
                         "gate's gate")
    args = ap.parse_args(argv)

    pattern = args.history or str(REPO_ROOT / "NUMERICS_r*.json")
    history_paths = sorted(glob.glob(pattern))
    if args.self_check:
        if args.fresh:
            ap.error("--self-check takes no --fresh artifact")
        return self_check(history_paths, args.tolerance, args.min_history)

    if args.fresh:
        try:
            artifact = perf_sentinel.load_artifact(args.fresh)
        except (OSError, ValueError) as exc:
            print(f"ulp_sentinel: cannot load fresh artifact: {exc}",
                  file=sys.stderr)
            return 2
    else:
        artifact = measure()
    if args.as_json:
        print(json.dumps(artifact, indent=2))
    if args.write:
        Path(args.write).write_text(json.dumps(artifact, indent=2) + "\n")
        print(f"ulp_sentinel: wrote {args.write}")
    # a freshly written artifact should not band against a history that
    # already includes itself twice; still gate it fully
    return gate(artifact, history_paths, args.tolerance, args.min_history,
                "fresh measurement" if not args.fresh else
                f"fresh {args.fresh}")


if __name__ == "__main__":
    sys.exit(main())
