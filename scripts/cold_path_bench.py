"""Cold-path wall-clock for BASELINE eval config #3 (VERDICT r3 item 7).

One measured END-TO-END SearchJob at ~80k formulas with a COLD isocalc
cache: staging + parse + decoy selection + isotope-pattern generation +
scoring + FDR + storage, on a ~10^4-pixel section.  Everything before this
script only quoted the warm, per-phase pieces; BASELINE #3's wall-clock
includes pattern generation on a cold cache, so this measures exactly that.

The dataset embeds signal for ~1% of formulas (a tissue section contains a
tiny fraction of HMDB+LipidMaps, ref: SURVEY.md §6 config #3 [U]); the
other 99% still cost full pattern generation + scoring + decoy ranking,
which is the point.

Prints ONE JSON line; logs to stderr.  Runtime is dominated by isocalc on
this 1-core host (~75 core-minutes at 80k formulas x21 decoy+target
adducts) — run it solo so the wall-clock is honest.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).parent.parent))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--n-formulas", type=int, default=80_000)
    ap.add_argument("--nrows", type=int, default=100)
    ap.add_argument("--ncols", type=int, default=100)
    ap.add_argument("--decoy-sample-size", type=int, default=20)
    ap.add_argument("--present", type=int, default=800,
                    help="formulas with embedded spatial signal")
    ap.add_argument("--work-dir", default="",
                    help="job work dir (default: .cache/cold_path; the "
                         "isocalc cache inside is REMOVED first — that's "
                         "the 'cold' in cold path)")
    ap.add_argument("--isocalc-device", action="store_true",
                    help="route blur->centroid through the batched XLA "
                         "stage (parallel.isocalc_device=on)")
    ap.add_argument("--isocalc-workers", type=int, default=0,
                    help="isocalc pool size (0 = all cores)")
    args = ap.parse_args()

    from sm_distributed_tpu.io.fixtures import (
        expand_formula_list,
        generate_synthetic_dataset,
    )
    from sm_distributed_tpu.utils.logger import init_logger, logger

    init_logger()
    root = Path(args.work_dir or Path(__file__).parent.parent
                / ".cache" / "cold_path")
    root.mkdir(parents=True, exist_ok=True)

    formulas = expand_formula_list(args.n_formulas)
    t0 = time.perf_counter()
    ds_path, _truth = generate_synthetic_dataset(
        root / "ds", nrows=args.nrows, ncols=args.ncols,
        formulas=formulas[: args.present], present_fraction=1.0,
        noise_peaks=200, seed=11, reuse=True)
    logger.info("fixture: %dx%d px, %d signal formulas (%.1fs)",
                args.nrows, args.ncols, args.present,
                time.perf_counter() - t0)

    # cold cache: the whole point of this measurement
    import shutil

    job_work = root / "work"
    for stale in (job_work / "isocalc_cache", root / "results"):
        shutil.rmtree(stale, ignore_errors=True)

    from sm_distributed_tpu.engine.search_job import SearchJob
    from sm_distributed_tpu.utils.config import DSConfig, SMConfig

    sm_config = SMConfig.from_dict({
        "backend": "jax_tpu",
        "fdr": {"decoy_sample_size": args.decoy_sample_size},
        "storage": {"results_dir": str(root / "results"),
                    "store_images": False},
        "work_dir": str(job_work),
        "parallel": {
            "isocalc_device": "on" if args.isocalc_device else "off",
            "isocalc_workers": args.isocalc_workers,
        },
    })
    ds_config = DSConfig.from_dict({
        "isotope_generation": {"adducts": ["+H"]},
        "image_generation": {"ppm": 3.0},
    })

    t0 = time.perf_counter()
    job = SearchJob("cold3", "cold-path-config3", ds_path, ds_config,
                    sm_config, formulas=formulas)
    bundle = job.run()
    wall = time.perf_counter() - t0

    t = bundle.timings
    # generation wall (isocalc_gen) vs residual blocking wait
    # (isotope_patterns): with the ISSUE 3 overlap they differ — staging/
    # parse/scoring run concurrently with generation
    isocalc_s = t.get("isocalc_gen", t.get("isotope_patterns", 0.0))
    iso_stats = job.last_isocalc_stats or {}
    out = {
        "metric": "cold_path_config3_wall_clock",
        "unit": "s",
        "value": round(wall, 1),
        "n_formulas": args.n_formulas,
        "n_ions": int(bundle.all_metrics.shape[0]),
        "n_pixels": args.nrows * args.ncols,
        "isocalc_s": round(isocalc_s, 1),
        "isocalc_share": round(isocalc_s / wall, 3) if wall else None,
        "isocalc_wait_s": round(t.get("isotope_patterns", 0.0), 1),
        "isocalc_workers": iso_stats.get("workers"),
        "patterns_per_s": iso_stats.get("patterns_per_s"),
        "isocalc_device": bool(iso_stats.get("device", False)),
        "phases_s": {k: round(v, 1) for k, v in sorted(t.items())},
        "n_annotations_fdr10": int((bundle.annotations["fdr"] <= 0.1).sum())
        if len(bundle.annotations) else 0,
    }
    print(json.dumps(out))


if __name__ == "__main__":
    sys.exit(main())
