#!/usr/bin/env python
"""Cold-start smoke gate (ISSUE 13; wired into scripts/check_tier1.sh).

Proves the cold path end to end, through the REAL service stack, with the
persistent XLA cache CLEARED (a fresh empty dir):

1. a 64x64-pixel fixture submit on the ``jax_tpu`` backend must reach its
   first FDR-rankable annotations in **under 5 s from submit** —
   the ROADMAP item 1 acceptance — proven via ``GET /slo`` attainment on
   the ``first_annotation`` SLI (objective pinned to 5 s for this run);
2. the job's trace must pin the cold-start anatomy: at least one REAL
   ``compile`` event (cached=false — this run paid the cold compile), a
   ``first_annotation`` event that lands AFTER the first compile started
   but BEFORE the job's terminal state, and a ``partial_annotations``
   event (streamed first results) carrying a provisional count;
3. ``scripts/trace_report.py`` must render the compile/queue/compute
   split from that trace: ``accounting.compile_s > 0`` (the cold job paid
   compiles), ``queue_wait_s`` present, and
   ``accounting.first_annotation_s < 5``;
4. the job record's ``partial`` field (GET /jobs) must carry the
   provisional annotations while-running payload (checked at terminal —
   the field persists);
5. the shape-bucket lattice recorded the job's executables
   (``/debug/compile`` shows >= 1 known bucket) and one
   ``CachePrimer.prime_once`` pass marks them primed — the idle primer's
   work, driven synchronously here.

Exit 0 = gate passes.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import urllib.request
from pathlib import Path

os.environ.setdefault("JAX_PLATFORMS", "cpu")

REPO_ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO_ROOT))

from scripts.load_sweep import Harness  # noqa: E402
from scripts.trace_report import summarize  # noqa: E402
from sm_distributed_tpu.analysis import retrace  # noqa: E402
from sm_distributed_tpu.io.fixtures import generate_synthetic_dataset  # noqa: E402

FIRST_ANNOTATION_SLO_S = 5.0


def fail(msg: str) -> int:
    print(f"coldstart_smoke: FAIL — {msg}", file=sys.stderr)
    return 1


def run(work: Path) -> int:
    # the 64x64 acceptance fixture; a handful of formulas keeps isocalc
    # fast while the ion table still spans several scoring batches (so the
    # leading-group split is what delivers the first annotations early)
    fx_path, truth = generate_synthetic_dataset(
        work / "fx64", nrows=64, ncols=64, formulas=None,
        present_fraction=0.5, noise_peaks=20, seed=13)
    cache_dir = work / "xla_cache"          # fresh == cleared cold cache
    h = Harness(work, "coldstart", sm_overrides={
        "backend": "jax_tpu",
        "parallel": {"formula_batch": 4, "checkpoint_every": 1,
                     "compile_cache_dir": str(cache_dir)},
        "telemetry": {"slo_first_annotation_s": FIRST_ANNOTATION_SLO_S},
    })
    retrace.enable()
    try:
        msg = {"ds_id": "cold64", "msg_id": "cold64",
               "input_path": str(fx_path),
               "formulas": truth.formulas[:4],
               "ds_config": {"isotope_generation": {"adducts": ["+H"]}}}
        status, _hd, body = h.submit(msg)
        if status != 202:
            return fail(f"submit returned {status}: {body}")
        rows = h.wait_terminal([body["msg_id"]], timeout_s=300.0)
        row = rows[body["msg_id"]]
        if row["state"] != "done":
            return fail(f"job state {row['state']}: {row['error']!r}")

        # ---- 1. the /slo attainment proof: p50 < 5 s cold
        with urllib.request.urlopen(f"{h.base}/slo", timeout=30.0) as r:
            slo = json.loads(r.read())
        fa = slo["slos"]["first_annotation"]
        if fa["objective_s"] != FIRST_ANNOTATION_SLO_S:
            return fail(f"first_annotation objective is {fa['objective_s']}"
                        f" (expected {FIRST_ANNOTATION_SLO_S})")
        if not fa["count"]:
            return fail("first_annotation SLI recorded no jobs")
        if (fa["attainment"] or 0.0) < 0.5:
            # evidence for the margin: with one job the histogram sum IS
            # the measured latency, so a 5.1 s host-load blip reads
            # differently from a 30 s regression in the CI log
            measured = None
            try:
                with urllib.request.urlopen(f"{h.base}/metrics",
                                            timeout=30.0) as r:
                    for line in r.read().decode().splitlines():
                        if line.startswith(
                                "sm_slo_first_annotation_seconds_sum"):
                            measured = float(line.rsplit(" ", 1)[1])
            except (OSError, ValueError):
                pass          # evidence only — the SLO miss still fails
            return fail(
                f"cold submit→first-annotation missed the {FIRST_ANNOTATION_SLO_S:.0f} s "
                f"p50: attainment {fa['attainment']} over {fa['count']} "
                f"job(s), measured {measured} s")

        # ---- 2. trace anatomy: compile → first_annotation ordering,
        # streamed partial_annotations present
        with urllib.request.urlopen(
                f"{h.base}/jobs/{body['msg_id']}/trace?raw=1",
                timeout=30.0) as r:
            records = json.loads(r.read())["records"]
        events = [rec for rec in records if rec["kind"] == "event"]
        compiles = [e for e in events if e["name"] == "compile"
                    and not (e.get("attrs") or {}).get("cached")]
        firsts = [e for e in events if e["name"] == "first_annotation"]
        partials = [e for e in events if e["name"] == "partial_annotations"]
        if not compiles:
            return fail("cleared-cache job paid no compile — the cold "
                        "path went unobserved (vacuous smoke)")
        if not firsts:
            return fail("no first_annotation event on the trace")
        if not partials:
            return fail("no partial_annotations event — streamed first "
                        "results did not engage")
        t_compile = min(e["ts"] for e in compiles)
        t_first = min(e["ts"] for e in firsts)
        if not t_compile < t_first:
            return fail(f"event ordering broken: first compile at "
                        f"{t_compile} not before first_annotation at "
                        f"{t_first}")
        pa = partials[0].get("attrs") or {}
        if not pa.get("provisional") or not pa.get("n_scored"):
            return fail(f"partial_annotations event malformed: {pa}")
        if pa.get("n_scored") >= pa.get("n_ions", 0):
            return fail(f"partial event fired for a full result: {pa}")

        # ---- 3. trace_report renders the compile/queue/compute split
        s = summarize(records)
        acc = s["accounting"]
        if not acc["compile_s"] > 0:
            return fail(f"trace_report accounting has no compile time: {acc}")
        if acc["queue_wait_s"] is None:
            return fail("trace_report accounting lost queue_wait")
        if acc.get("first_annotation_s") is None or \
                acc["first_annotation_s"] >= FIRST_ANNOTATION_SLO_S:
            return fail(f"trace-derived first_annotation_s = "
                        f"{acc.get('first_annotation_s')} (want < "
                        f"{FIRST_ANNOTATION_SLO_S})")

        # ---- 4. the job record's streamed `partial` field
        if not (row.get("partial") or {}).get("provisional"):
            return fail(f"job record carries no partial results field: "
                        f"{row.get('partial')!r}")

        # ---- 5. the lattice recorded buckets and one prime pass primes
        # them (the idle primer's unit of work, driven synchronously)
        with urllib.request.urlopen(f"{h.base}/debug/compile",
                                    timeout=30.0) as r:
            dbg = json.loads(r.read())
        if not dbg["primer"] or dbg["primer"]["known"] < 1:
            return fail(f"/debug/compile shows no known buckets: {dbg}")
        res = h.service.primer.prime_once(abort_when_busy=False)
        if res["compiled"] + res["skipped"] < 1 or res["errors"]:
            return fail(f"prime pass did not cover the recorded lattice: "
                        f"{res}")
        snap = h.service.primer.snapshot()
        if snap["primed"] < 1:
            return fail(f"no bucket marked primed after prime_once: {snap}")

        print(f"coldstart_smoke: OK — first annotation at "
              f"{acc['first_annotation_s']:.2f}s cold (SLO {FIRST_ANNOTATION_SLO_S:.0f}s, "
              f"attainment {fa['attainment']}), compile {acc['compile_s']:.2f}s "
              f"across {len(compiles)} compile(s), partial preview "
              f"{pa.get('n_scored')}/{pa.get('n_ions')} ions, "
              f"{snap['primed']}/{snap['known']} buckets primed")
    finally:
        h.shutdown()
    return 0


def main() -> int:
    import shutil

    # One retry: the gate runs at ~85-90% of its 5 s budget on a loaded
    # CI host (in-suite, after the preceding gates, the measured cold
    # latency sits around 4.2-5.3 s), so a single transient host-load
    # blip must not fail the whole suite.  Each attempt is fully cold —
    # fresh work dir, fresh persistent cache, fresh jit wrappers — so a
    # PASS always means a genuinely cold job met the bar, and a
    # deterministic regression still fails both attempts.
    rc = 1
    for attempt in (1, 2):
        work = Path(tempfile.mkdtemp(prefix="sm_coldstart_"))
        try:
            rc = run(work)
        finally:
            shutil.rmtree(work, ignore_errors=True)
        if rc == 0:
            return 0
        if attempt == 1:
            print("coldstart_smoke: attempt 1 failed — retrying once "
                  "(the cold-start bar is wall-clock-margin sensitive "
                  "under CI load)", file=sys.stderr)
    return rc


if __name__ == "__main__":
    sys.exit(main())
