"""Roofline probe for the fused score graph (ISSUE 3 satellite).

Replaces the per-phase-probe basis of docs/PERF.md's "no headroom left"
claim with a measured ROOFLINE statement: the fused extract+score stream is
timed against this device's own measured peaks (reduction/copy bandwidth,
f32 matmul throughput) and the engine's minimum-work cost model
(``ops/imager_jax.py::fused_score_cost_model``).  The output is a bound —

    headroom_x = measured_seconds / max(bytes/peak_bw, flops/peak_flops)

— an UPPER bound on what any further tuning of the same algorithm could
recover (the model prices no padding, recompiles, or dispatch, and the
peaks are microbenchmark ceilings).

Usage::

    JAX_PLATFORMS=cpu python scripts/roofline_probe.py --tiny   # CI smoke
    python scripts/roofline_probe.py                            # bench case
    python scripts/roofline_probe.py --nrows 512 --ncols 512 \
        --n-formulas 500 --formula-batch 256                    # DESI case

Prints ONE JSON line on stdout; logs to stderr.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).parent.parent))


def _median(xs):
    s = sorted(xs)
    return s[len(s) // 2]


def measure_device_peaks(bw_mb: int = 256, mm_n: int = 2048) -> dict:
    """Microbenchmark ceilings on the CURRENT device: effective bandwidth of
    a reduction and an elementwise copy over a ``bw_mb``-MB f32 array, and
    f32 (HIGHEST — the engine's matmul precision) matmul throughput."""
    import jax
    import jax.numpy as jnp

    n = bw_mb * (1 << 20) // 4
    x = jnp.arange(n, dtype=jnp.float32)
    red = jax.jit(lambda v: v.sum())
    cpy = jax.jit(lambda v: v * 2.0)
    red(x).block_until_ready()
    cpy(x).block_until_ready()
    red_dts, cpy_dts = [], []
    for _ in range(5):
        t0 = time.perf_counter()
        red(x).block_until_ready()
        red_dts.append(time.perf_counter() - t0)
        t0 = time.perf_counter()
        cpy(x).block_until_ready()
        cpy_dts.append(time.perf_counter() - t0)
    red_bw = 4 * n / _median(red_dts)            # bytes read
    cpy_bw = 12 * n / _median(cpy_dts)           # read + write (+RFO on CPU)

    a = jnp.ones((mm_n, mm_n), jnp.float32)
    mm = jax.jit(lambda u, v: jnp.dot(
        u, v, precision=jax.lax.Precision.HIGHEST))
    mm(a, a).block_until_ready()
    mm_dts = []
    for _ in range(3):
        t0 = time.perf_counter()
        mm(a, a).block_until_ready()
        mm_dts.append(time.perf_counter() - t0)
    flops = 2.0 * mm_n**3 / _median(mm_dts)
    return dict(
        peak_reduction_gbps=red_bw / 1e9,
        peak_copy_gbps=cpy_bw / 1e9,
        peak_bw_gbps=max(red_bw, cpy_bw) / 1e9,
        peak_matmul_gflops=flops / 1e9,
        device=str(jax.devices()[0]),
    )


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--nrows", type=int, default=64)
    ap.add_argument("--ncols", type=int, default=64)
    ap.add_argument("--n-formulas", type=int, default=250)
    ap.add_argument("--formula-batch", type=int, default=2048)
    ap.add_argument("--decoy-sample-size", type=int, default=20)
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--tiny", action="store_true",
                    help="CI smoke shape (16x16 px, 8 formulas, tiny "
                         "microbenches)")
    ap.add_argument("--fused", choices=("auto", "on", "off"), default="auto",
                    help="parallel.fused_metrics for the probed backend "
                         "(ISSUE 18; 'on' forces the Pallas kernel, "
                         "interpret-mode off-TPU)")
    ap.add_argument("--cube-dtype", choices=("f32", "bf16", "int8"),
                    default="f32",
                    help="parallel.cube_dtype for the probed backend")
    ap.add_argument("--min-frac", type=float, default=0.0,
                    help="exit nonzero unless roofline_frac (= floor_s / "
                         "measured_s) >= this — the check_tier1 gate that "
                         "keeps measured-vs-model from regressing "
                         "catastrophically on whatever hardware runs CI")
    args = ap.parse_args()
    if args.tiny:
        args.nrows = args.ncols = 16
        args.n_formulas = 8
        args.formula_batch = 64
        args.decoy_sample_size = 4
        args.reps = 1

    from bench import BenchConfig, prepare
    from sm_distributed_tpu.models.msm_basic import make_backend
    from sm_distributed_tpu.ops.imager_jax import fused_score_cost_model
    from sm_distributed_tpu.utils.config import SMConfig
    from sm_distributed_tpu.utils.logger import init_logger, logger

    init_logger()
    cache_dir = Path(__file__).parent.parent / ".cache"
    cfg = BenchConfig("roofline", args.nrows, args.ncols, args.n_formulas,
                      args.formula_batch, args.decoy_sample_size,
                      reps=args.reps, baseline_ions=0)
    prep = prepare(cfg, cache_dir)
    table, ds = prep["table"], prep["ds"]

    sm_config = SMConfig.from_dict(
        {"backend": "jax_tpu",
         "fdr": {"decoy_sample_size": args.decoy_sample_size},
         "parallel": {"formula_batch": args.formula_batch,
                      "fused_metrics": args.fused,
                      "cube_dtype": args.cube_dtype,
                      "compile_cache_dir": str(cache_dir / "xla_cache")}})
    backend = make_backend("jax_tpu", ds, prep["ds_config"], sm_config,
                           table=table)
    batches = prep["batches"]
    if hasattr(backend, "warmup"):
        backend.warmup(batches)
    else:
        backend.score_batch(batches[0])

    dts = []
    for i in range(max(1, args.reps)):
        t0 = time.perf_counter()
        backend.score_batches(batches)
        dts.append(time.perf_counter() - t0)
        logger.info("rep %d: %.3fs (%.1f ions/s)", i, dts[-1],
                    table.n_ions / dts[-1])
    measured_s = _median(dts)

    peaks = measure_device_peaks(bw_mb=16 if args.tiny else 256,
                                 mm_n=256 if args.tiny else 2048)
    resident = getattr(backend, "_mz_host", None)
    resident_peaks = int(resident.size) if resident is not None else int(
        ds.n_peaks)
    # price the variant that actually dispatched: 'on' forces the fused
    # kernel everywhere; 'auto' engages it only on a real TPU
    import jax

    fused_active = args.fused == "on" or (
        args.fused == "auto" and jax.default_backend() == "tpu")
    model = fused_score_cost_model(
        n_pixels=ds.n_pixels,
        resident_peaks=resident_peaks,
        n_ions=table.n_ions,
        max_peaks=table.max_peaks,
        formula_batch=args.formula_batch,
        nlevels=prep["ds_config"].image_generation.nlevels,
        ordered=True,
        fused=fused_active,
        cube_dtype=args.cube_dtype,
    )
    t_bw = model["total_bytes"] / (peaks["peak_bw_gbps"] * 1e9)
    t_fl = model["matmul_flops"] / (peaks["peak_matmul_gflops"] * 1e9)
    floor_s = max(t_bw, t_fl)
    frac = floor_s / measured_s if measured_s > 0 else 0.0
    int_bytes = {"f32": 4, "bf16": 2, "int8": 1}[args.cube_dtype]
    out = {
        "metric": "fused_score_roofline",
        "measured_s_per_rep": round(measured_s, 4),
        "ions_per_s": round(table.n_ions / measured_s, 1),
        "model": model,
        "peaks": {k: round(v, 2) for k, v in peaks.items()
                  if isinstance(v, float)},
        "device": peaks["device"],
        "roofline_floor_s": round(floor_s, 4),
        "bound": "bandwidth" if t_bw >= t_fl else "compute",
        "headroom_x": round(measured_s / floor_s, 2) if floor_s > 0 else None,
        "roofline_frac": round(frac, 4),
        "fused": bool(fused_active),
        "cube_dtype": args.cube_dtype,
        "resident_cube_bytes": int(resident_peaks * int_bytes),
        "n_ions": int(table.n_ions),
        "n_pixels": int(ds.n_pixels),
        "resident_peaks": resident_peaks,
    }
    print(json.dumps(out))
    if args.min_frac and frac < args.min_frac:
        logger.error(
            "roofline_frac %.4f below gate --min-frac %.4f "
            "(measured %.4fs vs model floor %.4fs)",
            frac, args.min_frac, measured_s, floor_s)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
