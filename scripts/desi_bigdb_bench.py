"""BASELINE eval config #5 at its FULL definition (VERDICT r4 item 4).

Whole-slide pixels x big molecular DB in ONE measured end-to-end job:
512x512 px = 262,144 pixels (~279M dataset peaks) scored against ~80k
formulas x (1 target + 20 decoy) adducts = ~1.68M ions — "DESI whole-slide
high-res, ChEBI + 20 decoy adducts" (SURVEY.md §6 config #5 [U]).  The
default bench's ``desi`` case runs the same pixel count at 500 formulas;
the cold-path script runs the same DB at 100x100 px; this is the first
measurement that combines both axes, which is where the HBM plan (pre-run
estimate ~2.2 GB resident peaks + per-batch band scratch; the measured run
came to 1.95 GB after window-union restriction — docs/PERF.md), the sticky
band-bucket ladder over ~6.5k batches, and sustained-stream throughput
actually get stressed.

Reuses the default bench's 512x512 fixture (same generator parameters) and
the cold-path run's isocalc shard cache when present (same formula list,
adducts and FDR seed => identical (formula, adduct) pairs).  Run it solo
AFTER scripts/cold_path_bench.py for a warm-pattern measurement; pass a
fresh --work-dir for a cold one.

Prints ONE JSON line; logs to stderr.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).parent.parent))


def run(*, n_formulas: int, nrows: int, ncols: int, decoy_sample_size: int,
        formula_batch: int, checkpoint_every: int, cache_dir: Path,
        work_dir: Path | None = None, fixture_formulas: int = 500,
        noise_peaks: int = 200) -> dict:
    from sm_distributed_tpu.engine.search_job import SearchJob
    from sm_distributed_tpu.io.fixtures import (
        expand_formula_list,
        generate_synthetic_dataset,
    )
    from sm_distributed_tpu.utils.config import DSConfig, SMConfig
    from sm_distributed_tpu.utils.logger import logger

    cache_dir = Path(cache_dir)
    work_dir = Path(work_dir or cache_dir / "cold_path" / "work")

    # the default bench's DESI fixture, bit for bit (generator params from
    # bench.py::prepare) — the slide holds signal for ``fixture_formulas``
    # formulas; the other ~79.5k scored formulas still pay full extraction
    # + decoy ranking, which is the config-#5 point
    t0 = time.perf_counter()
    ds_path, _truth = generate_synthetic_dataset(
        cache_dir / f"bench_ds_{nrows}x{ncols}_f{fixture_formulas}",
        nrows=nrows, ncols=ncols,
        formulas=expand_formula_list(fixture_formulas),
        present_fraction=0.6, noise_peaks=noise_peaks, seed=7, reuse=True)
    logger.info("fixture: %dx%d px (%.1fs)", nrows, ncols,
                time.perf_counter() - t0)

    sm_config = SMConfig.from_dict({
        "backend": "jax_tpu",
        "fdr": {"decoy_sample_size": decoy_sample_size},
        "storage": {"results_dir": str(cache_dir / "desi_bigdb" / "results"),
                    "store_images": False},
        "work_dir": str(work_dir),
        "parallel": {"formula_batch": formula_batch,
                     "checkpoint_every": checkpoint_every,
                     "compile_cache_dir": str(cache_dir / "xla_cache")},
    })
    ds_config = DSConfig.from_dict({
        "isotope_generation": {"adducts": ["+H"]},
        "image_generation": {"ppm": 3.0},
    })
    formulas = expand_formula_list(n_formulas)

    t0 = time.perf_counter()
    job = SearchJob("desi_bigdb", "desi-bigdb-config5", ds_path, ds_config,
                    sm_config, formulas=formulas)
    bundle = job.run()
    wall = time.perf_counter() - t0

    t = bundle.timings
    n_ions = int(bundle.all_metrics.shape[0])
    score_s = t.get("score", 0.0)
    return {
        "metric": "desi_bigdb_config5_wall_clock",
        "unit": "s",
        "value": round(wall, 1),
        "n_formulas": n_formulas,
        "n_ions": n_ions,
        "n_pixels": nrows * ncols,
        "score_s": round(score_s, 1),
        "score_ions_per_s": round(n_ions / score_s, 1) if score_s else None,
        "isocalc_s": round(t.get("isotope_patterns", 0.0), 1),
        "phases_s": {k: round(v, 1) for k, v in sorted(t.items())},
        "n_annotations_fdr10": int((bundle.annotations["fdr"] <= 0.1).sum())
        if len(bundle.annotations) else 0,
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--n-formulas", type=int, default=80_000)
    ap.add_argument("--nrows", type=int, default=512)
    ap.add_argument("--ncols", type=int, default=512)
    ap.add_argument("--decoy-sample-size", type=int, default=20)
    ap.add_argument("--formula-batch", type=int, default=256,
                    help="256 keeps the flat-path histogram scratch inside "
                         "the HBM guard at 262k pixels (bench.py desi)")
    ap.add_argument("--checkpoint-every", type=int, default=64,
                    help="batches per checkpoint group (64 -> a group "
                         "boundary sync every ~16k ions; also exercises "
                         "mid-search checkpointing at BASELINE #5 scale)")
    ap.add_argument("--work-dir", default="",
                    help="job work dir (default: .cache/cold_path/work — "
                         "SHARES the cold-path run's isocalc shard cache)")
    args = ap.parse_args()

    from sm_distributed_tpu.utils.logger import init_logger

    init_logger()
    out = run(
        n_formulas=args.n_formulas, nrows=args.nrows, ncols=args.ncols,
        decoy_sample_size=args.decoy_sample_size,
        formula_batch=args.formula_batch,
        checkpoint_every=args.checkpoint_every,
        cache_dir=Path(__file__).parent.parent / ".cache",
        work_dir=Path(args.work_dir) if args.work_dir else None,
    )
    print(json.dumps(out))


if __name__ == "__main__":
    sys.exit(main())
