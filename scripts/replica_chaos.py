#!/usr/bin/env python
"""Multi-replica failover chaos harness (ISSUE 8 proof).

Runs N=3 real scheduler replicas — separate PROCESSES sharing one
partitioned spool — over a batch of real SearchJobs, kills one replica at
a chosen failpoint (mid-claim, mid-score, mid-commit, mid-heartbeat,
mid-takeover, or silently degraded into a fence race), and asserts the
exactly-once convergence invariants:

- every published message ends in ``done/`` exactly once — zero lost,
  zero duplicated, zero double-completed jobs;
- every dataset's stored annotations + all-metrics equal the fault-free
  golden report;
- the ledger holds no STARTED rows and each dataset's newest job is
  FINISHED; the annotation index row count matches golden per dataset;
- zero fence violations: every fence rejection the victim suffered is a
  HANDLED abort (logged + counted), never a write that landed — proven by
  the two invariants above plus the victim's own log evidence;
- no tmp/heartbeat/lease debris anywhere (surviving checkpoint shards
  from a fenced-out attempt are legitimate resume state and excluded,
  same rule as scripts/load_sweep.py);
- survivors demonstrably adopted the victim's shards
  (``sm_replica_shards_owned`` sums to the full partition across the
  survivors' exit metrics dumps) and, where the victim died holding
  claims, fenced + requeued them (``sm_replica_takeover_requeues_total``).

Usage::

    python scripts/replica_chaos.py            # full sweep, every scenario
    python scripts/replica_chaos.py --smoke    # 2-scenario CI gate
    python scripts/replica_chaos.py --only score_crash,fence_race
    python scripts/replica_chaos.py --list

Internal subcommand (the replica worker process)::

    python scripts/replica_chaos.py --replica-serve QUEUE_DIR SM_CONF \\
        --replica-id rX [--idle-exit S] [--metrics-dump FILE] \\
        [--bare --null-sleep S]

``--bare`` runs a plain JobScheduler with a null (sleep) callback instead
of the full AnnotationService — scripts/load_sweep.py uses it for its
10k-tenant multi-replica mix where job CONTENT is irrelevant.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import time
from dataclasses import dataclass
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO_ROOT))

from scripts.chaos_sweep import FIXTURE, _debris, _deep_merge  # noqa: E402
from sm_distributed_tpu.engine.daemon import (  # noqa: E402
    QUEUE_ANNOTATE,
    QueuePublisher,
    _STATES,
)
from sm_distributed_tpu.engine.storage import JobLedger  # noqa: E402
from sm_distributed_tpu.io.fixtures import generate_synthetic_dataset  # noqa: E402
from sm_distributed_tpu.service.leases import owned_shards, shard_of  # noqa: E402

CRASH_RC = 21
REPLICAS = ("r0", "r1", "r2")           # r0 is always the victim
VICTIM = "r0"
N_JOBS = 9
SHARDS = 8

SM_TEMPLATE = {
    "backend": "numpy_ref",
    "fdr": {"decoy_sample_size": 8, "seed": 42},
    "parallel": {"formula_batch": 16, "checkpoint_every": 2,
                 "resident_datasets": 2, "order_ions": "table"},
    "storage": {"store_images": False},
    "service": {"workers": 2, "poll_interval_s": 0.05, "job_timeout_s": 60.0,
                "max_attempts": 3, "backoff_base_s": 0.05,
                "backoff_max_s": 0.2, "backoff_jitter": 0.05,
                "heartbeat_interval_s": 0.2, "stale_after_s": 1.0,
                "drain_timeout_s": 10.0, "http_port": 0,
                # crash-looping fence cycles bump claims; keep quarantine
                # out of the way (the chaos here is replica death, not
                # poison jobs)
                "quarantine_after": 20,
                "replicas": len(REPLICAS), "spool_shards": SHARDS,
                "replica_heartbeat_interval_s": 0.25,
                "replica_stale_after_s": 1.0,
                "takeover_interval_s": 0.3},
}


@dataclass
class Scenario:
    """Kill (or degrade) the victim replica at one failpoint."""

    name: str
    spec: str                     # SM_FAILPOINTS armed on the VICTIM only
    note: str = ""
    expect_crash: bool = True     # victim must exit with the crash rc
    expect_fence: bool = False    # victim must log a handled fence abort
    expect_takeover: bool = True  # survivors must fence+requeue its claims
    # SIGSTOP the victim once it claims, SIGCONT after convergence — a GC
    # pause / network partition: the woken victim must find itself fenced
    stop_resume: bool = False
    # a crash AFTER the ledger commit but BEFORE the done/ ack makes the
    # survivor's idempotent rerun legitimate: the dataset then carries two
    # FINISHED rows with identical results (lost-ack redelivery, same as
    # RabbitMQ).  Everywhere else >1 FINISHED row = a double completion.
    allow_rerun_finished: bool = False


SCENARIOS: list[Scenario] = [
    Scenario("score_crash", "device.score_batch=crash@2",
             "victim dies mid-score holding a claim"),
    Scenario("commit_crash", "storage.results_rename=crash@1",
             "victim dies mid result-commit"),
    Scenario("complete_crash", "spool.complete=crash@1",
             "victim dies after the job, before the done/ ack",
             allow_rerun_finished=True),
    Scenario("claim_crash", "lease.renew=crash@1",
             "victim dies inside a lease renewal (mid-claim)"),
    Scenario("beat_crash", "replica.heartbeat=crash@2",
             "victim dies writing its registry heartbeat",
             expect_takeover=False),   # may die before claiming anything
    Scenario("takeover_crash", "takeover.scan=crash@2",
             "victim dies inside its own takeover scan",
             expect_takeover=False),
    Scenario("fence_race", "device.score_batch=sleep:1.6",
             "victim is PAUSED mid-score (GC pause / partition emulation); "
             "survivors fence + re-claim its work, then the woken victim's "
             "commit is REJECTED, never doubled",
             expect_crash=False, expect_fence=True, stop_resume=True),
]

SMOKE = ("score_crash", "fence_race")


# ----------------------------------------------------------- replica worker
def cmd_replica_serve(args) -> int:
    """One scheduler replica process: serve until the spool stays idle
    ``--idle-exit`` seconds, then drain and dump /metrics text."""
    # lock-order detection must wrap the lock FACTORIES before the service
    # stack builds its locks (same ordering as chaos_sweep's consume-one)
    from sm_distributed_tpu.analysis import lockorder

    lockorder.enable_from_env()
    from sm_distributed_tpu.utils.config import SMConfig

    sm = SMConfig.set_path(args.sm_config)
    import dataclasses

    sm = dataclasses.replace(
        sm, service=dataclasses.replace(sm.service,
                                        replica_id=args.replica_id))
    SMConfig.set(sm)
    from sm_distributed_tpu.utils.logger import init_logger

    init_logger(None, json_logs=False)
    metrics_text = ""
    try:
        if args.bare:
            from sm_distributed_tpu.service.metrics import MetricsRegistry
            from sm_distributed_tpu.service.scheduler import JobScheduler

            sleep_s = float(args.null_sleep)

            def null_callback(msg):
                time.sleep(sleep_s)

            registry = MetricsRegistry()
            sched = JobScheduler(args.queue_dir, null_callback,
                                 config=sm.service, metrics=registry)
            sched.start()
            root = Path(args.queue_dir) / QUEUE_ANNOTATE
            idle_since = None
            while True:
                if sched.drain_complete():
                    # zero-loss drain (ISSUE 11): the fleet controller asked
                    # this replica to retire and every claim resolved
                    break
                busy = (len(list(root.glob("pending/*.json")))
                        + len(list(root.glob("running/*.json"))))
                if busy:
                    idle_since = None
                elif idle_since is None:
                    idle_since = time.time()
                elif time.time() - idle_since >= args.idle_exit:
                    break
                time.sleep(0.05)
            sched.shutdown()
            metrics_text = registry.expose()
        else:
            from sm_distributed_tpu.engine.daemon import annotate_callback
            from sm_distributed_tpu.service import AnnotationService

            service = AnnotationService(
                args.queue_dir, annotate_callback(sm), sm_config=sm)
            service.install_signal_handlers()
            service.start()
            if args.ports_dir:
                d = Path(args.ports_dir)
                d.mkdir(parents=True, exist_ok=True)
                (d / f"{args.replica_id}.port").write_text(
                    str(service.api.address[1]))
            service.run_forever(idle_timeout_s=args.idle_exit)
            metrics_text = service.metrics.expose()
    finally:
        if args.metrics_dump and metrics_text:
            Path(args.metrics_dump).parent.mkdir(parents=True, exist_ok=True)
            Path(args.metrics_dump).write_text(metrics_text)
    return 0


# ------------------------------------------------------------------ driver
def _sub_env(spec: str | None) -> dict:
    env = dict(os.environ)
    env.pop("SM_FAILPOINTS", None)
    if spec:
        env["SM_FAILPOINTS"] = spec
    # lock-order detection (ISSUE 12 satellite, matching chaos_sweep and
    # load_sweep): child replicas run with the tsan-lite detector armed —
    # a lock-order cycle anywhere in the replica stack fails the scenario
    env.setdefault("SM_LOCK_ORDER", "raise")
    return env


def build_fixture(base: Path) -> tuple[Path, list[str]]:
    fx_dir = base / "fixture"
    imzml_path, truth = generate_synthetic_dataset(fx_dir, **FIXTURE)
    return imzml_path, truth.formulas


def _write_sm(base: Path) -> Path:
    sm = _deep_merge(json.loads(json.dumps(SM_TEMPLATE)), {})
    sm["work_dir"] = str(base / "work")
    sm["storage"] = dict(sm["storage"], results_dir=str(base / "results"))
    p = base / "sm.json"
    p.write_text(json.dumps(sm, indent=2))
    return p


def _messages(imzml_path: Path, formulas: list[str],
              n: int = N_JOBS) -> list[dict]:
    return [{
        "ds_id": f"m{i}", "ds_name": f"m{i}", "msg_id": f"m{i}",
        "input_path": str(imzml_path), "formulas": formulas,
        "tenant": f"t{i % 3}",
        "ds_config": {"isotope_generation": {"adducts": ["+H"]},
                      "image_generation": {"ppm": 3.0}},
    } for i in range(n)]


def _read_report(results: Path, ds_id: str):
    import pandas as pd

    out = []
    for name in ("annotations.parquet", "all_metrics.parquet"):
        df = pd.read_parquet(results / ds_id / name)
        out.append(df.sort_values(["sf", "adduct"]).reset_index(drop=True))
    return tuple(out)


def run_golden(base: Path, imzml_path: Path, formulas: list[str]):
    """One fault-free job through one replica — the report every dataset
    must converge to."""
    gbase = base / "golden"
    gbase.mkdir(parents=True)
    sm_conf = _write_sm(gbase)
    msg = _messages(imzml_path, formulas, n=1)[0]
    QueuePublisher(gbase / "queue").publish(msg)
    rc, out = _run_replica(gbase, sm_conf, "r0", spec=None, wait=True)
    if rc != 0:
        raise RuntimeError(f"golden run failed rc={rc}:\n{out[-3000:]}")
    return _read_report(gbase / "results", "m0")


def _run_replica(base: Path, sm_conf: Path, rid: str, spec: str | None,
                 wait: bool = False, idle_exit: float = 2.0):
    log = base / "logs" / f"{rid}.log"
    log.parent.mkdir(parents=True, exist_ok=True)
    cmd = [sys.executable, str(Path(__file__).resolve()),
           "--replica-serve", str(base / "queue"), str(sm_conf),
           "--replica-id", rid, "--idle-exit", str(idle_exit),
           "--metrics-dump", str(base / "metrics" / f"{rid}.prom"),
           "--ports-dir", str(base / "ports")]
    fh = open(log, "w")
    proc = subprocess.Popen(cmd, env=_sub_env(spec), stdout=fh, stderr=fh,
                            cwd=str(REPO_ROOT))
    if not wait:
        return proc, log
    rc = proc.wait(timeout=180)
    fh.close()
    return rc, log.read_text()


def _spool_census(root: Path) -> dict:
    return {s: sorted(p.stem for p in (root / s).glob("*.json"))
            for s in _STATES}


def _http_get(port: int, path: str) -> dict:
    import urllib.request

    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=10.0) as r:
        return json.loads(r.read())


def check_invariants(base: Path, golden, msgs: list[dict],
                     errs: list[str],
                     allow_rerun_finished: bool = False) -> None:
    root = base / "queue" / QUEUE_ANNOTATE
    want = sorted(m["msg_id"] for m in msgs)
    census = _spool_census(root)
    if census["done"] != want:
        errs.append(f"spool not exactly-once done: {census}")
    others = {s: v for s, v in census.items() if s != "done" and v}
    if others:
        errs.append(f"messages left outside done/: {others}")
    # no surviving lease files for terminal messages (after the operator's
    # final orphan sweep below there must be none at all)
    from sm_distributed_tpu.service.leases import LeaseStore

    LeaseStore(root, "operator").sweep_orphans(root, max_age_s=0.0)
    leftover_leases = sorted(p.name for p in (root / "leases").glob("*.json"))
    if leftover_leases:
        errs.append(f"lease files for terminal messages: {leftover_leases}")
    # checkpoint shards a fenced-out victim kept writing are legitimate
    # resume state (load_sweep rule); everything else must be gone
    debris = [p for p in _debris([root, base / "results", base / "work"])
              if ".ckpt." not in p]
    if debris:
        errs.append(f"tmp/heartbeat/lease debris: {debris}")
    ledger = JobLedger(base / "results")
    try:
        # operator reconcile, scoped the way a takeover would be: only the
        # swept datasets, only rows from before this reconcile
        ledger.fail_stale_started(ds_ids=[m["ds_id"] for m in msgs],
                                  before=time.time())
        for m in msgs:
            ds = m["ds_id"]
            jobs = ledger.jobs(ds)
            if jobs.empty:
                errs.append(f"{ds}: no ledger rows")
                continue
            if jobs.iloc[-1].status != "FINISHED":
                errs.append(f"{ds}: newest job {jobs.iloc[-1].status}")
            n_fin = int((jobs.status == "FINISHED").sum())
            if n_fin != 1 and not (allow_rerun_finished and n_fin == 2):
                # >1 FINISHED for one message = a double completion the
                # fences failed to stop (the "zero fence violations" gate);
                # exception: a lost-ack rerun scenario legitimately leaves 2
                errs.append(f"{ds}: {n_fin} FINISHED rows (double "
                            f"completion)")
            idx = ledger._conn.execute(
                "SELECT COUNT(*) FROM annotation WHERE ds_id=?",
                (ds,)).fetchone()[0]
            if idx != len(golden[0]):
                errs.append(f"{ds}: index rows {idx} != golden "
                            f"{len(golden[0])}")
    finally:
        ledger.close()
    import pandas as pd

    for m in msgs:
        try:
            got = _read_report(base / "results", m["ds_id"])
        except Exception as exc:
            errs.append(f"{m['ds_id']}: unreadable results: {exc}")
            continue
        for label, g, w in (("annotations", got[0], golden[0]),
                            ("all_metrics", got[1], golden[1])):
            try:
                pd.testing.assert_frame_equal(g, w, rtol=1e-9, atol=1e-12)
            except AssertionError as e:
                errs.append(f"{m['ds_id']}: {label} differ: "
                            f"{str(e).splitlines()[-1]}")


def _metric_value(text: str, prefix: str) -> float:
    total = 0.0
    for line in text.splitlines():
        if line.startswith(prefix):
            try:
                total += float(line.rsplit(" ", 1)[1])
            except (IndexError, ValueError):
                pass
    return total


def run_scenario(sc: Scenario, work: Path, imzml_path: Path,
                 formulas: list[str], golden, verbose: bool = False) -> dict:
    base = work / sc.name
    base.mkdir(parents=True)
    sm_conf = _write_sm(base)
    msgs = _messages(imzml_path, formulas)
    # precondition: the victim must own at least one published message's
    # shard, or the armed seams never execute
    victim_shards = owned_shards(VICTIM, set(REPLICAS), SHARDS)
    victim_msgs = [m["msg_id"] for m in msgs
                   if shard_of(m["msg_id"], SHARDS) in victim_shards]
    assert victim_msgs, "fixture msg ids never land on the victim's shards"
    pub = QueuePublisher(base / "queue")
    for m in msgs:
        pub.publish(m)
    procs = {}
    result = {"scenario": sc.name, "spec": sc.spec, "ok": False}
    root = base / "queue" / QUEUE_ANNOTATE
    t0 = time.time()
    try:
        procs[VICTIM], victim_log_path = _run_replica(
            base, sm_conf, VICTIM, spec=sc.spec, idle_exit=2.0)
        if sc.stop_resume:
            # deterministic staging: let the victim (alone) claim and START
            # SCORING one of its messages, then freeze it mid-batch — the
            # emulated GC pause / network partition.  Survivors start only
            # after the freeze, see its heartbeats go stale, fence its
            # claims, and re-run them.
            deadline = time.time() + 90.0
            while time.time() < deadline:
                if "FAILPOINT-FIRED name=device.score_batch" in \
                        victim_log_path.read_text():
                    break
                if procs[VICTIM].poll() is not None:
                    result["error"] = "victim exited before scoring"
                    return result
                time.sleep(0.05)
            else:
                result["error"] = "victim never started scoring"
                return result
            procs[VICTIM].send_signal(signal.SIGSTOP)
        for rid in REPLICAS:
            if rid != VICTIM:
                procs[rid], _ = _run_replica(base, sm_conf, rid, spec=None,
                                             idle_exit=2.0)
        # liveness probe through a survivor's admin API: /peers must list
        # every replica once their registrations land
        need_ids = set(REPLICAS)
        deadline = time.time() + 120.0
        peers_seen = False
        while time.time() < deadline:
            if not peers_seen:
                port_file = base / "ports" / "r1.port"
                if port_file.exists():
                    try:
                        peers = _http_get(int(port_file.read_text()),
                                          "/peers")
                        ids = {p.get("replica_id")
                               for p in peers.get("replicas", [])}
                        peers_seen = need_ids <= ids
                    except OSError:
                        pass
            done = len(list((root / "done").glob("*.json")))
            if done >= len(msgs):
                break
            if all(p.poll() is not None for p in procs.values()):
                result["error"] = ("all replicas exited with "
                                   f"{[p.poll() for p in procs.values()]} "
                                   f"before convergence ({done}/{len(msgs)})")
                return result
            time.sleep(0.1)
        else:
            result["error"] = (f"did not converge in 120s: "
                               f"{_spool_census(root)}")
            return result
        result["converge_s"] = round(time.time() - t0, 1)
        if sc.stop_resume:
            # wake the paused victim: it must discover it was fenced out
            # and abandon its in-flight commit
            procs[VICTIM].send_signal(signal.SIGCONT)
        if not peers_seen:
            result["error"] = "/peers on a survivor never listed all replicas"
            return result
        # replicas idle-exit on their own; the victim crashed (or, in the
        # fence race, survives to exit cleanly)
        for rid, p in procs.items():
            try:
                rc = p.wait(timeout=30)
            except subprocess.TimeoutExpired:
                p.send_signal(signal.SIGTERM)
                rc = p.wait(timeout=30)
            result[f"rc_{rid}"] = rc
        if sc.expect_crash and result[f"rc_{VICTIM}"] != CRASH_RC:
            result["error"] = (f"victim expected crash rc={CRASH_RC}, got "
                               f"{result[f'rc_{VICTIM}']}")
            return result
        victim_log = (base / "logs" / f"{VICTIM}.log").read_text()
        if f"FAILPOINT-FIRED name={sc.spec.split('=')[0]}" not in victim_log:
            result["error"] = "victim's armed failpoint never fired"
            return result
        if sc.expect_fence and "fence REJECTED" not in victim_log \
                and "fenced out" not in victim_log:
            result["error"] = ("fence race produced no handled rejection "
                               "on the victim")
            return result
        errs: list[str] = []
        check_invariants(base, golden, msgs, errs,
                         allow_rerun_finished=sc.allow_rerun_finished)
        # survivors' exit metrics: full shard coverage + (where the victim
        # died holding claims) at least one fenced takeover requeue
        survivors_owned = 0.0
        takeovers = 0.0
        for rid in REPLICAS:
            if rid == VICTIM:
                continue
            dump = base / "metrics" / f"{rid}.prom"
            if not dump.exists():
                errs.append(f"{rid}: no metrics dump")
                continue
            text = dump.read_text()
            if f'sm_replica_up{{replica="{rid}"}}' not in text:
                errs.append(f"{rid}: sm_replica_up missing/unlabeled")
            survivors_owned += _metric_value(
                text, f'sm_replica_shards_owned{{replica="{rid}"}}')
            takeovers += _metric_value(
                text, f'sm_replica_takeover_requeues_total{{replica="{rid}"}}')
        if sc.expect_crash and survivors_owned < SHARDS:
            errs.append(f"survivors own {survivors_owned}/{SHARDS} shards "
                        "after the victim's death")
        if sc.expect_takeover and takeovers < 1:
            errs.append("survivors recorded no takeover requeues")
        if errs:
            result["error"] = "; ".join(errs)
            return result
        result["ok"] = True
        return result
    finally:
        for p in procs.values():
            if p.poll() is None:
                p.kill()


def run_sweep(work: Path, only: list[str] | None = None,
              verbose: bool = False) -> list[dict]:
    os.environ.pop("SM_FAILPOINTS", None)
    names = {sc.name for sc in SCENARIOS}
    if only is not None and not set(only) <= names:
        raise RuntimeError(f"unknown scenario names: {set(only) - names}")
    scenarios = SCENARIOS if only is None else [
        sc for sc in SCENARIOS if sc.name in only]
    work.mkdir(parents=True, exist_ok=True)
    imzml_path, formulas = build_fixture(work)
    t0 = time.time()
    golden = run_golden(work, imzml_path, formulas)
    print(f"golden report: {len(golden[0])} annotations, "
          f"{len(golden[1])} scored ions ({time.time() - t0:.1f}s)")
    results = []
    for sc in scenarios:
        t0 = time.time()
        r = run_scenario(sc, work, imzml_path, formulas, golden,
                         verbose=verbose)
        r["seconds"] = round(time.time() - t0, 1)
        status = "OK " if r["ok"] else "FAIL"
        print(f"[{status}] {sc.name:<16} {r['seconds']:>5.1f}s  {sc.note}")
        if not r["ok"]:
            print(f"       spec: {sc.spec}\n       error: {r.get('error')}")
        results.append(r)
    n_ok = sum(r["ok"] for r in results)
    print(f"replica chaos: {n_ok}/{len(results)} scenarios converged with "
          f"exactly-once outcomes")
    return results


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--work", default=None)
    ap.add_argument("--smoke", action="store_true",
                    help=f"CI subset: {', '.join(SMOKE)}")
    ap.add_argument("--only", default=None)
    ap.add_argument("--list", action="store_true", dest="list_scenarios")
    ap.add_argument("--keep", action="store_true")
    ap.add_argument("-v", "--verbose", action="store_true")
    ap.add_argument("--replica-serve", nargs=2,
                    metavar=("QUEUE_DIR", "SM_CONFIG"))
    ap.add_argument("--replica-id", default="r0")
    ap.add_argument("--idle-exit", type=float, default=2.0)
    ap.add_argument("--metrics-dump", default=None)
    ap.add_argument("--ports-dir", default=None)
    ap.add_argument("--bare", action="store_true")
    ap.add_argument("--null-sleep", type=float, default=0.002)
    args = ap.parse_args(argv)

    if args.replica_serve:
        args.queue_dir, args.sm_config = args.replica_serve
        return cmd_replica_serve(args)
    if args.list_scenarios:
        for sc in SCENARIOS:
            print(f"{sc.name:<16} {sc.spec:<70} {sc.note}")
        return 0
    only = list(SMOKE) if args.smoke else (
        args.only.split(",") if args.only else None)
    import shutil
    import tempfile

    work = Path(args.work) if args.work else Path(
        tempfile.mkdtemp(prefix="sm_replica_chaos_"))
    try:
        results = run_sweep(work, only=only, verbose=args.verbose)
    finally:
        if not args.keep and args.work is None:
            shutil.rmtree(work, ignore_errors=True)
    return 0 if all(r["ok"] for r in results) else 1


if __name__ == "__main__":
    sys.exit(main())
