"""Benchmark: ions scored per second per chip (jax_tpu fused graph).

Primary metric per BASELINE.json ("formulas scored/sec/chip"): throughput of
the fused extract+score XLA graph — ion-image extraction + MSM metrics
(chaos, spatial, spectral) — over a synthetic spheroid-like dataset.
``vs_baseline`` is the speedup over the numpy_ref backend on the same
workload (the measured stand-in for the reference's Spark executor; the
reference publishes no numbers — SURVEY.md §6, BASELINE.json "published": {}).

Three configs run by default and land in the ONE JSON line:

- headline: 64x64 px, 250 formulas (the round-over-round comparison case);
- ``scale``: 256x256 px, 500 formulas, ~70M peaks — the high-res end of
  the BASELINE #5 regime (round-2 weak spot, VERDICT r2 item 1);
- ``desi``: 512x512 px = 262,144 pixels — BASELINE #5's actual ">200k
  pixel" whole-slide scale (VERDICT r3 item 1), run at formula_batch=256
  so the flat-path histogram scratch stays under the HBM guard.

Floor protocol (VERDICT r3 item 2 — pinned so ratio claims stop wobbling):
the numpy floor is measured over a FIXED deterministic ion sample (1,000
ions for headline/scale, 300 for desi — drawn evenly across each ion
table, so the target/decoy mix matches), timed median-of-7 with the
relative spread (max-min)/median reported in the JSON; same-run floors
only — vs_baseline never mixes runs.  Floors run single-core AND over a
fork pool on all cores (this container has one core, so the two coincide
here).  All floor pools fork BEFORE any JAX work — forking after a PJRT
client exists is unsupported and can deadlock.

Prints ONE JSON line on stdout; all logging goes to stderr.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys
import time
from pathlib import Path

import numpy as np

# module globals inherited by fork()ed floor workers (COW — the sorted peak
# view is NOT re-built or copied per worker)
_NP_BACKEND = None
_NP_TABLE = None


def _floor_worker(bounds: tuple[int, int]) -> int:
    """Score one slice of the floor table in a forked worker."""
    from sm_distributed_tpu.models.msm_basic import _slice_table

    s, e = bounds
    _NP_BACKEND.score_batch(_slice_table(_NP_TABLE, s, e))
    return e - s


from dataclasses import dataclass  # noqa: E402

# known-transient warmup failures worth ONE retry (ADVICE r5): the tunneled
# TPU's remote-compile transport occasionally drops a response mid-read.
# Anything else (misconfig, OOM, compile error) fails fast — retrying those
# only hides the bug and inflates compile_s.
_TRANSIENT_WARMUP_MARKERS = (
    "response body closed before all bytes were read",
    "connection reset",
    "broken pipe",
    "socket closed",
    "deadline exceeded",
)


def _is_transient_warmup_error(exc: BaseException) -> bool:
    text = f"{type(exc).__name__}: {exc}".lower()
    return any(marker in text for marker in _TRANSIENT_WARMUP_MARKERS)


@dataclass
class BenchConfig:
    name: str
    nrows: int
    ncols: int
    n_formulas: int
    formula_batch: int
    decoy_sample_size: int
    reps: int
    baseline_ions: int


def prepare(cfg: BenchConfig, cache_dir: Path):
    """Dataset + ion table + batches + numpy backend — NO jax involved."""
    from sm_distributed_tpu.io.dataset import SpectralDataset
    from sm_distributed_tpu.io.fixtures import (
        expand_formula_list,
        generate_synthetic_dataset,
    )
    from sm_distributed_tpu.models.msm_basic import NumpyBackend, _slice_table
    from sm_distributed_tpu.ops.fdr import FDR
    from sm_distributed_tpu.ops.isocalc import IsocalcWrapper, IsotopePatternTable
    from sm_distributed_tpu.utils.config import DSConfig
    from sm_distributed_tpu.utils.logger import logger

    t0 = time.perf_counter()
    formulas = expand_formula_list(cfg.n_formulas)
    work_dir = cache_dir / f"bench_ds_{cfg.nrows}x{cfg.ncols}_f{cfg.n_formulas}"
    path, truth = generate_synthetic_dataset(
        work_dir, nrows=cfg.nrows, ncols=cfg.ncols,
        formulas=formulas, present_fraction=0.6, noise_peaks=200, seed=7,
        reuse=True,
    )
    ds = SpectralDataset.from_imzml(path)
    logger.info("[%s] dataset: %dx%d px, %d peaks (%.1fs)",
                cfg.name, ds.nrows, ds.ncols, ds.n_peaks,
                time.perf_counter() - t0)

    ds_config = DSConfig.from_dict(
        {"isotope_generation": {"adducts": ["+H"]},
         "image_generation": {"ppm": 3.0}})
    fdr = FDR(decoy_sample_size=cfg.decoy_sample_size,
              target_adducts=("+H",), seed=42)
    assignment = fdr.decoy_adduct_selection(truth.formulas)
    pairs, flags = assignment.all_ion_tuples(truth.formulas, ("+H",))
    calc = IsocalcWrapper(ds_config.isotope_generation,
                          cache_dir=str(cache_dir / "isocalc"))
    t0 = time.perf_counter()
    table = calc.pattern_table(pairs, flags)
    isocalc_dt = time.perf_counter() - t0
    logger.info("[%s] isotope patterns: %d ions (%.1fs)",
                cfg.name, table.n_ions, isocalc_dt)
    # production auto ordering (parallel.order_ions): m/z-ordered streams
    # at >=6 batches make window unions m/z-localized bands (the band-slice
    # variant's regime); small streams keep targets-first.  Per-ion results
    # are identical in any order; the floor scores the same per-ion work
    # either way.
    from sm_distributed_tpu.models.msm_basic import maybe_order_table

    table = maybe_order_table(table, "auto", cfg.formula_batch)

    b = cfg.formula_batch
    batches = [_slice_table(table, s, min(s + b, table.n_ions))
               for s in range(0, table.n_ions, b)]
    # floor subset: even spread across the table -> same target/decoy mix
    n_base = min(cfg.baseline_ions, table.n_ions)
    sel = np.unique(np.linspace(0, table.n_ions - 1, n_base).astype(int))
    sub = IsotopePatternTable(
        sfs=[table.sfs[i] for i in sel],
        adducts=[table.adducts[i] for i in sel],
        mzs=table.mzs[sel], ints=table.ints[sel],
        n_valid=table.n_valid[sel], targets=table.targets[sel],
    )
    np_backend = NumpyBackend(ds, ds_config)
    return dict(ds=ds, ds_config=ds_config, table=table, batches=batches,
                sub=sub, np_backend=np_backend, isocalc_dt=isocalc_dt,
                pairs=pairs, flags=flags)


def measure_isocalc_cold(cfg: BenchConfig, prep: dict, n_procs: int,
                         device: bool) -> dict:
    """Cold-path generation throughput (ISSUE 3 pinned fields): regenerate
    the case's full ion set with NO cache, through the production chunk
    pipeline (pool + optional device blur), and report wall/workers/rate.
    Runs after the floors (spawn-based: safe beside JAX either way)."""
    from sm_distributed_tpu.ops.isocalc import IsocalcWrapper
    from sm_distributed_tpu.utils.logger import logger

    calc = IsocalcWrapper(prep["ds_config"].isotope_generation,
                          cache_dir=None, n_procs=n_procs,
                          device_blur=device or None)
    t0 = time.perf_counter()
    calc.pattern_table(prep["pairs"], prep["flags"])
    dt = time.perf_counter() - t0
    stats = calc.last_stats
    logger.info("[%s] cold isocalc: %d patterns in %.1fs -> %.1f patterns/s "
                "(%d workers%s)", cfg.name, stats.get("cold_patterns", 0), dt,
                stats.get("patterns_per_s", 0.0), stats.get("workers", 1),
                ", device blur" if stats.get("device") else "")
    return dict(isocalc_cold_s=dt,
                isocalc_workers=stats.get("workers", 1),
                patterns_per_s=stats.get("patterns_per_s", 0.0))


def measure_floor(cfg: BenchConfig, prep: dict, n_procs: int) -> dict:
    """Single-core (median of 3) + fork-pool floors — still no jax."""
    from sm_distributed_tpu.models.msm_basic import _slice_table
    from sm_distributed_tpu.utils.logger import logger

    np_backend, sub = prep["np_backend"], prep["sub"]
    np_backend.score_batch(_slice_table(prep["table"], 0, 2))  # warm caches
    # ONE untimed full-sample rep first: the timed reps must measure
    # compute, not first-touch page faults over the (up to ~500 MB) sorted
    # peak table — without this the first rep ran ~2x slow and the reported
    # spread was 30-90% (r4 measurement); with it the spread is the core's
    # genuine jitter
    np_backend.score_batch(sub)
    # median of 7 over a fixed >=300-ion sample: the shared-host core's
    # floor swung ~±25% run to run in round 3 on a 300-ion/5-rep protocol;
    # the pinned protocol reports its own within-run spread so every ratio
    # carries its error bar (VERDICT r3 item 2)
    np_dts = []
    for _ in range(7):
        t0 = time.perf_counter()
        np_backend.score_batch(sub)
        np_dts.append(time.perf_counter() - t0)
    srt = sorted(np_dts)
    np_dt = srt[3]
    np_rate = sub.n_ions / np_dt
    # two spreads: raw max-min (hostage to single scheduler outliers on a
    # shared host — measured medians across whole runs agree to ~0.5%
    # while raw spread swings 28-90%) and the middle-5 spread, which is
    # the core's genuine jitter and the error bar that matters for the
    # median-based ratio
    spread = (srt[-1] - srt[0]) / np_dt
    spread_mid5 = (srt[-2] - srt[1]) / np_dt
    logger.info("[%s] numpy_ref: %d ions in %.2fs (median of 7, mid-5 "
                "spread %.1f%%, raw %.1f%%) -> %.1f ions/s",
                cfg.name, sub.n_ions, np_dt, 100 * spread_mid5,
                100 * spread, np_rate)

    if n_procs > 1:
        import multiprocessing as mp

        global _NP_BACKEND, _NP_TABLE
        _NP_BACKEND, _NP_TABLE = np_backend, sub
        # every worker scores the FULL floor table (>= a single-core
        # workload per worker, so fork/dispatch overhead can't dominate);
        # pool startup is excluded and the timing is median-of-3 like the
        # single-core floor
        jobs = [(0, sub.n_ions)] * n_procs
        ctx = mp.get_context("fork")   # COW-share the sorted peak view
        with ctx.Pool(n_procs) as pool:
            pool.map(_floor_worker, [(0, 1)] * n_procs)   # warm the pool
            mp_dts = []
            for _ in range(3):
                t0 = time.perf_counter()
                done = sum(pool.map(_floor_worker, jobs))
                mp_dts.append(time.perf_counter() - t0)
        mp_dt = sorted(mp_dts)[1]
        mp_rate = done / mp_dt
        logger.info("[%s] numpy_ref x%d procs: %d ions in %.2fs (median of 3)"
                    " -> %.1f ions/s", cfg.name, n_procs, done, mp_dt, mp_rate)
    else:
        mp_rate = np_rate              # single-core host: floors coincide
        logger.info("[%s] single-core host: multi-process floor == "
                    "single-core floor", cfg.name)
    return dict(np_rate=np_rate, mp_rate=mp_rate, n_procs=n_procs,
                floor_n_ions=int(sub.n_ions), floor_spread=spread,
                floor_spread_mid5=spread_mid5)


def measure_cold(cfg: BenchConfig, prep: dict, cache_dir: Path) -> dict:
    """Cold-start pins (ISSUE 13): with the persistent XLA cache CLEARED
    (a fresh per-case dir), time (a) backend build -> first scored batch —
    the bench analog of submit→first-annotation, the latency the leading
    single-batch group + AOT priming attack — and (b) the full cold
    warmup (every executable variant compiled from nothing).  Runs BEFORE
    the warm measurement and uses its own cache dir, so the headline
    numbers still measure the warm path."""
    import shutil

    from sm_distributed_tpu.models.msm_basic import make_backend
    from sm_distributed_tpu.utils.config import SMConfig
    from sm_distributed_tpu.utils.logger import logger

    cold_dir = cache_dir / f"xla_cold_{cfg.name}"
    shutil.rmtree(cold_dir, ignore_errors=True)
    sm_config = SMConfig.from_dict(
        {"backend": "jax_tpu",
         "fdr": {"decoy_sample_size": cfg.decoy_sample_size},
         "parallel": {"formula_batch": cfg.formula_batch,
                      "compile_cache_dir": str(cold_dir)}})
    t0 = time.perf_counter()
    backend = make_backend("jax_tpu", prep["ds"], prep["ds_config"],
                           sm_config, table=prep["table"])
    backend.score_batch(prep["batches"][0])
    first_cold = time.perf_counter() - t0
    if hasattr(backend, "warmup"):
        backend.warmup(prep["batches"])
    cold_total = time.perf_counter() - t0
    shutil.rmtree(cold_dir, ignore_errors=True)
    logger.info("[%s] cold start: first batch %.2fs, full warmup %.2fs "
                "(cleared persistent cache)", cfg.name, first_cold,
                cold_total)
    return dict(first_annotation_cold_s=first_cold,
                cold_compile_s=cold_total)


def measure_jax(cfg: BenchConfig, prep: dict, cache_dir: Path,
                cube_dtype: str = "bf16") -> dict:
    """Warm every executable variant, then time the pipelined stream —
    median of 5 full streams with the spread in the JSON, the same
    discipline the floor gets (r4 same-code 10-rep runs measured 30.0k and
    47.6k ions/s on the headline case; one stream is not a measurement)."""
    from sm_distributed_tpu.analysis import retrace
    from sm_distributed_tpu.models.msm_basic import make_backend
    from sm_distributed_tpu.utils.config import SMConfig
    from sm_distributed_tpu.utils.logger import logger

    sm_config = SMConfig.from_dict(
        {"backend": "jax_tpu",
         "fdr": {"decoy_sample_size": cfg.decoy_sample_size},
         "parallel": {"formula_batch": cfg.formula_batch,
                      # ISSUE 18: the bench runs the shipped perf config —
                      # bf16-compacted resident cube (half the f32 bytes;
                      # FDR ranks identical by the declared contract) and
                      # the fused kernel wherever it engages (auto = TPU)
                      "cube_dtype": cube_dtype,
                      # repo-local persistent XLA cache: /tmp survives on
                      # this host, but a repo path survives anything short
                      # of a fresh checkout (VERDICT r4 item 5)
                      "compile_cache_dir": str(cache_dir / "xla_cache")}})
    # entries already in the persistent XLA cache before this case warms up
    # (VERDICT r4 item 5 — 7 of ~13 driver-bench minutes were silent cold
    # compiles).  All cases share the one cache dir, so 0 means certainly
    # cold; nonzero means at least partially warm (earlier cases' entries
    # count too — per-case key attribution isn't available from here).
    # Count ONLY real executable entries — `jit_<name>-<hex digest>` files,
    # excluding the `-atime` access-time sidecars and any lock/tmp/hidden
    # files the cache layer writes — so nonzero STRICTLY implies warm
    # executables (ADVICE r5).
    _entry_re = re.compile(r"^jit_.+-[0-9a-f]{32,}(-cache)?$")
    cache_entries = sum(
        1 for p in (cache_dir / "xla_cache").glob("jit_*")
        if p.is_file() and _entry_re.match(p.name)
    ) if (cache_dir / "xla_cache").exists() else 0
    backend = make_backend("jax_tpu", prep["ds"], prep["ds_config"],
                           sm_config, table=prep["table"])
    batches = prep["batches"]
    warmup_retried = False
    # warm-start attribution (ISSUE 18): the retrace census accumulates
    # jaxpr-trace / MLIR-lower / cache-load / backend-compile seconds —
    # delta around the warmup splits compile_s into its real components
    # (the remainder is warmup execution: running the warmed executables)
    dur0 = retrace.snapshot()["durations"]
    t0 = time.perf_counter()
    for attempt in (1, 2):
        try:
            if hasattr(backend, "warmup"):
                backend.warmup(batches)
            else:
                backend.score_batch(batches[0])
            break
        except Exception as exc:
            # ONE retry, but only for the known transient tunnel transport
            # failures (observed ~1 in 10 runs); a retried run's inflated
            # compile_s is flagged in the report via warmup_retried
            # (ADVICE r5 — a bare-Exception retry also masked misconfig/OOM)
            if attempt == 2 or not _is_transient_warmup_error(exc):
                raise
            warmup_retried = True
            logger.warning("[%s] warmup failed with a known transient tunnel "
                           "error; retrying once", cfg.name, exc_info=True)
    compile_dt = time.perf_counter() - t0
    dur1 = retrace.snapshot()["durations"]
    compile_split = {k: round(dur1[k] - dur0[k], 3) for k in dur1}
    compile_split["warmup_exec_s"] = round(
        max(0.0, compile_dt - sum(compile_split.values())), 3)
    logger.info("[%s] jax warmup/compile: %.1fs (trace %.1fs, lower %.1fs, "
                "cache load %.1fs, backend compile %.1fs, warmup exec %.1fs; "
                "%d persistent-cache entries before warmup)", cfg.name,
                compile_dt, compile_split["trace_s"],
                compile_split["lower_s"], compile_split["cache_load_s"],
                compile_split["backend_compile_s"],
                compile_split["warmup_exec_s"], cache_entries)

    # steady-state pipelined throughput: reps x batches enqueued as one
    # stream, one sync at the end (a production formula DB streams hundreds
    # of batches through the same executables).  Five independent streams,
    # median + spread reported — dispatch/fetch through the tunnel jitters
    # individual streams (the r3->r4 "headline regression" was one lucky
    # vs one unlucky single-stream draw).
    stream = batches * cfg.reps
    n_scored = prep["table"].n_ions * cfg.reps
    rates = []
    for i in range(5):
        t0 = time.perf_counter()
        backend.score_batches(stream)
        dt = time.perf_counter() - t0
        rates.append(n_scored / dt)
        logger.info("[%s] jax_tpu stream %d: %d ions in %.2fs -> %.1f ions/s",
                    cfg.name, i, n_scored, dt, rates[-1])
    srt = sorted(rates)
    jax_rate = srt[2]
    jax_spread = (srt[-1] - srt[0]) / jax_rate
    logger.info("[%s] jax_tpu: median of 5 streams %.1f ions/s "
                "(spread %.1f%%)", cfg.name, jax_rate, 100 * jax_spread)
    # HBM pinning (ISSUE 6 satellite): the device high-water mark while
    # this case's cube + scratch are resident.  peak_bytes_in_use is a
    # process-lifetime monotone max, so later cases report max(their own,
    # earlier cases') — still the honest answer to "did this run fit".
    # None (-> JSON null) on platforms without memory stats (CPU).
    from sm_distributed_tpu.utils.devicemem import hbm_summary

    hbm = hbm_summary(force_import=True)
    if hbm["hbm_peak_bytes"] is not None:
        logger.info("[%s] HBM peak: %.1f MB on %s", cfg.name,
                    hbm["hbm_peak_bytes"] / 2**20, hbm["device_kind"])
    roofline = measure_roofline(cfg, prep, backend, jax_rate)
    profiled = measure_profiled(cfg, prep, backend,
                                roofline["roofline_floor_s"], cache_dir)
    return dict(jax_rate=jax_rate, compile_dt=compile_dt,
                **profiled,
                compile_split=compile_split,
                jax_spread=jax_spread, cache_entries=cache_entries,
                warmup_retried=warmup_retried,
                warmup_skipped=bool(
                    getattr(backend, "last_warmup_skipped", False)),
                hbm_peak_bytes=hbm["hbm_peak_bytes"],
                device_kind=hbm["device_kind"], **roofline)


def measure_roofline(cfg: BenchConfig, prep: dict, backend,
                     jax_rate: float) -> dict:
    """Roofline + resident-footprint pins (ISSUE 18 satellite): the
    measured per-rep stream wall vs THIS device's microbenchmarked peaks
    and the engine's minimum-work cost model (the same bound
    scripts/roofline_probe.py reports, computed from the bench's own
    stream so the pinned fraction and the headline agree by construction).
    ``resident_cube_bytes`` is the HBM footprint of the compacted
    intensity cube — the acceptance criterion pins desi at <= half the
    f32 baseline, reported alongside as ``resident_cube_bytes_f32``."""
    import jax

    from sm_distributed_tpu.ops.imager_jax import fused_score_cost_model
    from sm_distributed_tpu.utils.logger import logger

    sys.path.insert(0, str(Path(__file__).parent / "scripts"))
    from roofline_probe import measure_device_peaks

    resident = getattr(backend, "_mz_host", None)
    resident_peaks = int(resident.size) if resident is not None else int(
        prep["ds"].n_peaks)
    cube_dtype = getattr(backend, "_cube_dtype", "f32")
    int_bytes = {"f32": 4, "bf16": 2, "int8": 1}[cube_dtype]
    # price the variant that actually dispatched: parallel.fused_metrics
    # defaults to "auto", which engages the fused kernel on a real TPU
    fused_active = (getattr(backend, "_fused_mode", "off") != "off"
                    and jax.default_backend() == "tpu")
    model = fused_score_cost_model(
        n_pixels=prep["ds"].n_pixels,
        resident_peaks=resident_peaks,
        n_ions=prep["table"].n_ions,
        max_peaks=prep["table"].max_peaks,
        formula_batch=cfg.formula_batch,
        nlevels=prep["ds_config"].image_generation.nlevels,
        ordered=True, fused=fused_active, cube_dtype=cube_dtype)
    peaks = measure_device_peaks(bw_mb=64, mm_n=1024)
    t_bw = model["total_bytes"] / (peaks["peak_bw_gbps"] * 1e9)
    t_fl = model["matmul_flops"] / (peaks["peak_matmul_gflops"] * 1e9)
    floor_s = max(t_bw, t_fl)
    measured_s = prep["table"].n_ions / jax_rate    # one full-table pass
    frac = floor_s / measured_s if measured_s > 0 else 0.0
    logger.info("[%s] roofline: model floor %.3fs vs measured %.3fs/rep "
                "-> %.1f%% of the %s-bound ceiling (cube %s, %.1f MB "
                "resident vs %.1f MB f32)", cfg.name, floor_s, measured_s,
                100 * frac, "bandwidth" if t_bw >= t_fl else "compute",
                cube_dtype, resident_peaks * int_bytes / 2**20,
                resident_peaks * 4 / 2**20)
    return dict(
        roofline_frac=round(frac, 4),
        roofline_floor_s=round(floor_s, 4),
        roofline_bound="bandwidth" if t_bw >= t_fl else "compute",
        fused=fused_active, cube_dtype=cube_dtype,
        resident_cube_bytes=int(resident_peaks * int_bytes),
        resident_cube_bytes_f32=int(resident_peaks * 4))


def measure_profiled(cfg: BenchConfig, prep: dict, backend,
                     floor_s: float, cache_dir: Path) -> dict:
    """Profiled stream (ISSUE 20): one extra full stream captured under
    ``jax.profiler``, device time attributed by kernel class
    (analysis/profiling.py — fused Pallas scoring kernel vs the
    gather/segment-sum chain vs transfers).  Pins

    - ``measured_roofline_frac``: the cost-model floor over the MEASURED
      per-rep device seconds the scoring kernels took.  The modeled
      ``roofline_frac`` above divides by end-to-end wall time, so it mixes
      in host dispatch slack; this one is the device-only answer, and a
      drop means the kernels themselves slowed down.
    - ``kernel_time_frac``: scoring kernels' share of ALL device time in
      the capture — falls when transfers/layout ops start eating the
      device.

    None-safe: a failed or empty capture (profiler unavailable on this
    runtime) pins nulls and never fails the bench."""
    from sm_distributed_tpu.analysis import profiling
    from sm_distributed_tpu.utils.logger import logger

    out: dict = {"measured_roofline_frac": None, "kernel_time_frac": None,
                 "device_kernel_s": None, "profile_n_events": 0}
    sess = profiling.ProfileSession(cache_dir / "profile" / cfg.name)
    try:
        sess.start()
        try:
            backend.score_batches(prep["batches"] * cfg.reps)
        finally:
            cap = sess.stop()
    except Exception:
        logger.warning("[%s] profiled stream failed; pinning nulls",
                       cfg.name, exc_info=True)
        return out
    attr = cap.get("attribution") or {}
    total = float(attr.get("total_device_s") or 0.0)
    by = attr.get("by_class_s") or {}
    kernel_s = float(by.get("fused_kernel", 0.0)) + \
        float(by.get("score_chain", 0.0))
    out["profile_n_events"] = int(attr.get("n_events", 0))
    if total > 0 and kernel_s > 0:
        out["measured_roofline_frac"] = round(
            profiling.measured_roofline(floor_s, kernel_s / cfg.reps), 4)
        out["kernel_time_frac"] = round(kernel_s / total, 4)
        out["device_kernel_s"] = round(kernel_s, 4)
        logger.info("[%s] profiled stream: %.3fs device in scoring kernels "
                    "(%.1f%% of device time) -> measured roofline %.1f%%",
                    cfg.name, kernel_s, 100 * out["kernel_time_frac"],
                    100 * out["measured_roofline_frac"])
    else:
        logger.info("[%s] profiled stream: no attributable device events "
                    "(%d total); pinning nulls", cfg.name,
                    out["profile_n_events"])
    return out


def _stream_rate(backend, prep: dict, cfg: BenchConfig, label: str) -> dict:
    """Warmup + median-of-5 pipelined streams for an already-built backend
    (the same measurement discipline as measure_jax, reused by the
    multichip section so single-chip and N-chip rates are same-protocol)."""
    from sm_distributed_tpu.utils.logger import logger

    batches = prep["batches"]
    t0 = time.perf_counter()
    backend.warmup(batches)
    compile_dt = time.perf_counter() - t0
    stream = batches * cfg.reps
    n_scored = prep["table"].n_ions * cfg.reps
    rates = []
    for i in range(5):
        t0 = time.perf_counter()
        backend.score_batches(stream)
        dt = time.perf_counter() - t0
        rates.append(n_scored / dt)
        logger.info("[%s/%s] stream %d: %d ions in %.2fs -> %.1f ions/s",
                    cfg.name, label, i, n_scored, dt, rates[-1])
    srt = sorted(rates)
    return dict(rate=srt[2], spread=(srt[-1] - srt[0]) / srt[2],
                compile_dt=compile_dt)


def measure_multichip(cfg: BenchConfig, prep: dict, cache_dir: Path,
                      n_devices: int, formulas_axis: int) -> dict:
    """The ``--devices N`` mode (ISSUE 7): same-run single-chip vs N-chip
    pjit-sharded rates on the ride-along case.  The single-chip reference
    is PINNED to chip 0 (1x1 mesh, no collectives) and the N-chip rate
    runs the GSPMD-sharded pixels×formulas mesh over chips [0, N) — the
    exact sub-mesh path a ``devices: N`` submit takes through the service's
    device pool.  Speedup is same-run, same-protocol (median of 5 streams
    each), mirroring the floor discipline."""
    import jax

    from sm_distributed_tpu.parallel.sharded import make_jax_backend
    from sm_distributed_tpu.utils.config import SMConfig
    from sm_distributed_tpu.utils.logger import logger

    avail = len(jax.devices())
    n = min(n_devices, avail)
    if n < n_devices:
        logger.warning("multichip: only %d of the requested %d devices "
                       "visible; measuring at %d", avail, n_devices, n)
    f = formulas_axis if formulas_axis > 0 and n % formulas_axis == 0 else 1
    base_par = {"formula_batch": cfg.formula_batch,
                "compile_cache_dir": str(cache_dir / "xla_cache")}
    base = {"backend": "jax_tpu",
            "fdr": {"decoy_sample_size": cfg.decoy_sample_size}}
    sm_single = SMConfig.from_dict(
        {**base, "parallel": {**base_par, "pixels_axis": 1,
                              "formulas_axis": 1}})
    single = make_jax_backend(prep["ds"], prep["ds_config"], sm_single,
                              restrict_table=prep["table"],
                              device_indices=(0,))
    s = _stream_rate(single, prep, cfg, "1-chip")
    sm_multi = SMConfig.from_dict(
        {**base, "parallel": {**base_par, "pixels_axis": n // f,
                              "formulas_axis": f}})
    multi = make_jax_backend(prep["ds"], prep["ds_config"], sm_multi,
                             restrict_table=prep["table"],
                             device_indices=tuple(range(n)))
    m = _stream_rate(multi, prep, cfg, f"{n}-chip")
    speedup = m["rate"] / s["rate"]
    logger.info("[%s] multichip: %.1f ions/s on %d chips vs %.1f on 1 "
                "-> %.2fx", cfg.name, m["rate"], n, s["rate"], speedup)
    from sm_distributed_tpu.utils.devicemem import hbm_summary

    hbm = hbm_summary(force_import=True)
    return {
        "case": cfg.name,
        "devices": n,
        "devices_requested": n_devices,
        "mesh": {"pixels": n // f, "formulas": f},
        "value": round(m["rate"], 2),
        "unit": "ions/s",
        "jax_spread": round(m["spread"], 4),
        "compile_s": round(m["compile_dt"], 2),
        "single_chip_ions_per_s": round(s["rate"], 2),
        "single_chip_spread": round(s["spread"], 4),
        "single_chip_compile_s": round(s["compile_dt"], 2),
        "speedup_vs_single_chip": round(speedup, 3),
        "n_ions": int(prep["table"].n_ions),
        "n_pixels": int(prep["ds"].n_pixels),
        "hbm_peak_bytes": hbm["hbm_peak_bytes"],
        "device_kind": hbm["device_kind"],
    }


def measure_read(n_rows: int = 2000, n_reads: int = 200) -> dict:
    """Read-plane pins (ISSUE 16): a synthetic ``n_rows`` columnar segment
    queried ``n_reads`` times through the real ReadPath handlers with a
    mixed cold/warm key population (20 distinct filter/sort/page shapes,
    cycled — the first pass is cold segment scans, the rest are LRU hits,
    roughly the production hit ratio the cache is sized for).  Pins
    ``reads_per_s`` and ``read_p50_ms``; perf_sentinel bands both."""
    import shutil
    import tempfile

    import pandas as pd

    from sm_distributed_tpu.engine.index import publish_segment
    from sm_distributed_tpu.service.readpath import ReadPath
    from sm_distributed_tpu.utils.config import ReadPathConfig

    root = Path(tempfile.mkdtemp(prefix="sm_bench_read_"))
    try:
        rng = np.random.default_rng(16)
        df = pd.DataFrame({
            "sf": [f"C{i % 40 + 1}H{i % 30 + 2}O{i % 7}N{i % 3}"
                   for i in range(n_rows)],
            "adduct": [("+H", "+Na", "+K")[i % 3] for i in range(n_rows)],
            "msm": rng.uniform(0, 1, n_rows),
            "fdr": rng.uniform(0, 0.5, n_rows),
            "fdr_level": rng.choice([0.05, 0.1, 0.2, 0.5], n_rows),
            "chaos": rng.uniform(0, 1, n_rows),
            "spatial": rng.uniform(0, 1, n_rows),
            "spectral": rng.uniform(0, 1, n_rows)})
        mzs = {(r.sf, r.adduct): 100.0 + i % 900
               for i, r in enumerate(df.itertuples())}
        d = root / "bench_ds"
        d.mkdir()
        publish_segment(d, "bench_ds", 1, df, mzs)
        rp = ReadPath(root, ReadPathConfig())
        shapes = [
            {"order": [o], "dir": [dn], "limit": [str(lim)], **flt}
            for o in ("msm", "mz") for dn in ("desc", "asc")
            for lim, flt in (
                ("100", {}), ("25", {"adduct": ["+H"]}),
                ("50", {"fdr": ["0.2"]}),
                ("100", {"min_msm": ["0.5"]}),
                ("10", {"mz_min": ["200"], "mz_max": ["600"]}))]
        lats = []
        t0 = time.perf_counter()
        for i in range(n_reads):
            t1 = time.perf_counter()
            status, _body, _hd = rp.handle_annotations(
                "bench_ds", shapes[i % len(shapes)])
            lats.append(time.perf_counter() - t1)
            assert status == 200, f"bench read returned {status}"
        total = time.perf_counter() - t0
    finally:
        shutil.rmtree(root, ignore_errors=True)
    lats.sort()
    return {"reads_per_s": round(n_reads / total, 2),
            "read_p50_ms": round(lats[len(lats) // 2] * 1000.0, 3),
            "read_rows": n_rows, "read_n": n_reads}


def report(prep: dict, floor: dict, jaxr: dict, iso: dict | None = None,
           cfg: BenchConfig | None = None, cold: dict | None = None) -> dict:
    iso = iso or {}
    cold = cold or {}
    # per-phase wall clock (ISSUE 5 satellite): BENCH_*.json trajectories
    # explain WHERE time moved, not just totals.  stream_s is the median
    # full-stream wall; floor_rep_s one full floor-sample numpy rep.
    phases = {
        "isocalc_s": round(prep["isocalc_dt"], 3),
        "floor_rep_s": round(floor["floor_n_ions"] / floor["np_rate"], 3),
        "compile_s": round(jaxr["compile_dt"], 3),
    }
    # warm-start attribution (ISSUE 18): compile_s split into its real
    # components, banded per-phase by perf_sentinel like any other phase
    split_names = {"trace_s": "compile_trace_s",
                   "lower_s": "compile_lower_s",
                   "cache_load_s": "compile_cache_load_s",
                   "backend_compile_s": "compile_backend_s",
                   "warmup_exec_s": "warmup_exec_s"}
    for k, v in (jaxr.get("compile_split") or {}).items():
        phases[split_names.get(k, k)] = v
    if cfg is not None:
        phases["stream_s"] = round(
            cfg.reps * prep["table"].n_ions / jaxr["jax_rate"], 3)
    return {
        "phases": phases,
        "value": round(jaxr["jax_rate"], 2),
        "jax_spread": round(jaxr["jax_spread"], 4),
        "vs_baseline": round(jaxr["jax_rate"] / floor["np_rate"], 2),
        "numpy_floor_ions_per_s": round(floor["np_rate"], 2),
        "numpy_floor_spread": round(floor["floor_spread"], 4),
        "numpy_floor_spread_mid5": round(floor["floor_spread_mid5"], 4),
        "numpy_floor_n_ions": floor["floor_n_ions"],
        "floor_procs": floor["n_procs"],
        "numpy_floor_multiproc_ions_per_s": round(floor["mp_rate"], 2),
        "vs_baseline_multiproc": round(jaxr["jax_rate"] / floor["mp_rate"], 2),
        "compile_s": round(jaxr["compile_dt"], 2),
        # ISSUE 13 pinned cold-start fields (sentinel-guarded; None when
        # --skip-cold): measured against a CLEARED persistent cache —
        # the warm headline above never covers the first-user experience
        "cold_compile_s": (round(cold["cold_compile_s"], 2)
                           if cold else None),
        "first_annotation_cold_s": (
            round(cold["first_annotation_cold_s"], 2) if cold else None),
        "warmup_retried": bool(jaxr.get("warmup_retried", False)),
        "warmup_skipped": bool(jaxr.get("warmup_skipped", False)),
        # ISSUE 6 pinned fields: device identity + HBM high-water mark
        # (null when the platform exposes no memory stats)
        "hbm_peak_bytes": jaxr.get("hbm_peak_bytes"),
        "device_kind": jaxr.get("device_kind"),
        # ISSUE 18 pinned fields: measured fraction of the roofline
        # ceiling (sentinel direction: falling = regression) and the
        # compacted resident-cube footprint vs its f32 baseline (the
        # desi acceptance pin: <= half)
        "roofline_frac": jaxr.get("roofline_frac"),
        "roofline_floor_s": jaxr.get("roofline_floor_s"),
        "roofline_bound": jaxr.get("roofline_bound"),
        # ISSUE 20 pinned fields: the MEASURED roofline — model floor over
        # profiled per-rep device seconds in the scoring kernels — and the
        # scoring kernels' share of all captured device time.  Both fall
        # when the kernels regress; None when the capture found nothing.
        "measured_roofline_frac": jaxr.get("measured_roofline_frac"),
        "kernel_time_frac": jaxr.get("kernel_time_frac"),
        "device_kernel_s": jaxr.get("device_kernel_s"),
        "fused": jaxr.get("fused"),
        "cube_dtype": jaxr.get("cube_dtype"),
        "resident_cube_bytes": jaxr.get("resident_cube_bytes"),
        "resident_cube_bytes_f32": jaxr.get("resident_cube_bytes_f32"),
        "xla_cache_entries_before": jaxr["cache_entries"],
        "n_ions": int(prep["table"].n_ions),
        "n_pixels": int(prep["ds"].n_pixels),
        "pixels_per_s": round(jaxr["jax_rate"] * prep["ds"].n_pixels, 0),
        "isocalc_s": round(prep["isocalc_dt"], 2),
        # ISSUE 3 pinned cold-path fields (None on cases that skip the cold
        # regeneration — only the headline case pays for it by default)
        "isocalc_cold_s": (round(iso["isocalc_cold_s"], 2)
                           if iso else None),
        "isocalc_workers": iso.get("isocalc_workers"),
        "patterns_per_s": iso.get("patterns_per_s"),
    }


def write_bench_trace(cache_dir: Path, configs: list, out: dict) -> str:
    """Emit the run's per-case phase spans as a trace file (ISSUE 5
    satellite): the bench JSON pins its path, and trace_report.py renders
    it like any job trace.  Spans are RETROACTIVE — durations are the
    measured ones, laid out sequentially (emitting live spans inside the
    timed hot loops would be measuring the measurement) — flagged with
    ``retro`` in attrs."""
    from sm_distributed_tpu.utils import tracing

    trace = tracing.new_trace(job_id="bench",
                              trace_dir=cache_dir / "traces")
    t = time.time()
    t0 = t
    for cfg in configs:
        case = out if cfg.name == "headline" else out.get(cfg.name, {})
        phases = case.get("phases") or {}
        case_ctx = trace.child()
        case_t0 = t
        for phase, dur in phases.items():
            if not isinstance(dur, (int, float)):
                continue
            tracing.emit_span(trace, phase.removesuffix("_s"), ts=t,
                              dur=float(dur), parent_id=case_ctx.span_id,
                              retro=True, phase=True)
            t += float(dur)
        tracing.emit_span(trace, f"case:{cfg.name}", ts=case_t0,
                          dur=t - case_t0, span_id=case_ctx.span_id,
                          parent_id=trace.span_id, retro=True)
    tracing.emit_span(trace, "bench", ts=t0, dur=t - t0,
                      span_id=trace.span_id, retro=True)
    return trace.file


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--nrows", type=int, default=64)
    ap.add_argument("--ncols", type=int, default=64)
    ap.add_argument("--decoy-sample-size", type=int, default=20)
    # 2048 balances scatter amortization (per-peak cost shared by more ions)
    # against padding waste on the 5250-ion default table
    ap.add_argument("--formula-batch", type=int, default=2048)
    ap.add_argument("--n-formulas", type=int, default=250,
                    help="fixture formulas (x21 adducts -> ion count)")
    ap.add_argument("--reps", type=int, default=None,
                    help="stream reps per case (default: 10 headline, 3 "
                         "scale/desi)")
    ap.add_argument("--baseline-ions", type=int, default=1000,
                    help="ions timed on numpy_ref (per-ion rate extrapolates)")
    ap.add_argument("--floor-procs", type=int, default=0,
                    help="processes for the multi-core numpy floor "
                         "(0 = all cores)")
    ap.add_argument("--skip-scale", action="store_true",
                    help="skip the 256x256/500-formula scale case")
    ap.add_argument("--skip-desi", action="store_true",
                    help="skip the 512x512 (262k px) DESI-scale case")
    ap.add_argument("--skip-isocalc-cold", action="store_true",
                    help="skip the headline case's cold isocalc regeneration")
    ap.add_argument("--skip-cold", action="store_true",
                    help="skip the cleared-cache cold-start measurement "
                         "(cold_compile_s / first_annotation_cold_s)")
    ap.add_argument("--cube-dtype", choices=("f32", "bf16", "int8"),
                    default="bf16",
                    help="parallel.cube_dtype for the benched backend "
                         "(ISSUE 18; default bf16 — the shipped perf "
                         "config, half the resident-cube bytes with "
                         "identical FDR ranks; f32 is the legacy cube)")
    ap.add_argument("--isocalc-device", action="store_true",
                    help="route the cold isocalc measurement through the "
                         "device blur->centroid stage (ops/isocalc_jax.py)")
    ap.add_argument("--devices", type=int, default=0,
                    help="measure an N-chip pjit-sharded 'multichip' "
                         "section on the ride-along case (same-run 1-chip "
                         "vs N-chip speedup; forces N virtual CPU devices "
                         "when the host platform exposes fewer)")
    ap.add_argument("--mesh-formulas", type=int, default=1,
                    help="formulas axis of the multichip mesh (must divide "
                         "--devices; pixels axis absorbs the rest)")
    args = ap.parse_args()

    # the virtual-mesh flag must land before jax initializes (harmless on
    # TPU hosts: it only affects the host CPU platform)
    if args.devices > 1 and "jax" not in sys.modules:
        flags = [fl for fl in os.environ.get("XLA_FLAGS", "").split()
                 if "xla_force_host_platform_device_count" not in fl]
        flags.append(
            f"--xla_force_host_platform_device_count={args.devices}")
        os.environ["XLA_FLAGS"] = " ".join(flags)

    from sm_distributed_tpu.utils.logger import init_logger

    init_logger()
    # compile-retrace attribution (ISSUE 12, analysis/retrace.py): the
    # bench pins how many XLA compiles it paid and how many distinct
    # signatures they covered — a widening signature count on the same
    # workload is the unbounded-retrace regression the census gates
    from sm_distributed_tpu.analysis import retrace

    retrace.enable()
    cache_dir = Path(__file__).parent / ".cache"
    n_procs = max(1, args.floor_procs or os.cpu_count() or 1)

    # headline reps default higher than the big cases: its whole stream is
    # ~0.15 s/rep, so at 3 reps the measurement is host/tunnel dispatch
    # jitter (observed 25k-37k ions/s across same-code runs); ~10 reps
    # amortize it at negligible cost.  An explicit --reps overrides both.
    head_reps = args.reps if args.reps is not None else 10
    big_reps = args.reps if args.reps is not None else 3
    head = BenchConfig("headline", args.nrows, args.ncols, args.n_formulas,
                       args.formula_batch, args.decoy_sample_size,
                       head_reps, args.baseline_ions)
    configs = [head]
    # the scale/desi cases only ride along on a default headline run (an
    # ad-hoc --nrows 256 run IS a scale run already)
    if not args.skip_scale and (args.nrows, args.ncols) == (64, 64):
        configs.append(BenchConfig(
            "scale", 256, 256, 500, args.formula_batch,
            args.decoy_sample_size, big_reps, args.baseline_ions))
    if not args.skip_desi and (args.nrows, args.ncols) == (64, 64):
        # BASELINE #5's actual scale (>200k px).  formula_batch=256 keeps
        # the flat-path histogram scratch inside the HBM guard at 262k
        # pixels; the floor sample is 300 ions (a numpy ion costs ~40 ms
        # here — 7x1000 ions would be ~5 min of floor alone)
        configs.append(BenchConfig(
            "desi", 512, 512, 500, 256,
            args.decoy_sample_size, big_reps, baseline_ions=300))

    # phase 1: all host-side prep + ALL floor measurements (fork-safe: no
    # jax yet); phase 1.5: cold isocalc regeneration (spawn-based, and the
    # device variant initializes jax — must come after the forked floors);
    # phase 2: jax timings per config
    preps = [prepare(c, cache_dir) for c in configs]
    floors = [measure_floor(c, p, n_procs) for c, p in zip(configs, preps)]
    iso_cold = (None if args.skip_isocalc_cold else
                measure_isocalc_cold(configs[0], preps[0], n_procs,
                                     args.isocalc_device))
    # cold-start pins first (ISSUE 13): fresh per-case cache dirs, so the
    # shared-cache warm measurement below is untouched
    colds = [None if args.skip_cold else measure_cold(c, p, cache_dir)
             for c, p in zip(configs, preps)]
    jaxrs = [measure_jax(c, p, cache_dir, cube_dtype=args.cube_dtype)
             for c, p in zip(configs, preps)]

    out = {
        "metric": "ions_scored_per_sec_per_chip",
        "unit": "ions/s",
        **report(preps[0], floors[0], jaxrs[0], iso_cold, configs[0],
                 cold=colds[0]),
    }
    for cfg, p, f, j, cd in zip(configs[1:], preps[1:], floors[1:],
                                jaxrs[1:], colds[1:]):
        out[cfg.name] = report(p, f, j, cfg=cfg, cold=cd)
    if args.devices > 1:
        # multichip rides the LAST case (desi on a default run — the
        # acceptance target — else whatever case this invocation built)
        out["multichip"] = measure_multichip(
            configs[-1], preps[-1], cache_dir, args.devices,
            args.mesh_formulas)
    out.update(measure_read())          # ISSUE 16 read-plane pins
    compile_snap = retrace.snapshot()
    out["compile_events"] = compile_snap["events_total"]
    out["compile_signatures"] = compile_snap["signatures_total"]
    out["compile_sites"] = len(compile_snap["sites"])
    out["trace_path"] = write_bench_trace(cache_dir, configs, out)
    print(json.dumps(out))


if __name__ == "__main__":
    sys.exit(main())
