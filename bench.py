"""Benchmark: ions scored per second per chip (jax_tpu fused graph).

Primary metric per BASELINE.json ("formulas scored/sec/chip"): throughput of
the fused extract+score XLA graph — ion-image extraction + MSM metrics
(chaos, spatial, spectral) — over a synthetic spheroid-like dataset.
``vs_baseline`` is the speedup over the numpy_ref backend on the same
workload (the measured stand-in for the reference's Spark executor; the
reference publishes no numbers — SURVEY.md §6, BASELINE.json "published": {}).

Prints ONE JSON line on stdout; all logging goes to stderr.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

import numpy as np


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--nrows", type=int, default=64)
    ap.add_argument("--ncols", type=int, default=64)
    ap.add_argument("--decoy-sample-size", type=int, default=20)
    ap.add_argument("--formula-batch", type=int, default=512)
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--baseline-ions", type=int, default=48,
                    help="ions timed on numpy_ref (per-ion rate extrapolates)")
    args = ap.parse_args()

    from sm_distributed_tpu.io.dataset import SpectralDataset
    from sm_distributed_tpu.io.fixtures import FIXTURE_FORMULAS, generate_synthetic_dataset
    from sm_distributed_tpu.models.msm_basic import NumpyBackend, make_backend
    from sm_distributed_tpu.ops.fdr import FDR
    from sm_distributed_tpu.ops.isocalc import IsocalcWrapper
    from sm_distributed_tpu.utils.config import DSConfig, SMConfig
    from sm_distributed_tpu.utils.logger import init_logger, logger

    init_logger()
    cache_dir = Path(__file__).parent / ".cache"
    work_dir = cache_dir / "bench_ds"

    t0 = time.perf_counter()
    path, truth = generate_synthetic_dataset(
        work_dir, nrows=args.nrows, ncols=args.ncols,
        formulas=FIXTURE_FORMULAS, present_fraction=0.6, noise_peaks=200, seed=7,
    )
    ds = SpectralDataset.from_imzml(path)
    logger.info("dataset: %dx%d px, %d peaks (%.1fs)",
                ds.nrows, ds.ncols, ds.n_peaks, time.perf_counter() - t0)

    ds_config = DSConfig.from_dict(
        {"isotope_generation": {"adducts": ["+H"]},
         "image_generation": {"ppm": 3.0}}
    )
    sm_config = SMConfig.from_dict(
        {"backend": "jax_tpu",
         "fdr": {"decoy_sample_size": args.decoy_sample_size},
         "parallel": {"formula_batch": args.formula_batch}}
    )
    SMConfig.set(sm_config)

    # Full target+decoy ion table (the realistic scoring workload).
    fdr = FDR(decoy_sample_size=args.decoy_sample_size,
              target_adducts=("+H",), seed=42)
    assignment = fdr.decoy_adduct_selection(truth.formulas)
    pairs, flags = assignment.all_ion_tuples(truth.formulas, ("+H",))
    calc = IsocalcWrapper(ds_config.isotope_generation, cache_dir=str(cache_dir / "isocalc"))
    t0 = time.perf_counter()
    table = calc.pattern_table(pairs, flags)
    logger.info("isotope patterns: %d ions (%.1fs)", table.n_ions, time.perf_counter() - t0)

    from sm_distributed_tpu.models.msm_basic import _slice_table

    def batches(n, b):
        return [(s, min(s + b, n)) for s in range(0, n, b)]

    # --- jax_tpu timing (compile excluded via warmup) -------------------
    backend = make_backend("jax_tpu", ds, ds_config, sm_config)
    b = args.formula_batch
    warm = _slice_table(table, 0, min(b, table.n_ions))
    t0 = time.perf_counter()
    backend.score_batch(warm)
    logger.info("jax warmup/compile: %.1fs", time.perf_counter() - t0)

    t0 = time.perf_counter()
    n_scored = 0
    for _ in range(args.reps):
        for s, e in batches(table.n_ions, b):
            backend.score_batch(_slice_table(table, s, e))
            n_scored += e - s
    jax_dt = time.perf_counter() - t0
    jax_rate = n_scored / jax_dt
    logger.info("jax_tpu: %d ions in %.2fs -> %.1f ions/s", n_scored, jax_dt, jax_rate)

    # --- numpy_ref floor (subset, extrapolated per-ion) -----------------
    np_backend = NumpyBackend(ds, ds_config)
    sub = _slice_table(table, 0, min(args.baseline_ions, table.n_ions))
    np_backend.score_batch(_slice_table(table, 0, 2))  # warm caches
    t0 = time.perf_counter()
    np_backend.score_batch(sub)
    np_dt = time.perf_counter() - t0
    np_rate = sub.n_ions / np_dt
    logger.info("numpy_ref: %d ions in %.2fs -> %.1f ions/s", sub.n_ions, np_dt, np_rate)

    print(json.dumps({
        "metric": "ions_scored_per_sec_per_chip",
        "value": round(jax_rate, 2),
        "unit": "ions/s",
        "vs_baseline": round(jax_rate / np_rate, 2),
    }))


if __name__ == "__main__":
    sys.exit(main())
