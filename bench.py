"""Benchmark: ions scored per second per chip (jax_tpu fused graph).

Primary metric per BASELINE.json ("formulas scored/sec/chip"): throughput of
the fused extract+score XLA graph — ion-image extraction + MSM metrics
(chaos, spatial, spectral) — over a synthetic spheroid-like dataset.
``vs_baseline`` is the speedup over the numpy_ref backend on the same
workload (the measured stand-in for the reference's Spark executor; the
reference publishes no numbers — SURVEY.md §6, BASELINE.json "published": {}).

The numpy floor is measured over >=200 ions drawn evenly across the ion
table (targets AND decoys, matching the mix the jax path scores), and
per-phase numbers (compile, scoring, floor) are separate JSON fields
(VERDICT r1 item 10).

Prints ONE JSON line on stdout; all logging goes to stderr.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from pathlib import Path

import numpy as np

# module globals inherited by fork()ed floor workers (COW — the sorted peak
# view is NOT re-built or copied per worker)
_NP_BACKEND = None
_NP_TABLE = None


def _floor_worker(bounds: tuple[int, int]) -> int:
    """Score one slice of the floor table in a forked worker."""
    from sm_distributed_tpu.models.msm_basic import _slice_table

    s, e = bounds
    _NP_BACKEND.score_batch(_slice_table(_NP_TABLE, s, e))
    return e - s


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--nrows", type=int, default=64)
    ap.add_argument("--ncols", type=int, default=64)
    ap.add_argument("--decoy-sample-size", type=int, default=20)
    # 2048 balances scatter amortization (per-peak cost shared by more ions)
    # against padding waste on the 5250-ion default table
    ap.add_argument("--formula-batch", type=int, default=2048)
    ap.add_argument("--n-formulas", type=int, default=250,
                    help="fixture formulas (x21 adducts -> ion count)")
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--baseline-ions", type=int, default=210,
                    help="ions timed on numpy_ref (per-ion rate extrapolates)")
    ap.add_argument("--floor-procs", type=int, default=0,
                    help="processes for the multi-core numpy floor "
                         "(0 = all cores)")
    args = ap.parse_args()

    from sm_distributed_tpu.io.dataset import SpectralDataset
    from sm_distributed_tpu.io.fixtures import expand_formula_list, generate_synthetic_dataset
    from sm_distributed_tpu.models.msm_basic import NumpyBackend, _slice_table, make_backend
    from sm_distributed_tpu.ops.fdr import FDR
    from sm_distributed_tpu.ops.isocalc import IsocalcWrapper
    from sm_distributed_tpu.utils.config import DSConfig, SMConfig
    from sm_distributed_tpu.utils.logger import init_logger, logger

    init_logger()
    cache_dir = Path(__file__).parent / ".cache"
    work_dir = cache_dir / "bench_ds"

    t0 = time.perf_counter()
    bench_formulas = expand_formula_list(args.n_formulas)
    path, truth = generate_synthetic_dataset(
        work_dir, nrows=args.nrows, ncols=args.ncols,
        formulas=bench_formulas, present_fraction=0.6, noise_peaks=200, seed=7,
        reuse=True,
    )
    ds = SpectralDataset.from_imzml(path)
    logger.info("dataset: %dx%d px, %d peaks (%.1fs)",
                ds.nrows, ds.ncols, ds.n_peaks, time.perf_counter() - t0)

    ds_config = DSConfig.from_dict(
        {"isotope_generation": {"adducts": ["+H"]},
         "image_generation": {"ppm": 3.0}}
    )
    sm_config = SMConfig.from_dict(
        {"backend": "jax_tpu",
         "fdr": {"decoy_sample_size": args.decoy_sample_size},
         "parallel": {"formula_batch": args.formula_batch}}
    )
    SMConfig.set(sm_config)

    # Full target+decoy ion table (the realistic scoring workload).
    fdr = FDR(decoy_sample_size=args.decoy_sample_size,
              target_adducts=("+H",), seed=42)
    assignment = fdr.decoy_adduct_selection(truth.formulas)
    pairs, flags = assignment.all_ion_tuples(truth.formulas, ("+H",))
    calc = IsocalcWrapper(ds_config.isotope_generation, cache_dir=str(cache_dir / "isocalc"))
    t0 = time.perf_counter()
    table = calc.pattern_table(pairs, flags)
    isocalc_dt = time.perf_counter() - t0
    logger.info("isotope patterns: %d ions (%.1fs)", table.n_ions, isocalc_dt)

    b = args.formula_batch
    batches = [_slice_table(table, s, min(s + b, table.n_ions))
               for s in range(0, table.n_ions, b)]

    # --- numpy_ref floor FIRST (spread subset, extrapolated per-ion) ----
    # The floor (incl. its fork pool) runs BEFORE any JAX work: forking a
    # process that already holds a live PJRT/TPU client and runtime threads
    # is unsupported and can deadlock the workers.
    np_backend = NumpyBackend(ds, ds_config)
    n_base = min(args.baseline_ions, table.n_ions)
    # even spread across the table -> same target/decoy mix as the full run
    sel = np.linspace(0, table.n_ions - 1, n_base).astype(int)
    sel = np.unique(sel)
    from sm_distributed_tpu.ops.isocalc import IsotopePatternTable
    sub = IsotopePatternTable(
        sfs=[table.sfs[i] for i in sel],
        adducts=[table.adducts[i] for i in sel],
        mzs=table.mzs[sel], ints=table.ints[sel],
        n_valid=table.n_valid[sel], targets=table.targets[sel],
    )
    np_backend.score_batch(_slice_table(table, 0, 2))  # warm caches
    # median of 3: the shared-host floor varies ~±20% run to run, and
    # vs_baseline should not ride that noise
    np_dts = []
    for _ in range(3):
        t0 = time.perf_counter()
        np_backend.score_batch(sub)
        np_dts.append(time.perf_counter() - t0)
    np_dt = sorted(np_dts)[1]
    np_rate = sub.n_ions / np_dt
    logger.info("numpy_ref: %d ions in %.2fs (median of 3) -> %.1f ions/s",
                sub.n_ions, np_dt, np_rate)

    # --- multi-process floor: numpy_ref over a fork pool on ALL cores ---
    # The north star compares against a Spark CLUSTER, not one core
    # (BASELINE.md); reporting both floors makes "Xx one core, Yx an
    # N-core node" defensible with measured numbers (VERDICT r2 item 9).
    n_procs = max(1, args.floor_procs or os.cpu_count() or 1)
    if n_procs > 1:
        import multiprocessing as mp

        global _NP_BACKEND, _NP_TABLE
        _NP_BACKEND, _NP_TABLE = np_backend, sub
        cut = np.linspace(0, sub.n_ions, n_procs + 1).astype(int)
        chunks = [(int(cut[i]), int(cut[i + 1])) for i in range(n_procs)
                  if cut[i + 1] > cut[i]]
        ctx = mp.get_context("fork")   # COW-share the sorted peak view
        t0 = time.perf_counter()
        with ctx.Pool(n_procs) as pool:
            done = sum(pool.map(_floor_worker, chunks))
        mp_dt = time.perf_counter() - t0
        mp_rate = done / mp_dt
        logger.info("numpy_ref x%d procs: %d ions in %.2fs -> %.1f ions/s",
                    n_procs, done, mp_dt, mp_rate)
    else:
        mp_rate = np_rate              # single-core host: the floors coincide
        logger.info("single-core host: multi-process floor == single-core floor")

    # --- jax_tpu timing (compile excluded via warmup) -------------------
    backend = make_backend("jax_tpu", ds, ds_config, sm_config, table=table)
    t0 = time.perf_counter()
    # warm every executable the stream will use, one representative batch
    # per variant (plain vs peak-compaction; JaxBackend.warmup inspects the
    # plans rather than assuming which batches use which)
    if hasattr(backend, "warmup"):
        backend.warmup(batches)
    else:
        backend.score_batch(batches[0])
    compile_dt = time.perf_counter() - t0
    logger.info("jax warmup/compile: %.1fs", compile_dt)

    # steady-state pipelined throughput: reps x batches enqueued as one
    # stream, one sync at the end (matches a production-size formula DB where
    # hundreds of batches flow through the one executable)
    stream = batches * args.reps
    n_scored = table.n_ions * args.reps
    t0 = time.perf_counter()
    backend.score_batches(stream)
    jax_dt = time.perf_counter() - t0
    jax_rate = n_scored / jax_dt
    logger.info("jax_tpu: %d ions in %.2fs -> %.1f ions/s", n_scored, jax_dt, jax_rate)

    print(json.dumps({
        "metric": "ions_scored_per_sec_per_chip",
        "value": round(jax_rate, 2),
        "unit": "ions/s",
        "vs_baseline": round(jax_rate / np_rate, 2),
        "numpy_floor_ions_per_s": round(np_rate, 2),
        "numpy_floor_n_ions": int(sub.n_ions),
        "floor_procs": int(n_procs),
        "numpy_floor_multiproc_ions_per_s": round(mp_rate, 2),
        "vs_baseline_multiproc": round(jax_rate / mp_rate, 2),
        "compile_s": round(compile_dt, 2),
        "n_ions": int(table.n_ions),
        "n_pixels": int(ds.n_pixels),
        "pixels_per_s": round(jax_rate * ds.n_pixels, 0),
        "isocalc_s": round(isocalc_dt, 2),
    }))


if __name__ == "__main__":
    sys.exit(main())
