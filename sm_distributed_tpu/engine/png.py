"""Colormapped PNG rendering of ion images.

Reference: ``sm/engine/png_generator.py::PngGenerator`` [U] (SURVEY.md #17) —
matplotlib-colormapped PNG bytes for the web app.  Here: PIL + a viridis-like
colormap computed directly (no matplotlib import on the hot path).
"""

from __future__ import annotations

import io
from pathlib import Path

import numpy as np

# 10-anchor viridis approximation (full range through the yellow end,
# ADVICE r1), linearly interpolated to 256 entries.
_ANCHORS = np.array([
    [68, 1, 84], [72, 40, 120], [62, 74, 137], [49, 104, 142],
    [38, 130, 142], [31, 158, 137], [53, 183, 121], [109, 205, 89],
    [180, 222, 44], [253, 231, 37],
], dtype=np.float64)


def _viridis256() -> np.ndarray:
    x = np.linspace(0, len(_ANCHORS) - 1, 256)
    lo = np.clip(np.floor(x).astype(int), 0, len(_ANCHORS) - 2)
    frac = (x - lo)[:, None]
    return np.clip(_ANCHORS[lo] * (1 - frac) + _ANCHORS[lo + 1] * frac, 0, 255
                   ).astype(np.uint8)


class PngGenerator:
    """Render a 2-D intensity image to RGBA PNG bytes/file."""

    def __init__(self, mask: np.ndarray | None = None):
        # pixels outside the sample-area mask render transparent, like the
        # reference passing the dataset mask to its generator [U]
        self.mask = mask
        self._lut = _viridis256()

    def render(self, img: np.ndarray) -> bytes:
        from PIL import Image

        img = np.asarray(img, dtype=np.float64)
        vmax = img.max()
        norm = (img / vmax * 255).astype(np.uint8) if vmax > 0 else np.zeros(
            img.shape, dtype=np.uint8
        )
        rgba = np.zeros((*img.shape, 4), dtype=np.uint8)
        rgba[..., :3] = self._lut[norm]
        rgba[..., 3] = 255
        if self.mask is not None:
            rgba[~self.mask] = 0
        buf = io.BytesIO()
        Image.fromarray(rgba, mode="RGBA").save(buf, format="PNG")
        return buf.getvalue()

    def save(self, img: np.ndarray, path: str | Path) -> Path:
        path = Path(path)
        path.write_bytes(self.render(img))
        return path
