"""SearchJob — the end-to-end annotation job orchestrator (L5).

Reference: ``sm/engine/search_job.py::SearchJob`` [U] (SURVEY.md #13, call
stack §3.1): the one place that touches every layer — config, work-dir
staging, conversion, distributed context, theor-peak generation, search,
result storage, cleanup, with job status rows (STARTED/FINISHED/FAILED).

TPU-native differences: no imzML→txt conversion step (the native reader
parses straight into the device-friendly CSR layout); the Spark context is
replaced by the jitted backend (mesh-aware via SMConfig.parallel); results go
to parquet + sqlite instead of Postgres/ES.  Failure model per SURVEY.md
§5.3: any exception marks the job FAILED with the error recorded, partial
index entries for the dataset are removed, and re-running is idempotent.
"""

from __future__ import annotations

import traceback
from pathlib import Path

from ..io.dataset import SpectralDataset
from ..models.msm_basic import IsotopePrefetch, MSMBasicSearch, SearchResultsBundle
from ..utils import devicemem, tracing
from ..utils.cancel import JobCancelledError, hold_cancellable
from ..utils.config import DSConfig, SMConfig
from ..utils.logger import logger, phase_timer
from .moldb import MolecularDB
from .storage import JobLedger, SearchResultsStore
from .work_dir import WorkDirManager


class SearchJob:
    """Run a full annotation job for one dataset."""

    def __init__(
        self,
        ds_id: str,
        ds_name: str,
        input_path: str | Path,
        ds_config: DSConfig,
        sm_config: SMConfig | None = None,
        formulas: list[str] | None = None,
        profile_dir: str | None = None,
        residency=None,
        device_token=None,
        cancel=None,
        fence=None,
        on_partial=None,
    ):
        self.ds_id = ds_id
        self.ds_name = ds_name
        # URIs (file://, s3://) must NOT round-trip through Path — it
        # collapses "://" to ":/" before the staging fetcher can parse it
        s = str(input_path)
        self.input_path: str | Path = s if "://" in s else Path(s)
        self.ds_config = ds_config
        self.sm_config = sm_config or SMConfig.get_conf()
        self.formulas = formulas      # explicit list overrides the mol DB
        self.profile_dir = profile_dir
        # service mode: engine/residency.DatasetResidency shared across jobs
        # keeps parsed datasets + compiled backends warm (SURVEY #16 analog)
        self.residency = residency
        # service scheduler's device lease (service/device_pool.py — still
        # Lock-protocol compatible, so a plain threading.Lock works too):
        # when set, the device-bound compile+search+store phase runs under
        # the lease's 1..N chips while staging/parse phases overlap across
        # jobs.  A 1-chip lease pins scoring to its chip; an N-chip lease
        # scores through the pjit-sharded sub-mesh (parallel/sharded.py).
        self.device_token = device_token
        # cooperative cancellation (utils/cancel.CancelToken): checked at
        # phase boundaries here and at checkpoint-group boundaries inside
        # the search, so a timed-out/cancelled job releases the device
        # token and stores no partial results
        self.cancel = cancel
        # multi-replica fence gate (service/leases.py): a callable raising
        # FenceRejectedError when a peer replica fenced this job's claim
        # out.  Checked immediately before results become durable and
        # before the ledger commit — the two writes that would otherwise
        # double-complete under a split-brain takeover.
        self.fence = fence
        # streamed first results (ISSUE 13): provisional-annotation
        # payloads from the search's first FDR-rankable group — recorded
        # on ``last_partial`` and forwarded to ``on_partial`` (the service
        # passes ``ctx.set_partial`` so GET /jobs shows the preview)
        self.on_partial = on_partial
        self.last_partial: dict = {}
        self.ledger = JobLedger(self.sm_config.storage.results_dir)
        # generation stats of the last completed run (workers, patterns/s,
        # device flag) — read by probes/benches (scripts/cold_path_bench.py)
        self.last_isocalc_stats: dict = {}
        # device-memory high-water mark of the last completed run (ISSUE 6):
        # {device_kind, hbm_peak_bytes, ...}; byte fields None on platforms
        # without memory stats (utils/devicemem.py)
        self.last_hbm: dict = {}
        self.store = SearchResultsStore(
            self.ledger,
            store_images=self.sm_config.storage.store_images,
            image_format=self.sm_config.storage.image_format,
        )
        self.work_dir = WorkDirManager(self.sm_config.work_dir, ds_id)

    def _load_formulas(self) -> list[str]:
        if self.formulas is not None:
            return list(self.formulas)
        db_cfg = self.ds_config.database
        return MolecularDB(self.ledger).formulas(db_cfg.name, db_cfg.version)

    def run(self, clean: bool = False) -> SearchResultsBundle:
        """Stage → read → search → store; job row tracks status."""
        import dataclasses

        self.ledger.upsert_dataset(
            self.ds_id, self.ds_name, str(self.input_path),
            dataclasses.asdict(self.ds_config),
        )
        job_id = self.ledger.start_job(self.ds_id)
        logger.info("job %d started for ds %s (%s)", job_id, self.ds_id, self.ds_name)
        prof = None
        succeeded = False
        prefetch = None
        try:
            timings: dict[str, float] = {}
            # ISSUE 3 layer 3: isotope-pattern generation needs only the
            # formula list + configs, and it dominates the cold path (94.5%
            # of the BASELINE #3 wall) — start it FIRST, so staging + parse
            # below overlap it instead of queueing behind it
            formulas = self._load_formulas()
            if self.cancel is not None:
                self.cancel.check("load_formulas")
            if self.sm_config.parallel.overlap_isocalc != "off":
                prefetch = IsotopePrefetch(
                    formulas, self.ds_config, self.sm_config,
                    str(Path(self.sm_config.work_dir) / "isocalc_cache"))
            ds = self._prepare_dataset(timings)
            logger.info(
                "dataset %s: %dx%d px, %d spectra, %d peaks",
                self.ds_id, ds.nrows, ds.ncols, ds.n_spectra, ds.n_peaks,
            )
            if self.profile_dir:
                import jax

                prof = self.profile_dir
                jax.profiler.start_trace(prof)
                # correlate the jax.profiler trace dir into the job trace:
                # /jobs/<id>/trace surfaces it in otherData.jax_profile_dir
                tracing.event("jax_profile", dir=str(self.profile_dir))
            import contextlib

            # everything up to here is CPU-bound (staging, parse, formula
            # lookup) and overlaps freely across scheduler workers; from the
            # backend build through result storage the device is involved,
            # so concurrent service jobs serialize on the TPU token.  The
            # acquisition stays cancellable: a cancelled job must not sit in
            # the device queue, and the ``with`` exit releases the token on
            # the cooperative JobCancelledError unwind.
            if self.device_token is None and self.cancel is None:
                token = contextlib.nullcontext()
            else:
                token = hold_cancellable(self.device_token, self.cancel)
            # trace accounting: the device_hold span covers token WAIT +
            # HOLD; the acquired event inside marks the boundary, so
            # trace_report can split queue-wait vs token-wait vs compute
            with tracing.span("device_hold"), token:
                # a DeviceLease exposes the granted chip indices; a plain
                # Lock (legacy callers) has none — the event then matches
                # the pre-pool shape and the search meshes over all devices
                lease_devs = getattr(self.device_token, "devices", None)
                tracing.event(
                    "device_token_acquired",
                    **({"devices": [int(i) for i in lease_devs]}
                       if lease_devs else {}))
                search = MSMBasicSearch(
                    ds, formulas, self.ds_config, self.sm_config,
                    isocalc_cache_dir=str(Path(self.sm_config.work_dir) / "isocalc_cache"),
                    checkpoint_dir=str(self.work_dir.path),
                    backend_cache=self.residency,
                    prefetch=prefetch,
                    cancel=self.cancel,
                    device_indices=lease_devs,
                    partial_observer=self._note_partial,
                )
                prefetch = None   # ownership passed: search() consumes/cancels
                bundle = search.search()
                if search.isocalc is not None:
                    self.last_isocalc_stats = dict(search.isocalc.last_stats)
                if prof:
                    import jax

                    jax.profiler.stop_trace()
                    prof = None
                    logger.info("profile trace written to %s", self.profile_dir)
                bundle.timings.update(timings)
                if self.cancel is not None:
                    # last cooperative gate before results become durable: a
                    # cancelled/expired job must store NOTHING partial
                    self.cancel.check("store_results")
                if self.fence is not None:
                    # last FENCE gate before results become durable: a claim
                    # lost to a peer takeover must store NOTHING (the peer's
                    # rerun owns the results now)
                    self.fence()
                with phase_timer("store_results", bundle.timings):
                    ion_mzs = {
                        (table_sf, table_ad): mz
                        for table_sf, table_ad, mz in zip(
                            search.last_table.sfs,
                            search.last_table.adducts,
                            search.last_table.mzs[:, 0],
                        )
                    } if search.last_table is not None else None
                    # images first, index/parquet swap last: a failure anywhere
                    # in storage leaves the previous successful job's results
                    # fully queryable (ADVICE r1)
                    if self.sm_config.storage.store_images:
                        self._store_annotation_images(ds, search, bundle)
                    self.store.store(self.ds_id, job_id, bundle, ion_mzs)
                # pin the device high-water mark while this job's arrays
                # are still resident; the trace gets it as an event so
                # every per-phase hbm sample has a job-level roll-up
                self.last_hbm = devicemem.hbm_summary()
                if self.last_hbm.get("hbm_peak_bytes") is not None:
                    tracing.event("hbm_job_peak", **self.last_hbm)
            if self.fence is not None:
                # ledger-commit fence: a stale replica must not flip the
                # job row FINISHED under the takeover replica's run
                self.fence()
            self.ledger.finish_job(job_id)
            if search.last_checkpoint is not None:
                # only after results are durably persisted: a storage failure
                # above must leave the checkpoint for the rerun to resume
                # from; and a failed cleanup must not FAIL a finished job
                try:
                    search.last_checkpoint.finalize()
                except OSError:
                    logger.warning(
                        "could not remove search checkpoint shards under %s",
                        search.last_checkpoint.dir, exc_info=True)
            logger.info("job %d FINISHED (%d annotations)", job_id, len(bundle.annotations))
            succeeded = True
            return bundle
        except Exception as exc:
            if prefetch is not None:
                # job died between prefetch start and search(): stop the
                # background generation before reporting failure
                try:
                    prefetch.cancel()
                except Exception:
                    logger.warning("isotope prefetch cancel failed",
                                   exc_info=True)
            if prof:
                import jax

                jax.profiler.stop_trace()
            self.ledger.fail_job(job_id, f"{exc}\n{traceback.format_exc()}")
            # remove THIS job's partial index entries (the reference's ES
            # cleanup [U]); earlier successful jobs' rows stay queryable
            self.store.index.delete_ds(self.ds_id, job_id=job_id)
            if isinstance(exc, JobCancelledError):
                logger.info("job %d CANCELLED: %s", job_id, exc)
            else:
                logger.error("job %d FAILED: %s", job_id, exc)
            raise
        finally:
            # on failure the work dir survives even with clean=True: it holds
            # the checkpoint shards + staged input the rerun resumes from
            if clean and succeeded:
                self.work_dir.clean()
            elif clean:
                logger.info(
                    "job failed: keeping work dir %s for resume",
                    self.work_dir.path)

    def _note_partial(self, payload: dict) -> None:
        """Provisional annotations landed (ISSUE 13): remember the latest
        payload and forward it to the service's ``on_partial`` (exception-
        safe — a preview consumer can never fail the job)."""
        self.last_partial = dict(payload or {})
        if self.on_partial is None:
            return
        try:
            self.on_partial(self.last_partial)
        except Exception:
            logger.warning("on_partial consumer failed", exc_info=True)

    def _prepare_dataset(self, timings: dict[str, float]) -> SpectralDataset:
        """Stage the input + parse it into the canonical CSR layout.  The
        one overridable seam between job bookkeeping and scoring: a stream
        job (engine/stream.py) assembles its dataset from the committed
        chunk log instead of a staged imzML file, and everything else in
        ``run`` — ledger rows, device hold, search, fences, storage — is
        shared verbatim (which is what makes the end-of-acquisition pass
        bit-identical to a batch submit)."""
        with phase_timer("stage_input", timings):
            self.work_dir.copy_input_data(self.input_path)
        if self.cancel is not None:
            self.cancel.check("stage_input")
        with phase_timer("read_dataset", timings):
            ds = self._read_dataset()
        if self.cancel is not None:
            self.cancel.check("read_dataset")
        return ds

    def _read_dataset(self) -> SpectralDataset:
        """Parse the staged imzML — or reuse the residency cache's copy,
        keyed on the staging manifest so a restaged DIFFERENT input misses."""
        path = self.work_dir.imzml_path()
        if self.residency is None:
            return SpectralDataset.from_imzml(path)
        import hashlib

        manifest = self.work_dir.file("input.manifest.json")
        content = manifest.read_text() if manifest.exists() else str(path)
        key = (self.ds_id, hashlib.sha256(content.encode()).hexdigest())
        return self.residency.dataset(
            key, lambda: SpectralDataset.from_imzml(path))

    def _store_annotation_images(
        self, ds: SpectralDataset, search: MSMBasicSearch, bundle: SearchResultsBundle
    ) -> None:
        """Persist ion images for annotations at FDR <= 0.5 (the reference
        stores images for scored target ions — ``store_sf_iso_images`` [U]).

        On the jax paths — single-device AND mesh-sharded — the images come
        off the DEVICE arrays (bit-identical to the numpy extraction via the
        shared integer grids) instead of being re-extracted on CPU (VERDICT
        r1 item 9); numpy_ref uses the numpy extractor.
        """
        import numpy as np

        table = search.last_table
        if table is None or bundle.annotations.empty:
            return
        keep = bundle.annotations[bundle.annotations.fdr_level <= 0.5]
        want = set(zip(keep.sf, keep.adduct))
        idx = [
            i for i, (sf, ad) in enumerate(zip(table.sfs, table.adducts))
            if (sf, ad) in want
        ]
        if not idx:
            return
        sub = table.__class__(
            sfs=[table.sfs[i] for i in idx],
            adducts=[table.adducts[i] for i in idx],
            mzs=table.mzs[idx],
            ints=table.ints[idx],
            n_valid=table.n_valid[idx],
            targets=table.targets[idx],
        )
        backend = search.last_backend
        if backend is not None and hasattr(backend, "extract_ion_images"):
            images = backend.extract_ion_images(sub)
        else:
            from ..ops.imager_np import SortedPeakView, extract_ion_images

            view = SortedPeakView.prepare(ds, self.ds_config.image_generation.ppm)
            images = extract_ion_images(view, sub, self.ds_config.image_generation.ppm)
        path = self.store.store_ion_images(
            self.ds_id, np.asarray(images),
            list(zip(sub.sfs, sub.adducts)), ds.nrows, ds.ncols,
            mask=ds.get_sample_area_mask(),
        )
        logger.info("stored %d ion image sets -> %s", len(idx), path)
