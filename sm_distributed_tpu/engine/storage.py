"""Result storage + job ledger + annotation index — the L0 state plane.

TPU-native/offline replacements for the reference's service stack (SURVEY.md
#2 ``db.py::DB`` Postgres, #14 ``search_results.py::SearchResults``, #15
``es_export.py::ESExporter``, #21 SQL schema):

- ``JobLedger``     — sqlite tables ``dataset`` / ``job`` with status rows
  (STARTED/FINISHED/FAILED), the reference's job bookkeeping.
- ``SearchResultsStore`` — per-job parquet files (annotations + all metrics)
  plus sparse ion images as npz, the reference's ``iso_image_metrics`` /
  ``iso_image`` tables.
- ``AnnotationIndex`` — a searchable sqlite table of flattened annotations
  (ds, sf, adduct, msm, fdr, mz), the reference's Elasticsearch index:
  ``index_ds`` / ``delete_ds`` / ``search`` with the same flattening.

Everything lives under ``StorageConfig.results_dir``; all writers are
idempotent per (ds_id, job_id) so failed jobs can simply be re-run
(SURVEY.md §5.3: idempotent re-run as the recovery model).
"""

from __future__ import annotations

import json
import sqlite3
import time
from pathlib import Path

import numpy as np
import pandas as pd

from ..utils.failpoints import failpoint, record_recovery, register_failpoint
from ..utils.logger import logger

FP_RESULTS_RENAME = register_failpoint(
    "storage.results_rename",
    "between results tmp writes and their atomic renames into place")
FP_INDEX_COMMIT = register_failpoint(
    "storage.index_commit",
    "inside the annotation index delete+insert, before the commit")
FP_LEDGER_FINISH = register_failpoint(
    "ledger.finish_job", "before the job row flips STARTED -> FINISHED")

JOB_STARTED = "STARTED"
JOB_FINISHED = "FINISHED"
JOB_FAILED = "FAILED"

_SCHEMA = """
CREATE TABLE IF NOT EXISTS dataset (
    id TEXT PRIMARY KEY,
    name TEXT,
    input_path TEXT,
    ds_config TEXT,
    created_at REAL
);
CREATE TABLE IF NOT EXISTS job (
    id INTEGER PRIMARY KEY AUTOINCREMENT,
    ds_id TEXT REFERENCES dataset(id),
    status TEXT,
    started_at REAL,
    finished_at REAL,
    error TEXT
);
CREATE TABLE IF NOT EXISTS annotation (
    ds_id TEXT,
    job_id INTEGER,
    sf TEXT,
    adduct TEXT,
    mz REAL,
    msm REAL,
    fdr REAL,
    fdr_level REAL,
    chaos REAL,
    spatial REAL,
    spectral REAL
);
CREATE INDEX IF NOT EXISTS annotation_ds ON annotation(ds_id);
CREATE INDEX IF NOT EXISTS annotation_sf ON annotation(sf);
"""


class JobLedger:
    """Job/dataset status bookkeeping (reference: ``job``/``dataset`` rows in
    Postgres written by SearchJob [U])."""

    # Concurrent scheduler workers each open their own connection to the one
    # ledger file; without a busy timeout a writer collision dies instantly
    # with "database is locked" (ISSUE 2 satellite).
    BUSY_TIMEOUT_S = 30.0

    def __init__(self, results_dir: str | Path):
        self.root = Path(results_dir)
        self.root.mkdir(parents=True, exist_ok=True)
        self.db_path = self.root / "engine.sqlite"
        self._conn = sqlite3.connect(self.db_path, timeout=self.BUSY_TIMEOUT_S)
        self._conn.execute(
            f"PRAGMA busy_timeout={int(self.BUSY_TIMEOUT_S * 1000)}")
        # WAL lets readers proceed under a writer (index replace vs /jobs
        # queries); falls back gracefully where the filesystem can't do WAL
        mode = self._conn.execute("PRAGMA journal_mode=WAL").fetchone()[0]
        if str(mode).lower() != "wal":
            logger.warning(
                "ledger %s: journal_mode=WAL unavailable (got %r); "
                "concurrent access falls back to rollback-journal locking",
                self.db_path, mode)
        else:
            self._conn.execute("PRAGMA synchronous=NORMAL")
        self._conn.executescript(_SCHEMA)
        self._conn.commit()

    def upsert_dataset(self, ds_id: str, name: str, input_path: str,
                       ds_config: dict) -> None:
        self._conn.execute(
            "INSERT INTO dataset(id, name, input_path, ds_config, created_at) "
            "VALUES(?,?,?,?,?) ON CONFLICT(id) DO UPDATE SET "
            "name=excluded.name, input_path=excluded.input_path, "
            "ds_config=excluded.ds_config",
            (ds_id, name, input_path, json.dumps(ds_config), time.time()),
        )
        self._conn.commit()

    def start_job(self, ds_id: str) -> int:
        cur = self._conn.execute(
            "INSERT INTO job(ds_id, status, started_at) VALUES(?,?,?)",
            (ds_id, JOB_STARTED, time.time()),
        )
        self._conn.commit()
        return int(cur.lastrowid)

    def finish_job(self, job_id: int) -> None:
        failpoint(FP_LEDGER_FINISH)
        self._conn.execute(
            "UPDATE job SET status=?, finished_at=? WHERE id=?",
            (JOB_FINISHED, time.time(), job_id),
        )
        self._conn.commit()

    def fail_job(self, job_id: int, error: str) -> None:
        self._conn.execute(
            "UPDATE job SET status=?, finished_at=?, error=? WHERE id=?",
            (JOB_FAILED, time.time(), error[:4000], job_id),
        )
        self._conn.commit()

    def job_status(self, job_id: int) -> str | None:
        row = self._conn.execute(
            "SELECT status FROM job WHERE id=?", (job_id,)
        ).fetchone()
        return row[0] if row else None

    def fail_stale_started(self, ds_id: str | None = None,
                           error: str = "orphaned by process crash",
                           ds_ids=None, before: float | None = None) -> int:
        """Crash reconciliation: mark STARTED job rows FAILED.  A row stuck in
        STARTED means the owning process died between start_job and its
        terminal update — rerunning is idempotent, but the ledger must not
        report a dead job as live forever.  With ``ds_id`` the sweep is
        scoped to one dataset.

        Multi-replica scoping (ISSUE 8 satellite): a takeover replica must
        not reap a LIVE peer's in-flight rows.  ``ds_ids`` restricts the
        sweep to the datasets whose spool messages the takeover actually
        fenced + requeued (the dead replica's shard contents), and
        ``before`` restricts it to rows started before the takeover
        timestamp — a row a live peer started afterwards survives even if
        its dataset collides."""
        q = "UPDATE job SET status=?, finished_at=?, error=? WHERE status=?"
        args: list = [JOB_FAILED, time.time(), error, JOB_STARTED]
        if ds_id is not None:
            q += " AND ds_id=?"
            args.append(ds_id)
        if ds_ids is not None:
            ids = sorted({str(d) for d in ds_ids})
            if not ids:
                return 0
            q += f" AND ds_id IN ({','.join('?' * len(ids))})"
            args.extend(ids)
        if before is not None:
            q += " AND started_at < ?"
            args.append(float(before))
        cur = self._conn.execute(q, args)
        self._conn.commit()
        n = cur.rowcount if cur.rowcount and cur.rowcount > 0 else 0
        if n:
            record_recovery("ledger.stale_started")
            logger.warning("ledger: marked %d orphaned STARTED job(s) FAILED", n)
        return n

    def jobs(self, ds_id: str | None = None) -> pd.DataFrame:
        q = "SELECT * FROM job"
        args: tuple = ()
        if ds_id is not None:
            q += " WHERE ds_id=?"
            args = (ds_id,)
        return pd.read_sql_query(q + " ORDER BY id", self._conn, params=args)

    def close(self) -> None:
        self._conn.close()


class AnnotationIndex:
    """The Elasticsearch-equivalent searchable annotation index
    (reference: ``ESExporter.index_ds/delete_ds`` [U], SURVEY.md #15)."""

    def __init__(self, ledger: JobLedger):
        self._conn = ledger._conn

    def index_ds(self, ds_id: str, job_id: int, annotations: pd.DataFrame,
                 ion_mzs: dict[tuple[str, str], float] | None = None) -> int:
        """Flatten + index annotations; re-indexing a dataset replaces its
        rows (idempotent, like delete+index in the reference).  Delete and
        insert commit as ONE transaction, so a failure mid-replace leaves
        the previous successful job's rows queryable (ADVICE r1)."""
        rows = [
            (
                ds_id, job_id, r.sf, r.adduct,
                float(ion_mzs.get((r.sf, r.adduct), np.nan)) if ion_mzs else np.nan,
                float(r.msm), float(r.fdr), float(r.fdr_level),
                float(r.chaos), float(r.spatial), float(r.spectral),
            )
            for r in annotations.itertuples()
        ]
        try:
            self._conn.execute("DELETE FROM annotation WHERE ds_id=?", (ds_id,))
            self._conn.executemany(
                "INSERT INTO annotation VALUES(?,?,?,?,?,?,?,?,?,?,?)", rows
            )
            # a crash HERE rolls the whole replace back on the next open —
            # the previous job's rows stay queryable (the invariant the
            # chaos sweep's storage.index_commit scenario checks)
            failpoint(FP_INDEX_COMMIT)
        except Exception:
            self._conn.rollback()
            raise
        self._conn.commit()
        return len(rows)

    def delete_ds(self, ds_id: str, job_id: int | None = None) -> None:
        """Drop a dataset's index rows; with ``job_id``, only that job's rows
        (failure cleanup must not erase a previous successful job's index)."""
        if job_id is None:
            self._conn.execute("DELETE FROM annotation WHERE ds_id=?", (ds_id,))
        else:
            self._conn.execute(
                "DELETE FROM annotation WHERE ds_id=? AND job_id=?", (ds_id, job_id)
            )
        self._conn.commit()

    def search(
        self,
        ds_id: str | None = None,
        sf: str | None = None,
        adduct: str | None = None,
        max_fdr_level: float | None = None,
        min_msm: float | None = None,
        mz_min: float | None = None,
        mz_max: float | None = None,
    ) -> pd.DataFrame:
        """Query annotations; mz_min/mz_max cover the reference webapp's
        search-by-mass use of the ES index (principal-peak ion m/z)."""
        clauses, args = [], []
        for col, val in (("ds_id", ds_id), ("sf", sf), ("adduct", adduct)):
            if val is not None:
                clauses.append(f"{col}=?")
                args.append(val)
        if max_fdr_level is not None:
            clauses.append("fdr_level<=?")
            args.append(max_fdr_level)
        if min_msm is not None:
            clauses.append("msm>=?")
            args.append(min_msm)
        if mz_min is not None:
            clauses.append("mz>=?")
            args.append(mz_min)
        if mz_max is not None:
            clauses.append("mz<=?")
            args.append(mz_max)
        q = "SELECT * FROM annotation"
        if clauses:
            q += " WHERE " + " AND ".join(clauses)
        return pd.read_sql_query(q + " ORDER BY msm DESC", self._conn, params=args)


class SearchResultsStore:
    """Persist a finished search (reference: ``SearchResults.store`` →
    ``iso_image_metrics`` + ``iso_image`` + ES trigger [U], SURVEY.md #14)."""

    def __init__(self, ledger: JobLedger, store_images: bool = True,
                 image_format: str = "npz"):
        self.ledger = ledger
        self.index = AnnotationIndex(ledger)
        self.store_images = store_images
        self.image_format = image_format

    def ds_dir(self, ds_id: str) -> Path:
        d = self.ledger.root / ds_id
        d.mkdir(parents=True, exist_ok=True)
        return d

    def store(self, ds_id: str, job_id: int, bundle,
              ion_mzs: dict[tuple[str, str], float] | None = None) -> Path:
        """Write annotations + metrics parquet, index annotations. Returns the
        dataset results dir.

        Write order protects the previous successful job (ADVICE r1/r2):
        files land under temp names and are atomically renamed into place
        BEFORE the index replace commits — a crash before the renames leaves
        the old results fully intact, and a crash between the renames and
        the index transaction leaves new parquet with the old index rows,
        which the next successful ``store`` (or a re-index) repairs; the
        index never references annotations that are not on disk.
        """
        d = self.ds_dir(ds_id)
        # disk-budget preflight (ISSUE 10, service/resources.py): deny the
        # store up front — before any tmp write — when the headroom floor
        # would be breached; rough estimate, refined by the GC rescan
        from ..service import resources as _resources

        _resources.preflight(
            "storage.results_store",
            256 * (len(bundle.annotations) + len(bundle.all_metrics)) + 8192)
        # sweep tmp debris a crashed previous store left behind: the rerun
        # overwrites the same names, but a FAILED-then-abandoned dataset
        # must not leak .tmp files forever
        stale = list(d.glob("*.tmp"))
        for p in stale:
            p.unlink(missing_ok=True)
        if stale:
            record_recovery("storage.stale_tmp")
        tmps = []
        for name, df in (("annotations.parquet", bundle.annotations),
                         ("all_metrics.parquet", bundle.all_metrics)):
            tmp = d / (name + ".tmp")
            df.to_parquet(tmp, index=False)
            tmps.append((tmp, d / name))
        tmp_t = d / "timings.json.tmp"
        tmp_t.write_text(json.dumps(bundle.timings, indent=2))
        tmps.append((tmp_t, d / "timings.json"))
        failpoint(FP_RESULTS_RENAME, path=tmps[0][0])
        for tmp, dst in tmps:
            tmp.replace(dst)
        n = self.index.index_ds(ds_id, job_id, bundle.annotations, ion_mzs)
        # read-plane publish (ISSUE 16): swap the dataset's columnar read
        # segment LAST, behind the same caller-held fence as the rest of the
        # store — readers see the previous complete segment until this commits
        from .index import publish_segment

        publish_segment(d, ds_id, job_id, bundle.annotations, ion_mzs)
        logger.info("stored %d annotations for ds %s under %s", n, ds_id, d)
        return d

    def store_ion_images(
        self,
        ds_id: str,
        images: np.ndarray,          # (n_ions, max_peaks, n_pix) dense
        ions: list[tuple[str, str]],
        nrows: int,
        ncols: int,
        mask: np.ndarray | None = None,
    ) -> Path:
        """Sparse-store ion images (reference keeps scipy CSR blobs in the
        ``iso_image`` table [U]; dense tiles live on TPU, sparsity only at
        host egress — SURVEY.md §2c).  PNG mode writes ALL isotope-peak
        images (suffix _0.._K-1, like the reference's per-isotope PNGs [U])
        with the sample-area mask rendered transparent."""
        d = self.ds_dir(ds_id)
        if self.image_format == "png":
            from .png import PngGenerator

            gen = PngGenerator(mask=mask)
            img_dir = d / "ion_images"
            img_dir.mkdir(exist_ok=True)
            for (sf, adduct), ion_imgs in zip(ions, images):
                name = f"{sf}{adduct}".replace("+", "p").replace("-", "m")
                for k in range(ion_imgs.shape[0]):
                    gen.save(ion_imgs[k].reshape(nrows, ncols),
                             img_dir / f"{name}_{k}.png")
            return img_dir
        flat = images.reshape(images.shape[0] * images.shape[1], -1)
        nz = flat != 0
        counts = nz.sum(axis=1)
        indptr = np.zeros(flat.shape[0] + 1, dtype=np.int64)
        np.cumsum(counts, out=indptr[1:])
        cols = np.nonzero(nz)[1].astype(np.int32)
        vals = flat[nz].astype(np.float32)
        # tmp + atomic rename: the tile service (ISSUE 16) reads this file
        # under concurrent re-annotation — readers must see the previous
        # complete npz or the new one, never a partial write
        tmp = d / "ion_images.npz.tmp"
        with open(tmp, "wb") as f:
            np.savez_compressed(
                f,
                data=vals, indices=cols, indptr=indptr,
                shape=np.array(
                    [images.shape[0], images.shape[1], nrows, ncols]),
                ions=np.array([f"{sf}|{adduct}" for sf, adduct in ions]),
            )
        tmp.replace(d / "ion_images.npz")
        return d / "ion_images.npz"

    @staticmethod
    def load_ion_images(path: str | Path) -> tuple[np.ndarray, list[tuple[str, str]]]:
        """Inverse of ``store_ion_images`` (npz format): dense (n_ions, K,
        nrows, ncols) + ion list."""
        z = np.load(path, allow_pickle=False)
        n_ions, k, nrows, ncols = (int(x) for x in z["shape"])
        flat = np.zeros((n_ions * k, nrows * ncols), dtype=np.float32)
        indptr = z["indptr"]
        for r in range(flat.shape[0]):
            s, e = indptr[r], indptr[r + 1]
            flat[r, z["indices"][s:e]] = z["data"][s:e]
        ions = [tuple(s.split("|", 1)) for s in z["ions"].tolist()]
        return flat.reshape(n_ions, k, nrows, ncols), ions
