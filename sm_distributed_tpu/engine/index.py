"""Columnar per-dataset annotation segments — the read-optimized index.

The reference engine served annotations to users through Elasticsearch
(SURVEY.md #15 ``es_export.py``); the sqlite ``AnnotationIndex`` in
``storage.py`` replaced the *write* side of that, but reads still went
through the writer's connection.  This module is the read plane (ISSUE 16):
at job-terminal commit ``SearchResultsStore.store`` publishes the dataset's
annotation table into a packed-npy columnar **segment**
(``<results_dir>/<ds_id>/segment.npz``) via tmp-write + verify + atomic
``os.replace`` — readers either see the previous complete segment or the new
complete segment, never a partial one.  ``SegmentReader`` then serves:

- dataset listing (``datasets()``);
- filtered/sorted/keyset-paginated per-dataset queries (``query()``), with
  formula/adduct/FDR-threshold/MSM/mz-window filters;
- cross-dataset per-molecule cohort queries (``cohort()``).

The publish seam carries the ``index.segment_commit`` failpoint so the chaos
sweep can kill the process between the tmp write and the swap and prove the
previous segment stays served and the rerun converges (docs/RECOVERY.md).

Query grammar (docs/SERVICE.md "Read path"): sort orders are ``msm`` | ``mz``
| ``fdr`` | ``sf``, ascending or descending, ties broken by ``(sf, adduct)``
in the same direction; pagination is keyset (the cursor encodes the last
row's sort key, so pages stay stable under concurrent republish), and a
cursor minted under one ``order``/``dir`` is rejected under another.

COMPILE_SURFACE / NUMERICS exemption (argued): this module is pure-host
numpy I/O — it projects already-scored float64 columns into npz and back,
never jits, never scores, never reduces.  No XLA compile can originate
here (nothing for retrace to attribute) and no ULP contract applies (the
values are copied, not computed; sort comparisons on copied float64 are
exact).
"""

from __future__ import annotations

import base64
import io
import json
import math
import os
import time
from dataclasses import dataclass
from pathlib import Path

import numpy as np

from ..utils.failpoints import failpoint, register_failpoint
from ..utils.logger import logger

FP_SEGMENT_COMMIT = register_failpoint(
    "index.segment_commit",
    "between the annotation-segment tmp write and its atomic swap into place")

SEGMENT_NAME = "segment.npz"
SCHEMA_VERSION = 1

# the columnar layout: float columns + the two string key columns
_FLOAT_COLS = ("mz", "msm", "fdr", "fdr_level", "chaos", "spatial", "spectral")
_ORDER_COLS = ("msm", "mz", "fdr", "sf")


class SegmentError(RuntimeError):
    """A segment file that cannot be read back (torn/corrupt)."""


class CursorError(ValueError):
    """A pagination cursor that is malformed or minted under a different
    order/direction than the request's."""


@dataclass
class Segment:
    """One dataset's published annotation segment, fully decoded."""

    ds_id: str
    job_id: int
    published_at: float
    n_rows: int
    sf: np.ndarray
    adduct: np.ndarray
    cols: dict[str, np.ndarray]

    def rows(self) -> list[dict]:
        """Decode to JSON-ready row dicts (NaN floats become None)."""
        out = []
        for i in range(self.n_rows):
            row = {"ds_id": self.ds_id, "job_id": self.job_id,
                   "sf": str(self.sf[i]), "adduct": str(self.adduct[i])}
            for c in _FLOAT_COLS:
                v = float(self.cols[c][i])
                row[c] = v if math.isfinite(v) else None
            out.append(row)
        return out


def publish_segment(ds_dir: str | Path, ds_id: str, job_id: int,
                    annotations, ion_mzs=None) -> Path:
    """Publish a dataset's annotation table as its columnar read segment.

    Called by ``SearchResultsStore.store`` AFTER the parquet renames + sqlite
    index commit, i.e. behind the caller's fence check (PR 8): a fenced
    replica abandons the store before reaching this seam, so it can never
    swap a stale segment over a peer's newer one.  Tmp-write + read-back
    verify + ``os.replace`` keeps the swap atomic; the tmp name matches the
    ``*.tmp`` debris sweep in ``store`` and the chaos sweep's debris check.
    """
    d = Path(ds_dir)
    # fixed-width unicode, not object dtype — readers load with
    # allow_pickle=False (a torn file must never execute anything)
    sf = np.asarray(annotations["sf"].astype(str).to_numpy(), dtype=np.str_)
    adduct = np.asarray(
        annotations["adduct"].astype(str).to_numpy(), dtype=np.str_)
    mz = np.array(
        [float(ion_mzs.get((s, a), np.nan)) if ion_mzs else np.nan
         for s, a in zip(sf, adduct)], dtype=np.float64)
    cols: dict[str, np.ndarray] = {"mz": mz}
    for c in _FLOAT_COLS[1:]:
        cols[c] = annotations[c].to_numpy(dtype=np.float64)
    meta = {"schema": SCHEMA_VERSION, "ds_id": ds_id, "job_id": int(job_id),
            "published_at": time.time(), "n_rows": int(len(sf))}
    tmp = d / (SEGMENT_NAME + ".tmp")
    # np.savez appends ".npz" to plain path names — write through a file
    # object so the tmp keeps its sweep-matched name
    with open(tmp, "wb") as f:
        np.savez_compressed(
            f, sf=sf, adduct=adduct,
            meta=np.array([json.dumps(meta)]), **cols)
    failpoint(FP_SEGMENT_COMMIT, path=tmp)
    # read-back verify: a torn tmp (fault injection, ENOSPC short write)
    # must fail THIS attempt rather than swap garbage over a good segment
    _load_file(tmp)
    os.replace(tmp, d / SEGMENT_NAME)
    logger.info("published read segment for ds %s job %s (%d rows)",
                ds_id, job_id, meta["n_rows"])
    return d / SEGMENT_NAME


def _load_file(path: Path) -> Segment:
    try:
        with open(path, "rb") as f:
            z = np.load(io.BytesIO(f.read()), allow_pickle=False)
            meta = json.loads(str(z["meta"][0]))
            seg = Segment(
                ds_id=str(meta["ds_id"]), job_id=int(meta["job_id"]),
                published_at=float(meta["published_at"]),
                n_rows=int(meta["n_rows"]),
                sf=z["sf"], adduct=z["adduct"],
                cols={c: z[c] for c in _FLOAT_COLS})
    except (OSError, ValueError, KeyError, json.JSONDecodeError) as exc:
        raise SegmentError(f"unreadable segment {path}: {exc}") from exc
    if len(seg.sf) != seg.n_rows or any(
            len(seg.cols[c]) != seg.n_rows for c in _FLOAT_COLS):
        raise SegmentError(f"segment {path}: column lengths != n_rows")
    return seg


def _encode_cursor(order: str, direction: str, key: tuple) -> str:
    raw = json.dumps({"o": order, "d": direction, "k": list(key)})
    return base64.urlsafe_b64encode(raw.encode()).decode()


def _decode_cursor(cursor: str, order: str, direction: str) -> tuple:
    try:
        obj = json.loads(base64.urlsafe_b64decode(cursor.encode()).decode())
        key = obj["k"]
        if not isinstance(key, list) or len(key) != 3:
            raise ValueError("bad key")
    except (ValueError, KeyError, UnicodeDecodeError) as exc:
        raise CursorError(f"malformed cursor: {exc}") from exc
    if obj.get("o") != order or obj.get("d") != direction:
        raise CursorError(
            f"cursor was minted under order={obj.get('o')}/{obj.get('d')}, "
            f"request asks order={order}/{direction}")
    return tuple(key)


def _sort_key(row: dict, order: str) -> tuple:
    v = row[order]
    if order != "sf" and v is None:
        v = float("-inf")              # NaN mz sorts first ascending
    return (v, row["sf"], row["adduct"])


class SegmentReader:
    """Serve queries over the published per-dataset segments.  Stateless —
    every call re-reads the segment files (the ReadPath LRU in front of it
    owns all caching), so a republished segment is visible immediately."""

    def __init__(self, results_dir: str | Path):
        self.results_dir = Path(results_dir)

    def segment_path(self, ds_id: str) -> Path:
        return self.results_dir / ds_id / SEGMENT_NAME

    def load(self, ds_id: str) -> Segment | None:
        """The dataset's segment, or None when it has never published one.
        Raises ``SegmentError`` on a torn/corrupt file (must not happen
        under the atomic-swap protocol; surfaced loudly if it does)."""
        path = self.segment_path(ds_id)
        if not path.exists():
            return None
        return _load_file(path)

    def datasets(self) -> list[dict]:
        """Every dataset with a published segment, with publish metadata."""
        out = []
        if not self.results_dir.exists():
            return out
        for p in sorted(self.results_dir.glob(f"*/{SEGMENT_NAME}")):
            seg = _load_file(p)
            out.append({"ds_id": seg.ds_id, "job_id": seg.job_id,
                        "n_rows": seg.n_rows,
                        "published_at": seg.published_at})
        return out

    @staticmethod
    def filter_rows(rows: list[dict], sf=None, adduct=None,
                    max_fdr_level=None, min_msm=None,
                    mz_min=None, mz_max=None) -> list[dict]:
        """The filter semantics, shared by query() and cohort() — and by the
        brute-force parity test, which re-applies them over the parquet."""
        out = []
        for r in rows:
            if sf is not None and r["sf"] != sf:
                continue
            if adduct is not None and r["adduct"] != adduct:
                continue
            if max_fdr_level is not None and not (
                    r["fdr_level"] is not None
                    and r["fdr_level"] <= max_fdr_level):
                continue
            if min_msm is not None and not (
                    r["msm"] is not None and r["msm"] >= min_msm):
                continue
            if mz_min is not None and not (
                    r["mz"] is not None and r["mz"] >= mz_min):
                continue
            if mz_max is not None and not (
                    r["mz"] is not None and r["mz"] <= mz_max):
                continue
            out.append(r)
        return out

    def query(self, ds_id: str, *, sf=None, adduct=None, max_fdr_level=None,
              min_msm=None, mz_min=None, mz_max=None, order: str = "msm",
              direction: str = "desc", limit: int = 100,
              cursor: str | None = None) -> dict | None:
        """Filtered, sorted, keyset-paginated annotations of one dataset.
        Returns None when the dataset has no published segment."""
        if order not in _ORDER_COLS:
            raise CursorError(
                f"unknown order {order!r} (valid: {', '.join(_ORDER_COLS)})")
        if direction not in ("asc", "desc"):
            raise CursorError(f"direction must be asc|desc, got {direction!r}")
        seg = self.load(ds_id)
        if seg is None:
            return None
        rows = self.filter_rows(
            seg.rows(), sf=sf, adduct=adduct, max_fdr_level=max_fdr_level,
            min_msm=min_msm, mz_min=mz_min, mz_max=mz_max)
        reverse = direction == "desc"
        rows.sort(key=lambda r: _sort_key(r, order), reverse=reverse)
        start = 0
        if cursor:
            last = _decode_cursor(cursor, order, direction)
            for i, r in enumerate(rows):
                k = _sort_key(r, order)
                after = k < last if reverse else k > last
                if after:
                    start = i
                    break
            else:
                start = len(rows)
        page = rows[start:start + max(1, int(limit))]
        next_cursor = None
        if page and start + len(page) < len(rows):
            next_cursor = _encode_cursor(
                order, direction, _sort_key(page[-1], order))
        return {"ds_id": ds_id, "job_id": seg.job_id,
                "published_at": seg.published_at, "total": len(rows),
                "order": order, "direction": direction,
                "rows": page, "next_cursor": next_cursor}

    def cohort(self, sf: str, *, adduct=None, max_fdr_level=None,
               min_msm=None) -> dict:
        """Cross-dataset per-molecule cohort: every published dataset's
        matching annotations for one formula, keyed by dataset."""
        datasets = []
        n_rows = 0
        for entry in self.datasets():
            seg = self.load(entry["ds_id"])
            if seg is None:              # republish race: listed then gone
                continue
            rows = self.filter_rows(
                seg.rows(), sf=sf, adduct=adduct,
                max_fdr_level=max_fdr_level, min_msm=min_msm)
            if rows:
                rows.sort(key=lambda r: _sort_key(r, "msm"), reverse=True)
                datasets.append({"ds_id": seg.ds_id, "job_id": seg.job_id,
                                 "rows": rows})
                n_rows += len(rows)
        return {"sf": sf, "n_datasets": len(datasets), "n_rows": n_rows,
                "datasets": datasets}
