"""Job orchestration + state plane (the reference's L5/L0 layers, TPU-native).

- search_job: SearchJob end-to-end orchestrator (SURVEY.md #13).
- storage:    JobLedger (job/dataset status), SearchResultsStore (parquet +
              sparse ion images), AnnotationIndex (the ES analog) (#2,#14,#15,#21).
- work_dir:   input staging with existence-check resume (#3).
- moldb:      molecular DB import/lookup (#18).
- cli:        run_molecule_search-style CLI (#19).
- daemon:     file-queue job intake, the RabbitMQ analog (#16).
- png:        ion-image PNG rendering (#17).
"""

from .storage import AnnotationIndex, JobLedger, SearchResultsStore
from .work_dir import WorkDirManager

__all__ = [
    "AnnotationIndex",
    "JobLedger",
    "SearchResultsStore",
    "WorkDirManager",
]
