"""Multi-dataset residency for service mode (daemon).

Reference: the daemon keeps ONE long-lived SparkContext across queue
messages, so repeat jobs skip cluster spin-up [U] (SURVEY.md #16).  The
TPU-native analog of that warm state is (a) the host-side CSR dataset
layout (minutes of parse for a large slide) and (b) the backend object —
device-resident flat peak arrays plus the compiled fused executable
(~15-20 s compile + hundreds of MB of HBM transfer).  This cache keeps the
last N of each across daemon messages with LRU eviction, so a second job on
the same dataset/shapes skips prepare AND compile (ROADMAP item 3,
VERDICT r2 item 7).

Keys carry content identity, not just names: datasets key on the staged
input manifest (so a restaged different file misses), backends key on the
search fingerprint (dataset content + image config + batch partition +
ion table) plus every backend-shaping parallel knob.
"""

from __future__ import annotations

import threading
from collections import OrderedDict

from ..utils.logger import logger


class _LRU:
    """Thread-safe LRU.  The service scheduler's workers share one residency
    across concurrent jobs; the lock guards only the dict bookkeeping, NOT
    ``builder()`` — holding it through a minutes-long parse would serialize
    exactly the CPU staging the scheduler exists to overlap.  Two workers
    missing on the same key may therefore both build; the first insert wins
    and the duplicate is dropped (device-backend builds don't race in
    practice because they run under the scheduler's TPU token)."""

    # shared-state registry checked by the smlint guarded-by rule
    # (docs/ANALYSIS.md): mutated only under _lock
    _GUARDED_BY = {"data": "_lock", "hits": "_lock", "misses": "_lock"}

    def __init__(self, maxsize: int):
        self.maxsize = maxsize
        self.data: OrderedDict = OrderedDict()
        self.hits = 0
        self.misses = 0
        self._lock = threading.Lock()

    def get_or_build(self, key, builder):
        with self._lock:
            if self.maxsize <= 0:
                self.misses += 1
            elif key in self.data:
                self.hits += 1
                self.data.move_to_end(key)
                return self.data[key]
            else:
                self.misses += 1
        val = builder()
        if self.maxsize <= 0:
            return val
        with self._lock:
            if key in self.data:       # concurrent builder won — reuse theirs
                return self.data[key]
            self.data[key] = val
            while len(self.data) > self.maxsize:
                old_key, _old = self.data.popitem(last=False)
                logger.info("residency: evicted %s", old_key[0] if old_key else old_key)
        return val


class DatasetResidency:
    """LRU caches for host datasets and compiled backends across jobs."""

    def __init__(self, max_datasets: int = 2, max_backends: int = 2):
        self._datasets = _LRU(max_datasets)
        self._backends = _LRU(max_backends)

    def dataset(self, key, loader):
        return self._datasets.get_or_build(key, loader)

    def backend(self, key, builder):
        return self._backends.get_or_build(key, builder)

    @property
    def stats(self) -> dict:
        return {
            "dataset_hits": self._datasets.hits,
            "dataset_misses": self._datasets.misses,
            "backend_hits": self._backends.hits,
            "backend_misses": self._backends.misses,
        }
