"""Molecular-database import + lookup.

Reference: ``scripts/import_molecular_db.py`` [U] (SURVEY.md #18) loads a CSV
(HMDB, ChEBI, LipidMaps exports) into Postgres ``formula_db``/``agg_formula``
tables; searches then select the formula list by (name, version).  Here the
same contract against the engine sqlite: import a CSV of molecules, aggregate
unique sum formulas per database, look them up by name/version.

CSV format (header required, extra columns ignored): columns ``formula`` (or
``sf``) and optionally ``id``/``name`` per molecule — matching the loose
shape of the reference's importer input.
"""

from __future__ import annotations

import csv
from pathlib import Path

from .storage import JobLedger

_SCHEMA = """
CREATE TABLE IF NOT EXISTS formula_db (
    id INTEGER PRIMARY KEY AUTOINCREMENT,
    name TEXT,
    version TEXT,
    UNIQUE(name, version)
);
CREATE TABLE IF NOT EXISTS molecule (
    db_id INTEGER REFERENCES formula_db(id),
    mol_id TEXT,
    mol_name TEXT,
    sf TEXT
);
CREATE INDEX IF NOT EXISTS molecule_db ON molecule(db_id);
"""


class MolecularDB:
    """Import/lookup of molecular databases in the engine sqlite."""

    def __init__(self, ledger: JobLedger):
        self._conn = ledger._conn
        self._conn.executescript(_SCHEMA)
        self._conn.commit()

    def import_csv(self, path: str | Path, name: str, version: str) -> int:
        """Load a molecules CSV; replaces any existing (name, version) DB.
        Returns the number of molecules imported."""
        path = Path(path)
        with path.open(newline="") as fh:
            reader = csv.DictReader(fh)
            if reader.fieldnames is None:
                raise ValueError(f"{path}: empty CSV")
            cols = {c.lower().strip(): c for c in reader.fieldnames}
            sf_col = cols.get("formula") or cols.get("sf")
            if sf_col is None:
                raise ValueError(
                    f"{path}: need a 'formula' or 'sf' column, got {reader.fieldnames}"
                )
            id_col = cols.get("id") or cols.get("mol_id")
            name_col = cols.get("name") or cols.get("mol_name")
            rows = [
                (
                    (r.get(id_col) or "").strip() if id_col else "",
                    (r.get(name_col) or "").strip() if name_col else "",
                    r[sf_col].strip(),
                )
                for r in reader
                if (r.get(sf_col) or "").strip()
            ]
        # no RETURNING: the image's sqlite predates 3.35, so upsert then
        # select the row id in two statements (same transaction)
        self._conn.execute(
            "INSERT INTO formula_db(name, version) VALUES(?,?) "
            "ON CONFLICT(name, version) DO NOTHING",
            (name, version),
        )
        db_id = self._conn.execute(
            "SELECT id FROM formula_db WHERE name=? AND version=?",
            (name, version),
        ).fetchone()[0]
        self._conn.execute("DELETE FROM molecule WHERE db_id=?", (db_id,))
        self._conn.executemany(
            "INSERT INTO molecule(db_id, mol_id, mol_name, sf) VALUES(?,?,?,?)",
            [(db_id, mid, mname, sf) for mid, mname, sf in rows],
        )
        self._conn.commit()
        return len(rows)

    def formulas(self, name: str, version: str | None = None) -> list[str]:
        """Unique sum formulas of a database, insertion-ordered (the
        reference's ``agg_formula`` aggregation [U])."""
        if version is None:
            row = self._conn.execute(
                "SELECT id FROM formula_db WHERE name=? ORDER BY id DESC LIMIT 1",
                (name,),
            ).fetchone()
        else:
            row = self._conn.execute(
                "SELECT id FROM formula_db WHERE name=? AND version=?",
                (name, version),
            ).fetchone()
        if row is None:
            raise KeyError(f"molecular DB {name!r} (version={version!r}) not imported")
        out = self._conn.execute(
            "SELECT DISTINCT sf FROM molecule WHERE db_id=? ORDER BY rowid", (row[0],)
        ).fetchall()
        return [r[0] for r in out]

    def databases(self) -> list[tuple[str, str]]:
        return [
            (r[0], r[1])
            for r in self._conn.execute(
                "SELECT name, version FROM formula_db ORDER BY id"
            ).fetchall()
        ]
