"""Live-acquisition streaming ingest (ISSUE 19).

Real instruments rasterize a slide pixel-by-pixel over minutes-to-hours;
waiting for a finished imzML file wastes the whole acquisition window.  A
``mode=stream`` submit opens a long-lived stateful job instead: the client
appends spectra chunks with ``POST /datasets/<id>/pixels`` while the
acquisition runs, gets provisional FDR-ranked annotations after every
committed chunk group, and closes with ``POST /datasets/<id>/finish`` —
whereupon the stream attempt converges **bit-identically** to what a
one-shot batch submit over the same pixels would have produced.

Three pieces, each crash-safe on its own:

``ChunkLog``
    The durable acquisition record: ``<work_dir>/stream/<ds_id>/`` holds
    one ``chunk_<seq>.npz`` per committed chunk plus ``manifest.json``, a
    monotone manifest naming every committed chunk with its CRC.  Both
    writes are tmp + ``os.replace``; the manifest commit is the ONLY
    publication point, so a crash anywhere leaves either the previous
    manifest (chunk invisible, client retries) or the new one (chunk
    durable, retry detected as a duplicate).  Duplicate and out-of-order
    POSTs are idempotent by sequence id; a same-seq chunk with DIFFERENT
    payload bytes is rejected (CRC mismatch).

``StreamIngest``
    The service-side facade the admin API calls: per-dataset ChunkLogs
    under one root, governed disk preflight, ``sm_stream_*`` counters.

``StreamSearchJob``
    A ``SearchJob`` subclass the scheduler dispatches for ``mode=stream``
    messages.  While the acquisition is open it polls the manifest,
    re-scores the committed prefix provisionally (riding the PR 13
    shape-bucket lattice — a growing pixel count is a handful of primeable
    row-bucket recompiles), and publishes each re-rank through the normal
    ``partial`` seam.  At end-of-acquisition it runs ``SearchJob.run``
    verbatim with the dataset assembled from the chunk log — the batch
    code path end to end, which is what makes the final report
    bit-identical (``from_arrays`` and ``from_imzml`` build the same
    canonical CSR) and the convergence idempotent under crash/retry: the
    chunk log + manifest + the search checkpoint shards ARE the streaming
    checkpoint a takeover replica resumes from.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
import uuid
import zlib
from pathlib import Path

import numpy as np

try:                                  # posix; ThreadingHTTPServer replicas
    import fcntl                      # share the stream root via flock
except ImportError:                   # pragma: no cover - non-posix fallback
    fcntl = None

from ..io.dataset import SpectralDataset
from ..utils import tracing
from ..utils.cancel import StreamIdleError, hold_cancellable
from ..utils.failpoints import failpoint, record_recovery, register_failpoint
from ..utils.logger import logger
from .search_job import SearchJob

FP_CHUNK_APPEND = register_failpoint(
    "stream.chunk_append",
    "between a stream chunk's tmp write and its os.replace into the log")
FP_MANIFEST_COMMIT = register_failpoint(
    "stream.manifest_commit",
    "after a stream chunk file is durable, before the manifest commit "
    "publishes it")
FP_FINISH = register_failpoint(
    "stream.finish",
    "before the manifest commit that marks an acquisition finished")

_MANIFEST_VERSION = 1


class ChunkConflictError(ValueError):
    """A chunk re-POSTed under an already-committed sequence id carried
    DIFFERENT payload bytes — not a retry but a protocol error."""


class StreamGapError(ValueError):
    """finish() with missing sequence ids: the acquisition record has
    holes, so no batch-identical result can exist yet."""


class StreamEmptyError(StreamGapError):
    """finish() with ZERO committed chunks: an empty acquisition has no
    pixels to annotate, so sealing it would only push a degenerate
    dataset deep into the engine.  Rejected at the seal seam instead."""


# process-local fallback when fcntl is unavailable: one lock per lock-file
# path still serializes the ThreadingHTTPServer handler threads of a
# single replica (the common deployment), just not cross-process peers
_LOCAL_LOCKS: dict[str, threading.Lock] = {}
_LOCAL_LOCKS_GUARD = threading.Lock()


class ChunkLog:
    """Crash-safe, CRC-checksummed chunk log + monotone acquisition
    manifest for one streamed dataset.

    Commit protocol per ``append``: (1) write ``.chunk_<seq>.npz.tmp`` and
    ``os.replace`` it to ``chunk_<seq>.npz`` — durable but UNPUBLISHED;
    (2) rewrite the manifest (tmp + ``os.replace``) now naming the chunk
    with its CRC.  Readers trust only the manifest, so the window between
    (1) and (2) is invisible: a chunk file stranded there by a crash is
    simply overwritten when the unacked chunk is re-posted, and
    ``sweep_debris`` reclaims torn ``.tmp`` leavings.  The manifest is
    monotone: entries are only ever added, and ``finished`` only ever
    flips true.

    The manifest read-modify-write in ``append``/``finish`` is serialized
    by an ``fcntl.flock`` on a per-dataset lock file: the admin API is a
    ThreadingHTTPServer and N replicas serve appends over ONE shared
    stream root, so without the lock two concurrent appends would each
    read the old manifest and the loser's committed-and-acked entry would
    vanish.  Tmp filenames carry a pid+uuid suffix for the same reason —
    two same-seq appends must never interleave writes through one tmp
    path and publish a corrupt chunk under a stale CRC.
    """

    def __init__(self, root: str | Path, ds_id: str):
        self.ds_id = ds_id
        self.dir = Path(root) / ds_id
        self.dir.mkdir(parents=True, exist_ok=True)
        self.manifest_path = self.dir / "manifest.json"
        self.lock_path = self.dir / ".lock"

    @contextlib.contextmanager
    def _locked(self):
        """Exclusive per-dataset critical section around the manifest
        read-modify-write.  flock works across processes AND across the
        handler threads of one process (each entry opens a fresh file
        description), and auto-releases on close — a crashed holder never
        wedges the acquisition."""
        if fcntl is None:             # pragma: no cover - non-posix
            with _LOCAL_LOCKS_GUARD:
                lock = _LOCAL_LOCKS.setdefault(str(self.lock_path),
                                               threading.Lock())
            with lock:
                yield
            return
        with open(self.lock_path, "a+b") as fh:
            fcntl.flock(fh, fcntl.LOCK_EX)
            try:
                yield
            finally:
                fcntl.flock(fh, fcntl.LOCK_UN)

    def _tmp(self, name: str) -> Path:
        """Collision-free tmp path (pid + uuid): concurrent writers each
        rename their OWN bytes, never a half-written shared file."""
        return self.dir / f".{name}.{os.getpid()}.{uuid.uuid4().hex[:8]}.tmp"

    # ------------------------------------------------------------ manifest
    def manifest(self) -> dict:
        if not self.manifest_path.exists():
            return {"version": _MANIFEST_VERSION, "ds_id": self.ds_id,
                    "chunks": {}, "finished": False}
        return json.loads(self.manifest_path.read_text())

    def _commit_manifest(self, m: dict, fence=None) -> None:
        # the fence gate sits immediately before the ONE write that
        # publishes acquisition state: a fenced-out replica's append dies
        # here with the chunk file unpublished (harmless debris, swept)
        if fence is not None:
            fence()
        tmp = self._tmp("manifest.json")
        tmp.write_text(json.dumps(m, indent=2, sort_keys=True))
        os.replace(tmp, self.manifest_path)

    def committed_seqs(self) -> list[int]:
        return sorted(int(s) for s in self.manifest()["chunks"])

    def finished(self) -> bool:
        return bool(self.manifest().get("finished"))

    def n_pixels(self) -> int:
        return sum(int(c["count"]) for c in self.manifest()["chunks"].values())

    # ------------------------------------------------------------- writing
    @staticmethod
    def _crc(coords: np.ndarray, offsets: np.ndarray, mzs: np.ndarray,
             ints: np.ndarray) -> int:
        crc = 0
        for a in (coords, offsets, mzs, ints):
            crc = zlib.crc32(np.ascontiguousarray(a).tobytes(), crc)
        return crc & 0xFFFFFFFF

    @staticmethod
    def _pack(spectra: list[tuple[np.ndarray, np.ndarray]]):
        lens = np.fromiter((len(m) for m, _ in spectra), dtype=np.int64,
                           count=len(spectra))
        offsets = np.concatenate([[0], np.cumsum(lens)]).astype(np.int64)
        mzs = (np.concatenate([np.asarray(m, np.float64) for m, _ in spectra])
               if spectra else np.empty(0, np.float64))
        ints = (np.concatenate([np.asarray(i, np.float32) for _, i in spectra])
                if spectra else np.empty(0, np.float32))
        return offsets, mzs, ints

    def chunk_path(self, seq: int) -> Path:
        return self.dir / f"chunk_{int(seq):06d}.npz"

    def append(self, seq: int, coords, spectra, fence=None) -> dict:
        """Commit one chunk: ``coords`` is (n, 2) int scan coordinates,
        ``spectra`` the matching list of (mzs, ints) pairs.  Idempotent by
        ``seq``: a byte-identical retry is acked as a duplicate without
        touching disk; a conflicting payload raises ``ChunkConflictError``.
        Out-of-order seqs commit fine — ordering only matters at finish."""
        seq = int(seq)
        if seq < 0:
            raise ValueError("stream: chunk seq must be >= 0")
        coords = np.asarray(coords, dtype=np.int64).reshape(-1, 2)
        spectra = [(np.asarray(m, np.float64), np.asarray(i, np.float32))
                   for m, i in spectra]
        if len(coords) != len(spectra):
            raise ValueError(
                f"stream: {len(coords)} coords for {len(spectra)} spectra")
        offsets, mzs, ints = self._pack(spectra)
        crc = self._crc(coords, offsets, mzs, ints)
        # lock spans manifest read -> manifest commit: a concurrent
        # same-dataset append sees THIS entry (duplicate/conflict checks
        # stay truthful) and can never base its commit on a stale manifest
        with self._locked():
            m = self.manifest()
            if m.get("finished"):
                raise StreamGapError(
                    f"stream {self.ds_id}: acquisition already finished")
            prev = m["chunks"].get(str(seq))
            if prev is not None:
                if int(prev["crc"]) != crc:
                    raise ChunkConflictError(
                        f"stream {self.ds_id}: chunk {seq} re-posted with "
                        f"different payload (crc {crc:#x} != {prev['crc']:#x})")
                # lost-ack redelivery: the commit already happened, ack again
                return {"seq": seq, "committed": True, "duplicate": True}
            # disk-budget preflight (ISSUE 10) before any byte lands
            from ..service import resources as _resources

            est = coords.nbytes + offsets.nbytes + mzs.nbytes + ints.nbytes
            _resources.preflight("stream.chunk_append", est + 4096)
            tmp = self._tmp(f"chunk_{seq:06d}.npz")
            with open(tmp, "wb") as fh:
                np.savez(fh, coords=coords, offsets=offsets, mzs=mzs,
                         ints=ints)
            failpoint(FP_CHUNK_APPEND, path=tmp)
            os.replace(tmp, self.chunk_path(seq))
            # the chunk file is durable but unpublished until the manifest
            # commit below — the exactly-once seam chaos_sweep crashes at
            failpoint(FP_MANIFEST_COMMIT, path=self.manifest_path)
            m["chunks"][str(seq)] = {"count": len(spectra), "crc": crc,
                                     "committed_at": time.time()}
            self._commit_manifest(m, fence=fence)
        return {"seq": seq, "committed": True, "duplicate": False}

    def finish(self, fence=None) -> dict:
        """Seal the acquisition.  Requires at least one committed chunk
        and a gap-free sequence 0..n-1; idempotent once sealed."""
        with self._locked():
            m = self.manifest()
            seqs = sorted(int(s) for s in m["chunks"])
            if m.get("finished"):
                return {"finished": True, "duplicate": True,
                        "chunks": len(seqs)}
            if not seqs:
                # [] passes the gap check vacuously, but sealing an empty
                # acquisition would push a zero-pixel dataset into the
                # batch engine — reject here with a distinct reason
                raise StreamEmptyError(
                    f"stream {self.ds_id}: cannot finish with zero "
                    f"committed chunks")
            if seqs != list(range(len(seqs))):
                missing = sorted(set(range(seqs[-1] + 1)) - set(seqs))
                raise StreamGapError(
                    f"stream {self.ds_id}: cannot finish with missing chunk "
                    f"seqs {missing} (committed: {len(seqs)})")
            failpoint(FP_FINISH, path=self.manifest_path)
            m["finished"] = True
            m["finished_at"] = time.time()
            self._commit_manifest(m, fence=fence)
        return {"finished": True, "duplicate": False, "chunks": len(seqs)}

    # ------------------------------------------------------------- reading
    def load_chunk(self, seq: int):
        """(coords, spectra) for one committed chunk, CRC-verified — a
        corrupted file fails loudly rather than skewing the science."""
        entry = self.manifest()["chunks"].get(str(int(seq)))
        if entry is None:
            raise KeyError(f"stream {self.ds_id}: chunk {seq} not committed")
        try:
            with np.load(self.chunk_path(seq)) as z:
                coords, offsets = z["coords"], z["offsets"]
                mzs, ints = z["mzs"], z["ints"]
        except OSError:
            raise
        except Exception as exc:          # zipfile.BadZipFile, KeyError, ...
            raise OSError(
                f"stream {self.ds_id}: chunk {seq} unreadable "
                f"({type(exc).__name__}: {exc})") from exc
        crc = self._crc(coords, offsets, mzs, ints)
        if crc != int(entry["crc"]):
            raise OSError(
                f"stream {self.ds_id}: chunk {seq} CRC mismatch "
                f"({crc:#x} != {int(entry['crc']):#x})")
        spectra = [(mzs[offsets[i]:offsets[i + 1]],
                    ints[offsets[i]:offsets[i + 1]])
                   for i in range(len(coords))]
        return coords, spectra

    def assemble_dataset(self, seqs: list[int] | None = None) -> SpectralDataset:
        """Build the canonical CSR dataset over the given committed chunks
        (default: all, in seq order).  ``from_arrays`` lexsorts by
        (pixel, m/z) regardless of arrival order, so the result depends
        only on the SET of pixels — the bit-identity anchor."""
        if seqs is None:
            seqs = self.committed_seqs()
        all_coords: list[np.ndarray] = []
        all_spectra: list[tuple[np.ndarray, np.ndarray]] = []
        for seq in sorted(seqs):
            coords, spectra = self.load_chunk(seq)
            all_coords.append(coords)
            all_spectra.extend(spectra)
        coords = (np.concatenate(all_coords) if all_coords
                  else np.empty((0, 2), np.int64))
        return SpectralDataset.from_arrays(coords, all_spectra)

    def sweep_debris(self, max_age_s: float = 1.0) -> int:
        """Reclaim torn ``.tmp`` leavings from a crashed appender.  Only
        tmps are swept, and only past the age gate: a concurrent append
        (another replica serving the same acquisition over the shared
        work dir) may be inside its write-then-rename window RIGHT NOW.
        Committed-named chunk files the manifest never published are left
        alone on purpose — deleting one would race an append that has
        renamed but not yet committed, and an idempotent re-post simply
        overwrites it; the governor reaps the whole directory once the
        acquisition finishes and ages out."""
        n = 0
        now = time.time()
        for p in self.dir.glob(".*.tmp"):
            try:
                if now - p.stat().st_mtime >= max_age_s:
                    p.unlink()
                    n += 1
            except FileNotFoundError:
                continue
        if n:
            record_recovery("stream.debris_sweep", n)
            logger.info("stream %s: swept %d torn append tmp(s)",
                        self.ds_id, n)
        return n


def stream_root(sm_config) -> Path:
    """Where every dataset's chunk log lives (governed work_dir space)."""
    return Path(sm_config.work_dir) / "stream"


class StreamIngest:
    """Service-side chunk intake: one ChunkLog per streamed dataset under
    the shared stream root, plus the ``sm_stream_*`` counters.  All state
    is on disk — any replica (or a takeover peer) sees the same logs."""

    def __init__(self, root: str | Path, metrics=None):
        self.root = Path(root)
        self.root.mkdir(parents=True, exist_ok=True)
        self._chunks = self._pixels = None
        if metrics is not None:
            self._chunks = metrics.counter(
                "sm_stream_chunks_total",
                "Stream chunks committed to the chunk log")
            self._pixels = metrics.counter(
                "sm_stream_pixels_total",
                "Stream pixels (spectra) committed to the chunk log")

    def log_for(self, ds_id: str) -> ChunkLog:
        return ChunkLog(self.root, ds_id)

    def append_chunk(self, ds_id: str, seq: int, coords, spectra,
                     fence=None) -> dict:
        log = self.log_for(ds_id)
        out = log.append(seq, coords, spectra, fence=fence)
        m = log.manifest()
        out.update(chunks=len(m["chunks"]),
                   pixels=sum(int(c["count"]) for c in m["chunks"].values()))
        if not out["duplicate"]:
            if self._chunks is not None:
                self._chunks.inc()
            if self._pixels is not None:
                self._pixels.inc(int(m["chunks"][str(int(seq))]["count"]))
        return out

    def finish(self, ds_id: str, fence=None) -> dict:
        return self.log_for(ds_id).finish(fence=fence)

    def status(self, ds_id: str) -> dict:
        m = self.log_for(ds_id).manifest()
        return {"ds_id": ds_id, "chunks": len(m["chunks"]),
                "pixels": sum(int(c["count"]) for c in m["chunks"].values()),
                "finished": bool(m.get("finished"))}

    def in_flight(self) -> int:
        """Acquisitions whose chunk log exists but is not yet finished —
        the fleet-status / timeseries signal for live instrument streams.
        Disk-derived like everything else here, so any replica answers the
        same; a torn manifest (mid-commit) counts as in flight."""
        n = 0
        try:
            entries = list(self.root.iterdir())
        except OSError:
            return 0
        for d in entries:
            if not d.is_dir():
                continue
            try:
                m = json.loads((d / "manifest.json").read_text())
            except (OSError, ValueError):
                m = {}
            if not m.get("finished"):
                n += 1
        return n


class StreamSearchJob(SearchJob):
    """The ``mode=stream`` attempt: wait on the chunk log, re-score the
    committed prefix provisionally as coverage grows, then run the batch
    pipeline verbatim once the acquisition is sealed.

    Liveness contract (the satellite fixes): every poll tick runs
    ``cancel.check`` — which is also the watchdog's progress touch, so a
    healthy acquisition waiting on the instrument is never reaped as
    stalled — and silence is bounded by ``service.stream.idle_timeout_s``
    (``StreamIdleError``, terminal) instead of the submit-pinned absolute
    deadline stream jobs are exempt from.
    """

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self.stream_cfg = self.sm_config.service.stream
        self.chunk_log = ChunkLog(stream_root(self.sm_config), self.ds_id)
        self.reranks = 0

    # the batch pass reads from the chunk log, not a staged imzML file —
    # everything else in SearchJob.run (ledger, device hold, search with
    # checkpoint resume, fence gates, storage) is inherited verbatim
    def _prepare_dataset(self, timings):
        from ..utils.logger import phase_timer

        with phase_timer("read_dataset", timings):
            ds = self.chunk_log.assemble_dataset()
        if self.cancel is not None:
            self.cancel.check("read_dataset")
        return ds

    def run(self, clean: bool = False):
        cfg = self.stream_cfg
        log = self.chunk_log
        log.sweep_debris()            # torn leftovers from a crashed appender
        formulas = None
        applied = 0                   # chunks covered by the last re-score
        last_n = 0                    # chunk count at the last observation
        last_new = time.time()
        logger.info("stream %s: acquisition open (%d chunk(s) committed, "
                    "idle timeout %.0fs)", self.ds_id,
                    len(log.committed_seqs()), cfg.idle_timeout_s)
        while True:
            if self.cancel is not None:
                # progress touch + cooperative gate: drain hand-off, user
                # cancel and fence loss all unwind from here
                self.cancel.check("stream_wait")
            m = log.manifest()
            n = len(m["chunks"])
            finished = bool(m.get("finished"))
            if finished:
                break
            # the idle clock resets ONLY on a genuinely new commit
            # (n > last_n), never on the mere existence of sub-threshold
            # pending chunks — otherwise rescore_min_chunks > 1 with a
            # dead client would refresh last_new forever and defeat the
            # liveness bound
            if n > last_n:
                last_n = n
                last_new = time.time()
            if n - applied >= cfg.rescore_min_chunks:
                if formulas is None:
                    formulas = self._load_formulas()
                self._provisional_rescore(m, formulas)
                applied = n
            elif cfg.idle_timeout_s > 0 and \
                    time.time() - last_new >= cfg.idle_timeout_s:
                raise StreamIdleError(
                    f"stream idle: no chunk committed for "
                    f"{cfg.idle_timeout_s:.0f}s ({n} chunk(s) committed, "
                    f"{applied} applied)")
            time.sleep(cfg.poll_interval_s)
        logger.info("stream %s: acquisition finished (%d chunks, %d px, "
                    "%d provisional re-rank(s)) — running batch convergence",
                    self.ds_id, len(log.committed_seqs()), log.n_pixels(),
                    self.reranks)
        return super().run(clean=clean)

    def _provisional_rescore(self, manifest: dict, formulas: list[str]) -> None:
        """Score the committed prefix end to end and publish the ranking
        through the ``partial`` seam.  Provisional work is stateless: no
        checkpoint dir, nothing stored — a failure here (device fault,
        mesh shrink mid-acquisition) degrades to a stale preview and the
        next commit retries, while cancel/fence errors still propagate so
        the scheduler's routing sees them."""
        from ..models.msm_basic import MSMBasicSearch
        from ..utils.cancel import JobCancelledError

        seqs = sorted(int(s) for s in manifest["chunks"])
        newest = max(float(c["committed_at"])
                     for c in manifest["chunks"].values())
        try:
            ds = self.chunk_log.assemble_dataset(seqs)
            token = hold_cancellable(self.device_token, self.cancel,
                                     phase="stream_rescore")
            with tracing.span("stream_rescore"), token:
                search = MSMBasicSearch(
                    ds, formulas, self.ds_config, self.sm_config,
                    isocalc_cache_dir=str(
                        Path(self.sm_config.work_dir) / "isocalc_cache"),
                    checkpoint_dir=None,
                    backend_cache=self.residency,
                    cancel=self.cancel,
                    device_indices=getattr(self.device_token, "devices",
                                           None),
                )
                bundle = search.search()
        except JobCancelledError:
            raise
        except Exception:
            logger.warning("stream %s: provisional re-score over %d "
                           "chunk(s) failed; preview stays stale",
                           self.ds_id, len(seqs), exc_info=True)
            return
        self.reranks += 1
        ann = bundle.annotations
        top = ann.sort_values("msm", ascending=False).head(5)
        payload = {
            "provisional": True,
            "n_scored": int(len(bundle.all_metrics)),
            "n_ions": int(len(bundle.all_metrics)),
            "annotations": int(len(ann)),
            "fdr_10pct": int((ann["fdr"] <= 0.1).sum()) if len(ann) else 0,
            "top": [
                {"sf": str(r.sf), "adduct": str(r.adduct),
                 "msm": round(float(r.msm), 6),
                 "fdr": round(float(r.fdr), 6)}
                for r in top.itertuples()
            ],
            # coverage + freshness block the service's SLO/metric seams
            # key off (scheduler._set_partial)
            "stream": {
                "chunks": len(seqs),
                "pixels": int(ds.n_spectra),
                "rerank": int(self.reranks),
                "commit_to_partial_s": max(0.0, time.time() - newest),
            },
        }
        tracing.event("stream_rerank",
                      **{k: v for k, v in payload.items() if k != "top"})
        self._note_partial(payload)
