"""Work-dir staging with existence-check resume.

Reference: ``sm/engine/work_dir.py::WorkDirManager`` [U] (SURVEY.md #3) stages
input data on local FS or S3 and skips finished stages when their outputs
already exist (the reference's poor-man's resume, SURVEY.md §5.4).  Here:
local staging only (no S3 in scope offline), same skip-if-present semantics,
plus a manifest recording the input fingerprint so a changed input busts the
stale staging.
"""

from __future__ import annotations

import json
import shutil
from pathlib import Path

from ..utils.logger import logger


class WorkDirManager:
    """Per-dataset scratch dir: ``<work_root>/<ds_id>/``."""

    def __init__(self, work_root: str | Path, ds_id: str):
        self.path = Path(work_root) / ds_id
        self.path.mkdir(parents=True, exist_ok=True)

    def _fingerprint(self, src: Path) -> dict:
        if src.is_file():
            return {src.name: [src.stat().st_size, int(src.stat().st_mtime)]}
        files = sorted(p for p in src.rglob("*") if p.is_file())
        return {
            str(p.relative_to(src)): [p.stat().st_size, int(p.stat().st_mtime)]
            for p in files
        }

    def copy_input_data(self, input_path: str | Path) -> Path:
        """Stage input (an imzML file or a directory holding the imzML/ibd
        pair) into the work dir; skip if already staged and unchanged."""
        src = Path(input_path)
        if not src.exists():
            raise FileNotFoundError(f"input path does not exist: {src}")
        dst = self.path / "input"
        manifest = self.path / "input.manifest.json"
        fp = self._fingerprint(src)
        if dst.exists() and manifest.exists():
            try:
                if json.loads(manifest.read_text()) == fp:
                    logger.info("work_dir: input already staged at %s, skipping", dst)
                    return dst
            except json.JSONDecodeError:
                pass
        if dst.exists():
            shutil.rmtree(dst)
        dst.mkdir(parents=True)
        if src.is_file():
            shutil.copy2(src, dst / src.name)
            ibd = src.with_suffix(".ibd")
            if ibd.exists():
                shutil.copy2(ibd, dst / ibd.name)
        else:
            # preserve relative layout — basename flattening would silently
            # overwrite same-named files from different subdirs
            for p in src.rglob("*"):
                if p.is_file():
                    out = dst / p.relative_to(src)
                    out.parent.mkdir(parents=True, exist_ok=True)
                    shutil.copy2(p, out)
        manifest.write_text(json.dumps(fp))
        logger.info("work_dir: staged %s -> %s", src, dst)
        return dst

    def imzml_path(self) -> Path:
        root = self.path / "input"
        hits = sorted(root.rglob("*.imzML")) or sorted(root.rglob("*.imzml"))
        if not hits:
            raise FileNotFoundError(f"no .imzML file staged under {root}")
        return hits[0]

    def exists(self, name: str) -> bool:
        return (self.path / name).exists()

    def file(self, name: str) -> Path:
        return self.path / name

    def clean(self) -> None:
        """Remove the whole per-dataset scratch dir (reference: WorkDir.clean [U])."""
        if self.path.exists():
            shutil.rmtree(self.path)
            logger.info("work_dir: cleaned %s", self.path)
