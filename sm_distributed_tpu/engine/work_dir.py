"""Work-dir staging with pluggable fetchers and partial-fetch resume.

Reference: ``sm/engine/work_dir.py::WorkDirManager`` [U] (SURVEY.md #3) stages
input data on local FS or S3 (boto) and skips finished stages when their
outputs already exist (the reference's poor-man's resume, SURVEY.md §5.4).

Here staging goes through a ``Fetcher`` seam (VERDICT r2 item 8):

- ``LocalFetcher`` — default, plain filesystem copies;
- ``S3Fetcher`` — ``s3://bucket/key`` URIs via boto3 when available (this
  build environment is offline, so it fails with guidance rather than
  pretending);
- any object with the two-method interface — tests inject a fake remote.

Resume is PER FILE, not all-or-nothing: each file lands under a temp name
and is renamed into place, files whose size+version already match the
remote listing are skipped, and the manifest is written only after every
file is staged — so a staging interrupted mid-transfer refetches only what
is missing or stale.
"""

from __future__ import annotations

import json
import shutil
from pathlib import Path

from ..utils.failpoints import failpoint, record_recovery, register_failpoint
from ..utils.logger import logger

FP_FETCH = register_failpoint(
    "workdir.fetch", "before fetching one staging file (remote I/O error)")
FP_STAGE_RENAME = register_failpoint(
    "workdir.stage_rename",
    "between a file's .part fetch and its rename into input/ (torn fetch)")


def sibling_ibd_names(filename: str) -> tuple[str, ...]:
    """Candidate .ibd sibling names for an imzML file (either extension
    case style), else empty — the ONE pairing rule both fetchers share, so
    local and S3 staging can't silently disagree on which inputs bring
    their binary sibling along."""
    if not filename.lower().endswith(".imzml"):
        return ()
    base = filename[: filename.rfind(".")]
    return (base + ".ibd", base + ".IBD")


class LocalFetcher:
    """Filesystem staging: ``src`` is a file (imzML; the sibling .ibd comes
    along) or a directory staged recursively with relative layout preserved
    (basename flattening would silently overwrite same-named files)."""

    def list_files(self, src: str | Path) -> dict[str, list]:
        """{relpath: [size, version]} — the staging manifest entries."""
        src = Path(src)
        if not src.exists():
            raise FileNotFoundError(f"input path does not exist: {src}")
        if src.is_file():
            out = {src.name: self._sig(src)}
            for name in sibling_ibd_names(src.name):
                ibd = src.with_name(name)
                if ibd.exists():
                    out[ibd.name] = self._sig(ibd)
                    break
            return out
        return {
            str(p.relative_to(src)): self._sig(p)
            for p in sorted(src.rglob("*")) if p.is_file()
        }

    @staticmethod
    def _sig(p: Path) -> list:
        st = p.stat()
        return [st.st_size, str(int(st.st_mtime))]

    def fetch_file(self, src: str | Path, rel: str, dst: Path) -> None:
        src = Path(src)
        # file source: rel is the file itself or its sibling .ibd
        origin = src.with_name(rel) if src.is_file() else src / rel
        shutil.copy2(origin, dst)


class S3Fetcher:
    """``s3://bucket/prefix`` staging via boto3 (the reference stages from
    S3 with boto — ``WorkDir.s3_path/copy_input_data`` [U]).  boto3 is not
    installed in the offline build image; constructing this fetcher without
    it fails with guidance instead of at first use."""

    def __init__(self, client=None):
        """``client``: an injected S3 client (tests exercise the listing and
        sibling logic with a fake); default constructs a real boto3 client."""
        if client is None:
            try:
                import boto3
            except ImportError as e:
                raise ImportError(
                    "s3:// staging needs boto3, which is not available in "
                    "this environment; stage the input locally (any "
                    "filesystem path) or install boto3") from e
            client = boto3.client("s3")
        self._s3 = client
        self._keys: dict[str, str] = {}   # rel -> exact object key (per src)

    @staticmethod
    def _split(uri: str) -> tuple[str, str]:
        rest = uri[len("s3://"):]
        bucket, _, prefix = rest.partition("/")
        return bucket, prefix

    def _head(self, bucket: str, key: str) -> tuple[list | None, bool]:
        """``([size, etag] | None, denied)`` — one HEAD request instead of
        paginating the whole prefix to detect an exact-key match (advisor
        r3: the scan iterated every object under a broad prefix, twice).
        404 = absent; 403 = HEAD denied (least-privilege policies return it
        both for missing s3:GetObject on an existing key and for a missing
        key without s3:ListBucket) — the caller falls through to the
        directory listing, and surfaces the denial if nothing else stages
        so a permissions problem doesn't masquerade as 'no objects'."""
        try:
            h = self._s3.head_object(Bucket=bucket, Key=key)
        except self._s3.exceptions.ClientError as e:
            meta = e.response.get("ResponseMetadata", {})
            code = meta.get("HTTPStatusCode")
            if code in (403, 404):
                return None, code == 403
            raise
        return [h["ContentLength"], h["ETag"].strip('"')], False

    def list_files(self, src: str) -> dict[str, list]:
        """An exact-key URI stages that one object (plus its .ibd sibling
        when it names an .imzML — the reader needs the pair, mirroring
        LocalFetcher); otherwise the prefix is treated as a directory and
        listed '/'-terminated, so a sibling prefix (ds1 vs ds10) can never
        leak into the listing.  Exact object keys are recorded for
        fetch_file — relpaths are never re-derived."""
        bucket, prefix = self._split(str(src))
        self._keys = {}
        out: dict[str, list] = {}
        exact, denied = ((None, False) if not prefix or prefix.endswith("/")
                         else self._head(bucket, prefix))
        if exact is not None:
            rel = Path(prefix).name
            self._keys[rel] = prefix
            out[rel] = exact
            key_dir = prefix[: -len(rel)]
            for name in sibling_ibd_names(rel):
                ibd, _ = self._head(bucket, key_dir + name)
                if ibd is not None:
                    self._keys[name] = key_dir + name
                    out[name] = ibd
                    break
            return out
        paginator = self._s3.get_paginator("list_objects_v2")
        dir_prefix = prefix.rstrip("/") + "/" if prefix else ""
        for page in paginator.paginate(Bucket=bucket, Prefix=dir_prefix):
            for obj in page.get("Contents", []):
                rel = obj["Key"][len(dir_prefix):]
                # skip console-created zero-byte "folder marker" keys — as
                # files they would shadow the directory and break mkdir
                if not rel or rel.endswith("/"):
                    continue
                self._keys[rel] = obj["Key"]
                out[rel] = [obj["Size"], obj["ETag"].strip('"')]
        if not out:
            if denied:
                raise PermissionError(
                    f"HEAD on {src} was denied (403) and no objects are "
                    "listable under it — check s3:GetObject/s3:ListBucket "
                    "permissions for this key")
            raise FileNotFoundError(f"no objects under {src}")
        return out

    def fetch_file(self, src: str, rel: str, dst: Path) -> None:
        bucket, _prefix = self._split(str(src))
        key = self._keys.get(rel)
        if key is None:
            raise KeyError(f"{rel} not in the current listing for {src}")
        self._s3.download_file(bucket, key, str(dst))


def resolve_fetcher(input_path: str | Path):
    """Pick a fetcher from the input URI scheme (plain paths -> local)."""
    s = str(input_path)
    if s.startswith("s3://"):
        return S3Fetcher()
    if "://" in s and not s.startswith("file://"):
        raise ValueError(f"unsupported input scheme: {s}")
    return LocalFetcher()


class WorkDirManager:
    """Per-dataset scratch dir: ``<work_root>/<ds_id>/``.

    ``fetcher``: staging backend override (tests inject a fake remote);
    default resolves from the input URI at copy_input_data time.
    """

    def __init__(self, work_root: str | Path, ds_id: str, fetcher=None):
        self.path = Path(work_root) / ds_id
        self.path.mkdir(parents=True, exist_ok=True)
        self.fetcher = fetcher

    def copy_input_data(self, input_path: str | Path) -> Path:
        """Stage input into ``<work_dir>/input``; per-file skip-if-current.

        A file is refetched only when absent or when its (size, version)
        no longer matches the source listing; extraneous local files are
        removed; the manifest commits the staging only once complete."""
        fetcher = self.fetcher or resolve_fetcher(input_path)
        s = str(input_path)
        if s.startswith("file://"):
            src: str | Path = Path(s[len("file://"):])   # plain local path
        elif "://" in s:
            src = s
        else:
            src = Path(s)
        listing = fetcher.list_files(src)
        dst = self.path / "input"
        manifest = self.path / "input.manifest.json"
        staged: dict = {}
        if manifest.exists():
            try:
                staged = json.loads(manifest.read_text())
            except json.JSONDecodeError:
                staged = {}
        # the manifest alone is not proof: a file deleted from dst since the
        # last staging must fall through to the per-file fetch loop
        if (staged == listing and dst.exists()
                and all((dst / rel).is_file() for rel in listing)):
            logger.info("work_dir: input already staged at %s, skipping", dst)
            return dst
        manifest.unlink(missing_ok=True)  # staging no longer current
        dst.mkdir(parents=True, exist_ok=True)
        # drop extraneous files from a previous (different) staging
        keep = {dst / rel for rel in listing}
        for p in sorted(dst.rglob("*"), reverse=True):
            if p.is_file() and p not in keep:
                p.unlink()
            elif p.is_dir() and not any(p.iterdir()):
                p.rmdir()
        fetched = 0
        for rel, sig in listing.items():
            out = dst / rel
            if out.exists() and staged.get(rel) == sig:
                record_recovery("workdir.resume_skip")
                continue                     # survived a partial staging
            out.parent.mkdir(parents=True, exist_ok=True)
            tmp = out.with_name(out.name + ".part")
            failpoint(FP_FETCH, path=tmp)
            fetcher.fetch_file(src, rel, tmp)
            failpoint(FP_STAGE_RENAME, path=tmp)
            # verify the byte count against the source listing BEFORE the
            # rename commits it: a torn/partial fetch must never be recorded
            # as current (the manifest would then skip it forever)
            got = tmp.stat().st_size
            if got != int(sig[0]):
                tmp.unlink(missing_ok=True)
                raise OSError(
                    f"staging fetched {got} bytes for {rel}, source lists "
                    f"{sig[0]} — torn or concurrent write, refusing to commit")
            tmp.replace(out)
            # commit per file: a crash mid-staging resumes from here
            staged[rel] = sig
            manifest.write_text(json.dumps(staged))
            fetched += 1
        manifest.write_text(json.dumps(listing))
        logger.info("work_dir: staged %s -> %s (%d fetched, %d current)",
                    src, dst, fetched, len(listing) - fetched)
        return dst

    def imzml_path(self) -> Path:
        root = self.path / "input"
        hits = sorted(root.rglob("*.imzML")) or sorted(root.rglob("*.imzml"))
        if not hits:
            raise FileNotFoundError(f"no .imzML file staged under {root}")
        return hits[0]

    def exists(self, name: str) -> bool:
        return (self.path / name).exists()

    def file(self, name: str) -> Path:
        return self.path / name

    def clean(self) -> None:
        """Remove the whole per-dataset scratch dir (reference: WorkDir.clean [U])."""
        if self.path.exists():
            shutil.rmtree(self.path)
            logger.info("work_dir: cleaned %s", self.path)
