"""CLI entry point — run a molecule search job.

Reference: ``scripts/run_molecule_search.py`` [U] (SURVEY.md #19, §3.1):
argparse over (ds name, input path, --config, --ds-config), constructs and
runs SearchJob.  Usage:

    python -m sm_distributed_tpu.engine.cli run DS_NAME INPUT.imzML \\
        [--ds-id ID] [--ds-config ds.json] [--sm-config sm.json] \\
        [--formulas-csv db.csv] [--profile DIR] [--clean]
    # without --formulas-csv, formulas come from the molecular DB named in
    # ds.json's "database" block (import it first with import-db)

    python -m sm_distributed_tpu.engine.cli import-db CSV NAME VERSION \\
        [--sm-config sm.json]

    python -m sm_distributed_tpu.engine.cli search [--ds-id ID] \\
        [--max-fdr 0.1] [--sm-config sm.json]

    python -m sm_distributed_tpu.engine.cli serve QUEUE_DIR \\
        [--sm-config sm.json] [--workers N] [--port P] [--no-api]
    # long-running annotation service: concurrent scheduler + retry/backoff
    # + /healthz /metrics /jobs /submit admin API (docs/SERVICE.md)
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

from ..utils.config import DSConfig, SMConfig
from ..utils.logger import init_logger, logger


def _load_configs(args) -> SMConfig:
    import os

    sm = SMConfig.set_path(args.sm_config) if args.sm_config else SMConfig.get_conf()
    init_logger(sm.logs_dir or None, json_logs=sm.logs.json)
    from ..utils import tracing

    tracing.configure(enabled=sm.tracing.enabled,
                      ring_size=sm.tracing.ring_size)
    if sm.failpoints and not os.environ.get("SM_FAILPOINTS"):
        # config-file activation (env always wins — it was applied at import)
        from ..utils import failpoints

        failpoints.configure(sm.failpoints)
        logger.warning("fault injection ACTIVE from config: %s", sm.failpoints)
    return sm


def cmd_run(args) -> int:
    sm_config = _load_configs(args)
    ds_config = DSConfig.load(args.ds_config) if args.ds_config else DSConfig()
    formulas = None
    if args.formulas_csv:
        from .moldb import MolecularDB
        from .storage import JobLedger

        db = MolecularDB(JobLedger(sm_config.storage.results_dir))
        db.import_csv(args.formulas_csv, name=Path(args.formulas_csv).stem, version="cli")
        formulas = db.formulas(Path(args.formulas_csv).stem, "cli")
    from ..utils import tracing
    from .search_job import SearchJob

    job = SearchJob(
        ds_id=args.ds_id or args.ds_name,
        ds_name=args.ds_name,
        input_path=args.input_path,
        ds_config=ds_config,
        sm_config=sm_config,
        formulas=formulas,
        profile_dir=args.profile,
    )
    # offline runs get the same end-to-end trace a /submit job gets — the
    # root is minted at CLI entry instead (ISSUE 5; docs/OBSERVABILITY.md)
    trace = (tracing.new_trace(job_id=job.ds_id,
                               trace_dir=sm_config.trace_dir)
             if sm_config.tracing.enabled else None)
    import time as _time

    t0 = _time.time()
    with tracing.attach(trace):
        try:
            bundle = job.run(clean=args.clean)
        finally:
            if trace is not None:
                tracing.emit_span(trace, "submit", ts=t0,
                                  dur=_time.time() - t0,
                                  span_id=trace.span_id, ds_id=job.ds_id,
                                  entry="cli")
                logger.info("trace written to %s (scripts/trace_report.py "
                            "renders it)", trace.file)
    n_pass = int((bundle.annotations.fdr_level <= 0.1).sum())
    logger.info(
        "done: %d target ions scored, %d at FDR<=10%%",
        len(bundle.annotations), n_pass,
    )
    return 0


def cmd_import_db(args) -> int:
    sm_config = _load_configs(args)
    from .moldb import MolecularDB
    from .storage import JobLedger

    db = MolecularDB(JobLedger(sm_config.storage.results_dir))
    n = db.import_csv(args.csv, args.name, args.version)
    logger.info("imported %d molecules into %s/%s", n, args.name, args.version)
    return 0


def cmd_search(args) -> int:
    sm_config = _load_configs(args)
    from .storage import AnnotationIndex, JobLedger

    index = AnnotationIndex(JobLedger(sm_config.storage.results_dir))
    df = index.search(ds_id=args.ds_id, sf=args.sf, max_fdr_level=args.max_fdr,
                      mz_min=args.mz_min, mz_max=args.mz_max)
    print(df.to_string(index=False) if not df.empty else "(no annotations)")
    return 0


def cmd_serve(args) -> int:
    """Run the annotation service: concurrent scheduler + admin API over a
    spool queue directory (sm_distributed_tpu.service)."""
    import dataclasses

    sm_config = _load_configs(args)
    overrides = {}
    if args.workers is not None:
        overrides["workers"] = args.workers
    if args.port is not None:
        overrides["http_port"] = args.port
    if args.host is not None:
        overrides["http_host"] = args.host
    if args.replica_id is not None:
        overrides["replica_id"] = args.replica_id
    if args.replicas is not None:
        overrides["replicas"] = args.replicas
    if args.shards is not None:
        overrides["spool_shards"] = args.shards
    if overrides:
        sm_config = dataclasses.replace(
            sm_config,
            service=dataclasses.replace(sm_config.service, **overrides))
        SMConfig.set(sm_config)
    from ..service import AnnotationService
    from .daemon import annotate_callback

    residency = None
    if sm_config.parallel.resident_datasets > 0:
        from .residency import DatasetResidency

        n = sm_config.parallel.resident_datasets
        residency = DatasetResidency(max_datasets=n, max_backends=n)
    service = AnnotationService(
        args.queue_dir,
        annotate_callback(sm_config, residency=residency),
        sm_config=sm_config,
        residency=residency,
        with_api=not args.no_api,
    )
    service.install_signal_handlers()
    service.start()
    if service.api is not None:
        host, port = service.api.address
        logger.info("serve: admin API on http://%s:%d "
                    "(/healthz /metrics /jobs POST /submit)", host, port)
    controller = None
    if args.fleet or sm_config.service.fleet.enabled:
        # elastic fleet (docs/SERVICE.md "Elasticity model"): THIS process
        # is replica r0 AND hosts the controller; additional replicas are
        # spawned `serve` subprocesses over the same spool, with their own
        # controllers disabled.  The controller's sm_fleet_* metrics land
        # on this replica's /metrics.
        from ..service.fleet import (
            FleetController,
            serve_spawn,
            service_signals,
            write_child_config,
        )

        child_conf = write_child_config(sm_config, sm_config.work_dir)
        controller = FleetController(
            args.queue_dir, sm_config.service.fleet, sm_config.service,
            spawn=serve_spawn(args.queue_dir, child_conf),
            signals=service_signals(service), metrics=service.metrics,
            self_replica_id=sm_config.service.replica_id)
        controller.start()
    try:
        return service.run_forever(max_terminal=args.max_jobs,
                                   idle_timeout_s=args.idle_timeout)
    finally:
        if controller is not None:
            controller.shutdown()


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(prog="sm-tpu")
    sub = ap.add_subparsers(dest="cmd", required=True)

    run = sub.add_parser("run", help="run an annotation job")
    run.add_argument("ds_name")
    run.add_argument("input_path")
    run.add_argument("--ds-id", default=None)
    run.add_argument("--ds-config", default=None)
    run.add_argument("--sm-config", default=None)
    run.add_argument("--formulas-csv", default=None,
                     help="molecules CSV; imported and used as the formula list")
    run.add_argument("--profile", default=None,
                     help="dump a jax.profiler trace to this dir")
    run.add_argument("--clean", action="store_true",
                     help="remove the work dir afterwards")
    run.set_defaults(fn=cmd_run)

    imp = sub.add_parser("import-db", help="import a molecular DB CSV")
    imp.add_argument("csv")
    imp.add_argument("name")
    imp.add_argument("version")
    imp.add_argument("--sm-config", default=None)
    imp.set_defaults(fn=cmd_import_db)

    srch = sub.add_parser("search", help="query indexed annotations")
    srch.add_argument("--ds-id", default=None)
    srch.add_argument("--sf", default=None)
    srch.add_argument("--max-fdr", type=float, default=None)
    srch.add_argument("--mz-min", type=float, default=None)
    srch.add_argument("--mz-max", type=float, default=None)
    srch.add_argument("--sm-config", default=None)
    srch.set_defaults(fn=cmd_search)

    srv = sub.add_parser(
        "serve", help="run the annotation service (scheduler + admin API)")
    srv.add_argument("queue_dir", help="spool queue directory")
    srv.add_argument("--sm-config", default=None)
    srv.add_argument("--workers", type=int, default=None,
                     help="override service.workers")
    srv.add_argument("--host", default=None, help="override service.http_host")
    srv.add_argument("--port", type=int, default=None,
                     help="override service.http_port (0 = ephemeral)")
    srv.add_argument("--replica-id", default=None,
                     help="this scheduler replica's identity (default r0); "
                          "run N processes with distinct ids over ONE spool "
                          "to scale out (docs/SERVICE.md 'Replication model')")
    srv.add_argument("--replicas", type=int, default=None,
                     help="expected replica count (informational; the live "
                          "set comes from registry heartbeats)")
    srv.add_argument("--shards", type=int, default=None,
                     help="override service.spool_shards (logical spool "
                          "partitions; must match across replicas)")
    srv.add_argument("--fleet", action="store_true",
                     help="run the elastic-fleet controller beside this "
                          "replica: spawn/drain serve subprocesses between "
                          "service.fleet.min_replicas and max_replicas on "
                          "SLO burn + queue depth (docs/SERVICE.md "
                          "'Elasticity model')")
    srv.add_argument("--no-api", action="store_true",
                     help="run the scheduler without the admin API")
    srv.add_argument("--max-jobs", type=int, default=None,
                     help="exit after N jobs reach a terminal state")
    srv.add_argument("--idle-timeout", type=float, default=None,
                     help="exit after the spool stays empty this many seconds")
    srv.set_defaults(fn=cmd_serve)
    return ap


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
