"""Queue daemon — file-queue job intake.

Reference: ``sm/engine/queue.py::QueueConsumer`` + ``scripts/sm_daemon.py``
[U] (SURVEY.md #16): RabbitMQ blocking consume on the ``sm_annotate`` queue;
each message ``{ds_id, input_path, ds_config}`` runs a SearchJob; success →
ack, failure → log + publish to a fail queue.

Offline TPU-native equivalent with the same contract: a spool DIRECTORY is
the queue.  ``QueuePublisher.publish`` drops ``<queue>/pending/<id>.json``;
the daemon claims a message by atomically renaming it into ``running/``
(rename is the ack/visibility mechanism — two daemons cannot claim the same
message), runs the job, then moves it to ``done/`` or ``failed/`` (the fail
queue).  Crash recovery: messages stuck in ``running/`` can be requeued with
``requeue_stale()``.
"""

from __future__ import annotations

import json
import os
import time
import uuid
from pathlib import Path

from ..utils.config import DSConfig, SMConfig
from ..utils.logger import logger

QUEUE_ANNOTATE = "sm_annotate"
_STATES = ("pending", "running", "done", "failed")


class QueuePublisher:
    """Drop job messages into the spool queue (reference: QueuePublisher [U])."""

    def __init__(self, queue_dir: str | Path, queue: str = QUEUE_ANNOTATE):
        self.root = Path(queue_dir) / queue
        for s in _STATES:
            (self.root / s).mkdir(parents=True, exist_ok=True)

    def publish(self, msg: dict) -> Path:
        if "ds_id" not in msg or "input_path" not in msg:
            raise ValueError("message needs at least ds_id and input_path")
        msg_id = msg.get("msg_id") or uuid.uuid4().hex
        msg = {**msg, "msg_id": msg_id, "published_at": time.time()}
        tmp = self.root / "pending" / f".{msg_id}.tmp"
        dst = self.root / "pending" / f"{msg_id}.json"
        tmp.write_text(json.dumps(msg, indent=2))
        os.replace(tmp, dst)          # atomic publish
        return dst


class QueueConsumer:
    """Consume the spool queue, one message at a time (blocking poll loop)."""

    def __init__(
        self,
        queue_dir: str | Path,
        callback,
        queue: str = QUEUE_ANNOTATE,
        on_success=None,
        on_failure=None,
        poll_interval: float = 1.0,
    ):
        self.root = Path(queue_dir) / queue
        for s in _STATES:
            (self.root / s).mkdir(parents=True, exist_ok=True)
        self.callback = callback
        self.on_success = on_success
        self.on_failure = on_failure
        self.poll_interval = poll_interval
        self._stop = False

    def stop(self) -> None:
        self._stop = True

    def _claim(self) -> Path | None:
        for p in sorted(self.root.glob("pending/*.json")):
            dst = self.root / "running" / p.name
            try:
                os.replace(p, dst)    # atomic claim
                return dst
            except FileNotFoundError:
                continue              # another consumer won the race
        return None

    def process_one(self) -> bool:
        """Claim + process a single message. Returns False if queue empty."""
        claimed = self._claim()
        if claimed is None:
            return False
        msg: dict = {}
        raw = ""
        try:
            raw = claimed.read_text()
            msg = json.loads(raw)
            logger.info("queue: processing %s (ds %s)", claimed.name, msg.get("ds_id"))
            self.callback(msg)
        except Exception as exc:
            # poison messages (bad JSON) land in failed/ too, instead of
            # crash-looping the consumer; keep the RAW payload as evidence
            # when parsing failed (ADVICE r1)
            failed = dict(msg) if msg else {"raw": raw}
            failed["error"] = str(exc)
            (self.root / "failed" / claimed.name).write_text(json.dumps(failed, indent=2))
            claimed.unlink()
            logger.error("queue: %s FAILED: %s", claimed.name, exc)
            if self.on_failure:
                self.on_failure(msg, exc)
        else:
            os.replace(claimed, self.root / "done" / claimed.name)
            logger.info("queue: %s done", claimed.name)
            if self.on_success:
                self.on_success(msg)
        return True

    def requeue_stale(self, max_age_s: float = 0.0) -> int:
        """Move crashed messages from running/ back to pending/."""
        n = 0
        now = time.time()
        for p in self.root.glob("running/*.json"):
            if now - p.stat().st_mtime >= max_age_s:
                os.replace(p, self.root / "pending" / p.name)
                n += 1
        return n

    def run(self, max_messages: int | None = None) -> None:
        """Blocking consume loop (the reference's pika blocking consume [U])."""
        n = 0
        while not self._stop:
            if self.process_one():
                n += 1
                if max_messages is not None and n >= max_messages:
                    return
            else:
                time.sleep(self.poll_interval)


def annotate_callback(sm_config: SMConfig, residency=None):
    """Build the daemon callback running a SearchJob per message
    (mirrors scripts/sm_daemon.py wiring [U]).

    A shared ``DatasetResidency`` keeps parsed datasets + compiled backends
    warm across messages (the reference daemon's long-lived SparkContext
    analog): a repeat job on the same dataset/shapes skips prepare and
    compile.  ``parallel.resident_datasets = 0`` disables."""
    if residency is None and sm_config.parallel.resident_datasets > 0:
        from .residency import DatasetResidency

        n = sm_config.parallel.resident_datasets
        residency = DatasetResidency(max_datasets=n, max_backends=n)

    def cb(msg: dict) -> None:
        from .search_job import SearchJob

        ds_config = (
            DSConfig.from_dict(msg["ds_config"]) if msg.get("ds_config") else DSConfig()
        )
        SearchJob(
            ds_id=msg["ds_id"],
            ds_name=msg.get("ds_name", msg["ds_id"]),
            input_path=msg["input_path"],
            ds_config=ds_config,
            sm_config=sm_config,
            formulas=msg.get("formulas"),
            residency=residency,
        ).run(clean=bool(msg.get("clean")))

    return cb


def main(argv: list[str] | None = None) -> int:
    import argparse

    ap = argparse.ArgumentParser(prog="sm-tpu-daemon")
    ap.add_argument("queue_dir")
    ap.add_argument("--sm-config", default=None)
    ap.add_argument("--max-messages", type=int, default=None)
    args = ap.parse_args(argv)
    sm_config = SMConfig.set_path(args.sm_config) if args.sm_config else SMConfig.get_conf()
    from ..utils.logger import init_logger

    init_logger(sm_config.logs_dir or None)
    consumer = QueueConsumer(args.queue_dir, annotate_callback(sm_config))
    consumer.requeue_stale()
    consumer.run(max_messages=args.max_messages)
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
