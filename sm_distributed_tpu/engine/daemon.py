"""Queue daemon — file-queue job intake.

Reference: ``sm/engine/queue.py::QueueConsumer`` + ``scripts/sm_daemon.py``
[U] (SURVEY.md #16): RabbitMQ blocking consume on the ``sm_annotate`` queue;
each message ``{ds_id, input_path, ds_config}`` runs a SearchJob; success →
ack, failure → log + publish to a fail queue.

Offline TPU-native equivalent with the same contract: a spool DIRECTORY is
the queue.  ``QueuePublisher.publish`` drops ``<queue>/pending/<id>.json``;
the daemon claims a message by atomically renaming it into ``running/``
(rename is the ack/visibility mechanism — two daemons cannot claim the same
message), runs the job, then moves it to ``done/`` or ``failed/`` (the fail
queue).  Crash recovery: messages stuck in ``running/`` can be requeued with
``requeue_stale()``, which is heartbeat-aware (see ``ClaimHeartbeat``) so a
slow-but-alive job is not confused with a crashed claim.

The production serving shape on top of this spool contract — concurrent
scheduler, retry/backoff/dead-letter, metrics, admin API — lives in
``sm_distributed_tpu.service`` (the ``serve`` CLI command, docs/SERVICE.md);
this module stays the minimal one-message-at-a-time consumer and the shared
spool primitives.
"""

from __future__ import annotations

import json
import os
import threading
import time
import uuid
from pathlib import Path

from ..utils.config import DSConfig, SMConfig
from ..utils.failpoints import failpoint, record_recovery, register_failpoint
from ..utils.logger import logger

QUEUE_ANNOTATE = "sm_annotate"
# quarantine/ holds messages the service scheduler parked after they crash-
# looped their claims (service/scheduler.py::_quarantine); the blocking
# consumer never writes it but creates it so both drain one spool layout
_STATES = ("pending", "running", "done", "failed", "quarantine")

FP_PUBLISH_RENAME = register_failpoint(
    "spool.publish_rename",
    "between a publish's tmp write and its os.replace into pending/")
FP_COMPLETE = register_failpoint(
    "spool.complete",
    "after a job succeeds, before its message moves running/ -> done/")
FP_HEARTBEAT = register_failpoint(
    "spool.heartbeat", "inside a claim's heartbeat touch (I/O error)")


def sweep_orphan_tmp(queue_root: Path, max_age_s: float = 300.0,
                     shards: "set[int] | None" = None,
                     total_shards: int = 0) -> int:
    """Remove orphaned publish/retry tmp files from ``pending/``.

    A crash between a tmp write and its ``os.replace`` (publisher's
    ``.{msg_id}.tmp``, scheduler retry's ``.{msg_id}.json.tmp``) leaks the
    hidden tmp forever — no ``*.json`` glob ever sees it.  Age-gated so a
    publish that is in flight RIGHT NOW is never swept; crash-recovery
    callers that know the writers are dead pass ``max_age_s=0``.

    Multi-replica scoping (ISSUE 8 satellite): with ``shards`` +
    ``total_shards`` set, only tmp files whose message id hashes into one
    of the given shards are touched — a takeover replica sweeps the dead
    peer's partitions without reaping a LIVE peer's in-flight retry tmp
    in a shard it doesn't own."""
    n = 0
    now = time.time()
    for p in (Path(queue_root) / "pending").glob(".*.tmp"):
        if shards is not None and total_shards > 1:
            # tmp names are ".{msg_id}.tmp" or ".{msg_id}.json.tmp"
            msg_id = p.name[1:]
            for suffix in (".json.tmp", ".tmp"):
                if msg_id.endswith(suffix):
                    msg_id = msg_id[: -len(suffix)]
                    break
            from ..service.leases import shard_of

            if shard_of(msg_id, total_shards) not in shards:
                continue
        try:
            if now - p.stat().st_mtime >= max_age_s:
                p.unlink()
                n += 1
        except FileNotFoundError:
            continue                  # a concurrent sweep/publish won
    if n:
        record_recovery("spool.orphan_tmp", n)
        logger.info("spool: swept %d orphaned pending tmp file(s)", n)
    return n


def heartbeat_path(msg_path: Path) -> Path:
    """Sidecar heartbeat file for a claimed message (``<id>.json.hb``).

    The ``*.json`` globs never match it, so it is invisible to claim/requeue
    scans except where explicitly consulted."""
    return msg_path.with_name(msg_path.name + ".hb")


def touch_heartbeat(msg_path: Path) -> None:
    hb = heartbeat_path(msg_path)
    failpoint(FP_HEARTBEAT, path=hb)
    hb.touch()
    # mtime-based liveness: touch() alone may not advance mtime on coarse
    # filesystems, so force it
    now = time.time()
    os.utime(hb, (now, now))


def clear_heartbeat(msg_path: Path) -> None:
    try:
        heartbeat_path(msg_path).unlink()
    except FileNotFoundError:
        pass


class ClaimHeartbeat(threading.Thread):
    """Background thread touching a claimed message's heartbeat file every
    ``interval_s`` while its job runs, so ``requeue_stale()`` can tell a slow
    job (live heartbeat) from a crashed claim (dead/absent heartbeat).

    Multi-replica mode (ISSUE 8): the scheduler hands every beat a fenced
    lease to renew too.  A renewal that discovers the lease LOST — a peer
    fenced this holder out after its beats went stale — fires ``on_lost``
    once, so the owning attempt can be cancelled early instead of running
    to completion only to have its commit rejected."""

    def __init__(self, msg_path: Path, interval_s: float = 5.0,
                 lease=None, lease_store=None, on_lost=None):
        super().__init__(daemon=True, name=f"hb-{msg_path.stem}")
        self.msg_path = Path(msg_path)
        self.interval_s = interval_s
        self.lease = lease
        self.lease_store = lease_store
        self.on_lost = on_lost
        self._lost_fired = False
        # NB: name must not collide with threading.Thread's internal _stop
        self._halt = threading.Event()

    def run(self) -> None:
        while not self._halt.is_set():
            try:
                touch_heartbeat(self.msg_path)
            except OSError:
                pass                  # message already moved to a terminal dir
            if self.lease is not None and self.lease_store is not None \
                    and not self._lost_fired:
                try:
                    alive = self.lease_store.renew(self.lease)
                except OSError:
                    alive = True      # renewal I/O fault: claim survives
                if not alive:
                    self._lost_fired = True
                    if self.on_lost is not None:
                        try:
                            self.on_lost()
                        except Exception:
                            logger.warning("claim heartbeat: on_lost failed",
                                           exc_info=True)
            self._halt.wait(self.interval_s)

    def stop(self) -> None:
        self._halt.set()
        self.join(timeout=2.0)
        clear_heartbeat(self.msg_path)


class QueuePublisher:
    """Drop job messages into the spool queue (reference: QueuePublisher [U])."""

    def __init__(self, queue_dir: str | Path, queue: str = QUEUE_ANNOTATE):
        self.root = Path(queue_dir) / queue
        for s in _STATES:
            (self.root / s).mkdir(parents=True, exist_ok=True)

    def publish(self, msg: dict) -> Path:
        if "ds_id" not in msg or "input_path" not in msg:
            raise ValueError("message needs at least ds_id and input_path")
        msg_id = msg.get("msg_id") or uuid.uuid4().hex
        msg = {**msg, "msg_id": msg_id, "published_at": time.time()}
        payload = json.dumps(msg, indent=2)
        # disk-budget preflight (ISSUE 10): a full disk refuses the publish
        # BEFORE the tmp write — no orphan tmp, structured error upstream
        from ..service import resources as _resources

        _resources.preflight("spool.publish", len(payload) + 1024)
        tmp = self.root / "pending" / f".{msg_id}.tmp"
        dst = self.root / "pending" / f"{msg_id}.json"
        tmp.write_text(payload)
        failpoint(FP_PUBLISH_RENAME, path=tmp)
        os.replace(tmp, dst)          # atomic publish
        return dst


class QueueConsumer:
    """Consume the spool queue, one message at a time (blocking poll loop)."""

    def __init__(
        self,
        queue_dir: str | Path,
        callback,
        queue: str = QUEUE_ANNOTATE,
        on_success=None,
        on_failure=None,
        poll_interval: float = 1.0,
    ):
        self.root = Path(queue_dir) / queue
        for s in _STATES:
            (self.root / s).mkdir(parents=True, exist_ok=True)
        self.callback = callback
        self.on_success = on_success
        self.on_failure = on_failure
        self.poll_interval = poll_interval
        self._stop = False

    def stop(self) -> None:
        self._stop = True

    def _claim(self) -> Path | None:
        for p in sorted(self.root.glob("pending/*.json")):
            dst = self.root / "running" / p.name
            try:
                os.replace(p, dst)    # atomic claim
                return dst
            except FileNotFoundError:
                continue              # another consumer won the race
        return None

    def process_one(self) -> bool:
        """Claim + process a single message. Returns False if queue empty."""
        claimed = self._claim()
        if claimed is None:
            return False
        msg: dict = {}
        raw = ""
        try:
            raw = claimed.read_text()
            msg = json.loads(raw)
            logger.info("queue: processing %s (ds %s)", claimed.name, msg.get("ds_id"))
            self.callback(msg)
        except Exception as exc:
            # poison messages (bad JSON) land in failed/ too, instead of
            # crash-looping the consumer; keep the RAW payload as evidence
            # when parsing failed (ADVICE r1)
            failed = dict(msg) if msg else {"raw": raw}
            failed["error"] = str(exc)
            (self.root / "failed" / claimed.name).write_text(json.dumps(failed, indent=2))
            claimed.unlink()
            logger.error("queue: %s FAILED: %s", claimed.name, exc)
            if self.on_failure:
                self.on_failure(msg, exc)
        else:
            failpoint(FP_COMPLETE, path=claimed)
            os.replace(claimed, self.root / "done" / claimed.name)
            logger.info("queue: %s done", claimed.name)
            if self.on_success:
                self.on_success(msg)
        return True

    def requeue_stale(self, max_age_s: float = 0.0) -> int:
        """Move crashed messages from running/ back to pending/.

        Heartbeat-aware: a claim's freshest sign of life is its heartbeat
        sidecar's mtime when one exists (the service scheduler touches it
        every ``heartbeat_interval_s``), else the message file's own mtime.
        A claim is requeued only when that is at least ``max_age_s`` old —
        so with ``max_age_s > heartbeat_interval_s`` a slow-but-alive job
        survives while a crashed claim (dead heartbeat) is recovered.  The
        default ``max_age_s=0`` keeps the original recover-everything
        behavior for cold daemon starts."""
        n = 0
        now = time.time()
        for p in self.root.glob("running/*.json"):
            hb = heartbeat_path(p)
            try:
                ref_mtime = hb.stat().st_mtime if hb.exists() else p.stat().st_mtime
            except FileNotFoundError:
                continue              # finished between glob and stat
            if now - ref_mtime >= max_age_s:
                os.replace(p, self.root / "pending" / p.name)
                clear_heartbeat(p)
                n += 1
        if n:
            record_recovery("spool.requeue_stale", n)
        return n

    def sweep_orphans(self, max_age_s: float = 300.0) -> int:
        """Startup sweep for orphaned publish tmp files (see
        ``sweep_orphan_tmp``)."""
        return sweep_orphan_tmp(self.root, max_age_s=max_age_s)

    def run(self, max_messages: int | None = None) -> None:
        """Blocking consume loop (the reference's pika blocking consume [U])."""
        n = 0
        while not self._stop:
            if self.process_one():
                n += 1
                if max_messages is not None and n >= max_messages:
                    return
            else:
                time.sleep(self.poll_interval)


def annotate_callback(sm_config: SMConfig, residency=None):
    """Build the daemon callback running a SearchJob per message
    (mirrors scripts/sm_daemon.py wiring [U]).

    A shared ``DatasetResidency`` keeps parsed datasets + compiled backends
    warm across messages (the reference daemon's long-lived SparkContext
    analog): a repeat job on the same dataset/shapes skips prepare and
    compile.  ``parallel.resident_datasets = 0`` disables."""
    if residency is None and sm_config.parallel.resident_datasets > 0:
        from .residency import DatasetResidency

        n = sm_config.parallel.resident_datasets
        residency = DatasetResidency(max_datasets=n, max_backends=n)

    def cb(msg: dict, ctx=None) -> None:
        from ..utils import tracing
        from .search_job import SearchJob

        ds_config = (
            DSConfig.from_dict(msg["ds_config"]) if msg.get("ds_config") else DSConfig()
        )
        # live-acquisition streaming (ISSUE 19, engine/stream.py): a
        # mode=stream message runs the long-lived stream attempt — same
        # constructor contract, input comes from the chunk log instead of
        # the message's input_path (a "stream://<ds_id>" sentinel)
        job_cls = SearchJob
        if msg.get("mode") == "stream":
            from .stream import StreamSearchJob

            job_cls = StreamSearchJob
        job = job_cls(
            ds_id=msg["ds_id"],
            ds_name=msg.get("ds_name", msg["ds_id"]),
            input_path=msg["input_path"],
            ds_config=ds_config,
            sm_config=sm_config,
            formulas=msg.get("formulas"),
            residency=residency,
            # service scheduler: serialize the device-bound phases across
            # worker threads while staging/parse overlap
            device_token=getattr(ctx, "device_token", None),
            # cooperative cancellation: the job checks this at phase and
            # checkpoint-group boundaries (utils/cancel.py)
            cancel=getattr(ctx, "cancel", None),
            # fenced-lease gate (service/leases.py): checked before the
            # result store and the ledger commit, so a replica fenced out
            # by a peer takeover never double-commits
            fence=getattr(ctx, "fence", None),
            # streamed first results (ISSUE 13): provisional annotations
            # from the first scored group surface on the job record's
            # ``partial`` field while later batches still run
            on_partial=getattr(ctx, "set_partial", None),
        )
        # the scheduler's attempt-span context (already ambient when the
        # scheduler ran this in an _Attempt thread; attached here too so the
        # plain blocking daemon's traced messages behave the same)
        with tracing.attach(getattr(ctx, "trace", None) or tracing.current()):
            job.run(clean=bool(msg.get("clean")))

    return cb


def main(argv: list[str] | None = None) -> int:
    import argparse

    ap = argparse.ArgumentParser(prog="sm-tpu-daemon")
    ap.add_argument("queue_dir")
    ap.add_argument("--sm-config", default=None)
    ap.add_argument("--max-messages", type=int, default=None)
    args = ap.parse_args(argv)
    sm_config = SMConfig.set_path(args.sm_config) if args.sm_config else SMConfig.get_conf()
    from ..utils.logger import init_logger

    init_logger(sm_config.logs_dir or None, json_logs=sm_config.logs.json)
    if sm_config.failpoints and not os.environ.get("SM_FAILPOINTS"):
        from ..utils import failpoints

        failpoints.configure(sm_config.failpoints)
        logger.warning("fault injection ACTIVE from config: %s",
                       sm_config.failpoints)
    consumer = QueueConsumer(args.queue_dir, annotate_callback(sm_config))
    consumer.requeue_stale()
    consumer.sweep_orphans()
    consumer.run(max_messages=args.max_messages)
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
