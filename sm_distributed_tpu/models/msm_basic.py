"""MSM basic search — the framework's "model": images -> metrics -> FDR.

Reference: ``sm/engine/msm_basic/msm_basic_search.py::MSMBasicSearch.search``
[U] (SURVEY.md #12, call stack §3.1): compute_sf_images -> sf_image_metrics ->
FDR.estimate_fdr.  Here the pipeline streams formula batches through a
backend's fused score function; the backend is selected by
``SMConfig.backend`` (numpy_ref | jax_tpu) per the north star.
"""

from __future__ import annotations

import hashlib
import os
import zlib
from dataclasses import dataclass, field
from pathlib import Path

import numpy as np
import pandas as pd

from ..io.dataset import SpectralDataset
from ..ops import buckets as shape_buckets
from ..ops import metrics_np
from ..ops.fdr import FDR, DecoyAssignment
from ..ops.imager_np import SortedPeakView, extract_ion_images
from ..ops.isocalc import (
    ISOCALC_PATTERN_VERSION,
    IsocalcWrapper,
    IsotopePatternTable,
)
from ..utils import tracing
from ..utils.cancel import JobCancelledError
from ..utils.config import DSConfig, SMConfig
from ..utils.failpoints import failpoint, record_recovery, register_failpoint
from ..utils.logger import logger, phase_timer
from . import faults, oom
from .breaker import get_device_breaker, record_degraded
from .faults import FP_CHIP_FAULT

FP_SHARD_WRITE = register_failpoint(
    "ckpt.shard_write",
    "between a checkpoint shard's tmp savez and its os.replace (torn/crash)")
FP_SHARD_LOAD = register_failpoint(
    "ckpt.shard_load", "per-shard checkpoint read (I/O error on resume)")
FP_DEVICE_SCORE = register_failpoint(
    "device.score_batch",
    "before scoring a batch group (TPU preemption / XLA failure mid-search)")
FP_DEVICE_ERROR = register_failpoint(
    "backend.device_error",
    "inside a device score_batches call — the consecutive-error seam the "
    "circuit breaker counts (open -> degrade to numpy -> half-open probe); "
    "raise:MemoryError injects an HBM RESOURCE_EXHAUSTED, which is a "
    "SIZING signal: batch backoff, no breaker trip (models/oom.py)")


# Checkpoint partition format version, hashed into the search fingerprint:
# bump whenever the group-partition RULE changes (a resume under a
# different partition would leave unscored zero rows).  v2 = the leading
# group is split to a single batch so the first FDR-rankable annotations
# land while later batches still run (ISSUE 13 streamed first results).
_PARTITION_VERSION = 2


# First-annotation observers (ISSUE 6): called once per search when the
# first checkpoint group's metrics land — the earliest moment FDR-rankable
# results exist.  Same producer-side pattern as logger phase observers /
# isocalc attach_metrics: the service's SLOTracker subscribes without this
# module importing the service layer.
_first_annotation_observers: list = []


def add_first_annotation_observer(fn) -> None:
    if fn not in _first_annotation_observers:
        _first_annotation_observers.append(fn)


def remove_first_annotation_observer(fn) -> None:
    if fn in _first_annotation_observers:
        _first_annotation_observers.remove(fn)


def _notify_first_annotation() -> None:
    """Exception-safe dispatch (observability never fails the pipeline)."""
    for fn in list(_first_annotation_observers):
        try:
            fn()
        except Exception:
            logger.warning("first-annotation observer %r failed", fn,
                           exc_info=True)


def _slice_table(table: IsotopePatternTable, s: int, e: int) -> IsotopePatternTable:
    return IsotopePatternTable(
        sfs=table.sfs[s:e],
        adducts=table.adducts[s:e],
        mzs=table.mzs[s:e],
        ints=table.ints[s:e],
        n_valid=table.n_valid[s:e],
        targets=table.targets[s:e],
    )


def maybe_order_table(table: IsotopePatternTable, order_ions: str,
                      formula_batch: int) -> IsotopePatternTable:
    """Apply parallel.order_ions: "mz" always orders, "table" never, "auto"
    orders when the stream has >=6 batches — the measured crossover: m/z
    locality won +20% at 6 batches (65k px) and 8.3x at 41 batches
    (262k px), but lost 17% at 3 batches where there is no locality to win
    and ordering spreads the blob-heavy target images' chaos cost across
    every batch (docs/PERF.md ledger)."""
    if order_ions == "mz":
        return order_table_by_mz(table)
    if order_ions == "table":
        return table
    n_batches = -(-table.n_ions // max(1, formula_batch))
    return order_table_by_mz(table) if n_batches >= 6 else table


def order_table_by_mz(table: IsotopePatternTable) -> IsotopePatternTable:
    """Reorder ions by principal-peak m/z (stable), targets and decoys
    interleaved.  Per-ion metrics are identical in any order (the window-
    bound histogram is exact per ion); what changes is BATCH COMPOSITION:
    a formula_batch slice of an m/z-sorted table has an m/z-LOCALIZED
    window union, so per-batch peak compaction (ops/imager_jax.py) keeps
    only that narrow band's peaks for every batch — total histogram-
    scatter work across a T-batch stream drops from ~T x N_resident
    (every batch touching most resident peaks) toward ~N_resident (each
    peak scattered where its band is scored).  The effect grows with
    batch count, i.e. exactly in the BASELINE #5 regime where the HBM
    guard forces small batches (VERDICT r3 item 3)."""
    order = np.argsort(table.mzs[:, 0], kind="stable")
    return IsotopePatternTable(
        sfs=[table.sfs[i] for i in order],
        adducts=[table.adducts[i] for i in order],
        mzs=table.mzs[order],
        ints=table.ints[order],
        n_valid=table.n_valid[order],
        targets=table.targets[order],
    )


class NumpyBackend:
    """The reference-semantics CPU backend (stand-in for the Spark-RDD
    executor; also the parity oracle for jax_tpu)."""

    name = "numpy_ref"

    def __init__(self, ds: SpectralDataset, ds_config: DSConfig):
        self.ds = ds
        self.ds_config = ds_config
        # sort once, reuse per batch; ppm selects the shared integer
        # intensity grid (exact cross-backend image parity)
        self._view = SortedPeakView.prepare(ds, ds_config.image_generation.ppm)

    def score_batches(self, tables, cancel=None) -> list[np.ndarray]:
        """Score an iterable of batches one at a time (no pipelining on CPU;
        accepts a lazy generator so only one slice is live at once).
        ``cancel`` is checked between batches — the host path's finest
        cooperative-cancellation grain."""
        out = []
        for t in tables:
            if cancel is not None:
                cancel.check("score_batch")
            out.append(self.score_batch(t))
        return out

    def score_batch(self, table: IsotopePatternTable) -> np.ndarray:
        """(n_ions, 4) array of (chaos, spatial, spectral, msm)."""
        with tracing.span("score_batch", backend=self.name,
                          ions=int(table.n_ions)):
            return self._score_batch(table)

    def _score_batch(self, table: IsotopePatternTable) -> np.ndarray:
        img_cfg = self.ds_config.image_generation
        images = extract_ion_images(self._view, table, img_cfg.ppm)
        out = np.zeros((table.n_ions, 4))
        for i in range(table.n_ions):
            out[i] = metrics_np.ion_metrics(
                images[i],
                table.ints[i],
                int(table.n_valid[i]),
                self.ds.nrows,
                self.ds.ncols,
                nlevels=img_cfg.nlevels,
                do_preprocessing=img_cfg.do_preprocessing,
                q=img_cfg.q,
            )
        return out


def make_backend(name: str, ds: SpectralDataset, ds_config: DSConfig,
                 sm_config: SMConfig, table: IsotopePatternTable | None = None,
                 device_indices=None):
    """``table``: the search's full ion table, when known up front — the jax
    backends drop dataset peaks outside the union of its windows (exact;
    the reference's "only hits shuffle" property).

    ``device_indices`` (ISSUE 7): the job's device-pool lease chips — 1
    chip pins the single-device fused graph to it, N chips score through
    the pjit-sharded sub-mesh; None = config-mesh over all devices."""
    if name == "numpy_ref":
        return NumpyBackend(ds, ds_config)
    if name == "jax_tpu":
        from ..parallel.sharded import make_jax_backend  # deferred: jax import is heavy

        return make_jax_backend(ds, ds_config, sm_config, restrict_table=table,
                                device_indices=device_indices)
    raise ValueError(f"unknown backend {name!r}")


def make_isocalc(ds_config: DSConfig, sm_config: SMConfig,
                 cache_dir: str | None) -> IsocalcWrapper:
    """IsocalcWrapper wired to the engine's parallel.* isocalc knobs."""
    par = sm_config.parallel
    return IsocalcWrapper(
        ds_config.isotope_generation,
        cache_dir=cache_dir,
        n_procs=par.isocalc_workers or None,
        # "on" forces the device stage; "off" leaves the decision to the
        # SM_ISOCALC_DEVICE env (None), so ad-hoc probes can opt in without
        # a config edit
        device_blur=True if par.isocalc_device == "on" else None,
        chunk_size=par.isocalc_chunk,
    )


class IsotopePrefetch:
    """Background decoy selection + isotope-pattern generation (ISSUE 3
    layer 3).  SearchJob starts this BEFORE staging/parsing the input, so
    the dominant cold-path cost — pattern generation — overlaps the input
    pipeline instead of following it.  Everything here depends only on the
    formula list and configs, never on the dataset.

    ``result()`` joins the setup thread (decoy sampling + cache-shard load +
    stream start — the generation itself keeps running inside the returned
    ``PatternStream``) and re-raises any setup failure.  ``cancel()`` tears
    the stream down when the job dies before consuming it.
    """

    def __init__(self, formulas: list[str], ds_config: DSConfig,
                 sm_config: SMConfig, cache_dir: str | None):
        import threading

        self.formulas = list(dict.fromkeys(formulas))
        self.ds_config = ds_config
        self.sm_config = sm_config
        self.cache_dir = cache_dir
        self.timings: dict[str, float] = {}
        self.fdr: FDR | None = None
        self.assignment: DecoyAssignment | None = None
        self.isocalc: IsocalcWrapper | None = None
        self.stream = None
        self._error: BaseException | None = None
        # thread hop: capture the caller's (SearchJob attempt) trace context
        # so prefetch setup + the generation stream trace into the job
        self._trace = tracing.current()
        self._thread = threading.Thread(
            target=self._run, name="isotope-prefetch", daemon=True)
        self._thread.start()

    def _run(self) -> None:
        import time

        try:
            with tracing.attach(self._trace), \
                    tracing.span("isotope_prefetch_setup"):
                self._setup()
        except BaseException as exc:  # noqa: BLE001 — result() re-raises
            self._error = exc

    def _setup(self) -> None:
        import time

        iso_cfg = self.ds_config.isotope_generation
        fdr_cfg = self.sm_config.fdr
        self.fdr = FDR(
            decoy_sample_size=fdr_cfg.decoy_sample_size,
            target_adducts=iso_cfg.adducts,
            seed=fdr_cfg.seed,
        )
        t0 = time.perf_counter()
        self.assignment = self.fdr.decoy_adduct_selection(self.formulas)
        self.pairs, self.flags = self.assignment.all_ion_tuples(
            self.formulas, iso_cfg.adducts)
        self.timings["decoy_selection"] = time.perf_counter() - t0
        # wrapper construction loads the cache shards (warm: seconds at
        # 1.68M ions) — deliberately inside this thread too
        self.isocalc = make_isocalc(
            self.ds_config, self.sm_config, self.cache_dir)
        self.stream = self.isocalc.stream_table(self.pairs, self.flags)

    def result(self):
        """(fdr, assignment, stream) — blocks on setup only."""
        self._thread.join()
        if self._error is not None:
            raise self._error
        return self.fdr, self.assignment, self.stream

    def cancel(self) -> None:
        self._thread.join()
        if self.stream is not None:
            self.stream.cancel()


class SearchCheckpoint:
    """Mid-search checkpoint of scored metrics (SURVEY §5.4: the reference has
    only coarse resume — theor_peaks cache + work-dir skips [U]; at BASELINE
    config #3/#5 scale a multi-hour search needs a finer grain).

    Append-style: one small npz shard per completed batch group (only that
    group's metric rows), so total checkpoint I/O is linear in ions — a single
    monolithic file rewritten per group would be quadratic and stall the
    device pipeline at every group boundary.  Shards are keyed by a
    fingerprint of (ion table, batch partition, image config, dataset
    content); a resume trusts only the contiguous shard prefix g0..gk.
    Metrics are backend-independent (cross-backend parity is bit-exact), so a
    search may resume under a different backend than it started with.
    """

    def __init__(self, directory: str | Path, fingerprint: str,
                 process_id: int = 0):
        # per-process filenames: co-located processes (or a shared work_dir
        # mount) must not race on one tmp/ckpt inode
        self.dir = Path(directory)
        self.prefix = f"msm_search.p{process_id}"
        self.fingerprint = fingerprint
        self.dir.mkdir(parents=True, exist_ok=True)

    def _shard(self, gi: int) -> Path:
        return self.dir / f"{self.prefix}.g{gi:05d}.ckpt.npz"

    def load(self, metrics: np.ndarray, n_groups: int,
             row_ranges: list[tuple[int, int]]) -> int:
        """Restore ``metrics`` rows in place from the contiguous shard
        prefix; return # of completed batch groups (0 if absent/stale).

        A shard that is unreadable, truncated, shape-mismatched, or fails its
        CRC32 checksum is treated as MISSING — the prefix ends there and the
        groups recompute — never as fatal: a torn checkpoint write must
        degrade to extra work, not crash the resume path."""
        done = 0
        for gi in range(n_groups):
            path = self._shard(gi)
            if not path.exists():
                break
            try:
                failpoint(FP_SHARD_LOAD, path=path)
                with np.load(path, allow_pickle=False) as z:
                    if (str(z["fingerprint"]) != self.fingerprint
                            or int(z["n_groups"]) != n_groups):
                        break             # stale checkpoint — normal miss
                    s, e = row_ranges[gi]
                    rows = z["rows"]
                    if rows.shape != (e - s, metrics.shape[1]):
                        raise ValueError("shard row shape mismatch")
                    # np.load happily returns rows from a zip whose payload
                    # bytes were silently corrupted in place; the checksum
                    # catches what the container format does not
                    if int(z["checksum"]) != zlib.crc32(
                            np.ascontiguousarray(rows).tobytes()):
                        raise ValueError("shard checksum mismatch")
                    metrics[s:e] = rows
            except Exception as exc:
                # unreadable/corrupt shard: trust only the prefix before it
                record_recovery("ckpt.corrupt_shard")
                logger.warning(
                    "checkpoint shard %s rejected (%s); resuming from the "
                    "%d-group prefix before it", path.name, exc, done)
                break
            done = gi + 1
        return done

    def save(self, metrics: np.ndarray, gi: int, n_groups: int,
             row_ranges: list[tuple[int, int]]) -> None:
        s, e = row_ranges[gi]
        rows = np.ascontiguousarray(metrics[s:e])
        # disk-budget preflight (ISSUE 10, service/resources.py): a full
        # disk fails the shard BEFORE a torn write, with headroom reserved
        # for the seams below this one.  No-op outside the service.
        from ..service import resources as _resources

        _resources.preflight("ckpt.shard_write", rows.nbytes + 4096)
        tmp = self._shard(gi).with_suffix(".tmp.npz")  # same dir -> atomic
        np.savez(tmp, fingerprint=np.str_(self.fingerprint),
                 rows=rows, n_groups=n_groups,
                 checksum=zlib.crc32(rows.tobytes()))
        failpoint(FP_SHARD_WRITE, path=tmp)
        os.replace(tmp, self._shard(gi))

    def finalize(self) -> None:
        # shards AND any orphaned tmp from a kill between savez and replace
        for path in self.dir.glob(f"{self.prefix}.g*"):
            path.unlink(missing_ok=True)


@dataclass
class SearchResultsBundle:
    """Everything the orchestrator persists (reference: metrics df + sparse
    ion images handed to SearchResults.store [U])."""

    annotations: pd.DataFrame      # target ions with fdr/fdr_level
    all_metrics: pd.DataFrame      # every scored ion incl. decoys
    timings: dict[str, float] = field(default_factory=dict)


class MSMBasicSearch:
    """End-to-end search over a dataset + formula list (class name kept)."""

    def __init__(
        self,
        ds: SpectralDataset,
        formulas: list[str],
        ds_config: DSConfig,
        sm_config: SMConfig | None = None,
        isocalc_cache_dir: str | None = None,
        checkpoint_dir: str | None = None,
        backend_cache=None,
        prefetch: IsotopePrefetch | None = None,
        cancel=None,
        device_indices=None,
        partial_observer=None,
    ):
        self.ds = ds
        self.formulas = list(dict.fromkeys(formulas))  # dedup, keep order
        self.ds_config = ds_config
        self.sm_config = sm_config or SMConfig.get_conf()
        self.checkpoint_dir = checkpoint_dir
        # service mode (engine/residency.DatasetResidency): reuse a compiled
        # backend across jobs when the search fingerprint + backend-shaping
        # knobs all match — the second job skips device transfer AND compile
        self.backend_cache = backend_cache
        # orchestrator-started generation (SearchJob overlap): decoys +
        # isocalc already running — search() consumes its stream instead of
        # starting one
        self.prefetch = prefetch
        # cooperative cancellation (utils/cancel.CancelToken or None):
        # checked at checkpoint-group boundaries and inside the host
        # backend's per-batch loop
        self.cancel = cancel
        # the job's device-pool lease chips (ISSUE 7): forwarded into
        # make_backend so a 1-chip job pins to its chip and an N-chip job
        # scores through the pjit-sharded sub-mesh; None = all devices
        self.device_indices = (tuple(int(i) for i in device_indices)
                               if device_indices else None)
        self.isocalc = None if prefetch is not None else make_isocalc(
            ds_config, self.sm_config, isocalc_cache_dir)
        # populated by search(); the orchestrator reads these to persist ion
        # images / m/z values for annotated ions (engine/search_job.py) —
        # last_backend lets the jax path export DEVICE images instead of
        # re-extracting on CPU
        self.last_table: IsotopePatternTable | None = None
        self.last_backend = None
        self.last_checkpoint: SearchCheckpoint | None = None
        # streamed first results (ISSUE 13): called once per search with a
        # provisional-annotation payload when the first FDR-rankable group
        # lands (the service threads it to the job record's `partial`
        # field); None = no consumer
        self.partial_observer = partial_observer
        # effective scoring batch (ISSUE 10/13): the config formula_batch
        # snapped to the shape-bucket lattice (ops/buckets.effective_batch
        # — the jax backends pad with the same snap, so slicing and
        # padding can never disagree), capped by a previously LEARNED
        # proven-safe size for this (dataset shape, backend, lease) — set
        # in _score_and_rank before the fingerprint (the checkpoint
        # partition depends on it)
        self._batch_eff = shape_buckets.effective_batch(
            self.sm_config.parallel)
        # in-flight OOM backoff cap: once a group halves its way to a
        # fitting size, every LATER group of this search starts capped
        # there (the device backend's padding batch already shrank)
        self._oom_cap = 0

    def _fingerprint(self, table: IsotopePatternTable) -> str:
        """Identity of a search for checkpoint validity: the exact ion table
        (decoys included — they depend on the FDR seed), image-config knobs,
        the batch partition (groups_done counts groups under a specific
        (formula_batch, checkpoint_every) split — resuming under a different
        split would leave unscored zero rows), and dataset content (strided
        peak sample + exact intensity sum, so a restaged same-shape dataset
        invalidates the checkpoint)."""
        img = self.ds_config.image_generation
        par = self.sm_config.parallel
        h = hashlib.sha256()
        h.update(repr((self.ds.nrows, self.ds.ncols, int(self.ds.n_peaks),
                       img.ppm, img.nlevels, img.do_preprocessing, img.q,
                       # the EFFECTIVE batch (== the lattice-snapped
                       # formula_batch unless an OOM-learned safe size caps
                       # it): the checkpoint partition is keyed on what
                       # actually ran, under the current partition format
                       self._batch_eff, par.checkpoint_every,
                       _PARTITION_VERSION)).encode())
        stride = max(1, self.ds.mzs_flat.size // 65536)
        h.update(np.ascontiguousarray(self.ds.mzs_flat[::stride]).tobytes())
        h.update(np.ascontiguousarray(self.ds.ints_flat[::stride]).tobytes())
        h.update(np.float64(
            self.ds.ints_flat.sum(dtype=np.float64)).tobytes())
        h.update("\x00".join(table.sfs).encode())
        h.update("\x00".join(table.adducts).encode())
        h.update(np.ascontiguousarray(table.mzs).tobytes())
        return h.hexdigest()

    def _fingerprint_pairs(self, table: IsotopePatternTable) -> str:
        """Checkpoint fingerprint computable BEFORE patterns exist (the
        overlapped path scores leading groups while generation runs, so it
        cannot hash the pattern m/z block like ``_fingerprint``).  Instead
        of pattern bits it hashes what determines them: the exact ion list,
        the isotope-generation params, and ``ISOCALC_PATTERN_VERSION`` —
        which MUST be bumped when pattern math changes result bits, or a
        stale checkpoint would resume against different patterns."""
        img = self.ds_config.image_generation
        par = self.sm_config.parallel
        iso = self.ds_config.isotope_generation
        h = hashlib.sha256()
        h.update(repr((self.ds.nrows, self.ds.ncols, int(self.ds.n_peaks),
                       img.ppm, img.nlevels, img.do_preprocessing, img.q,
                       # the EFFECTIVE batch (== the lattice-snapped
                       # formula_batch unless an OOM-learned safe size caps
                       # it): the checkpoint partition is keyed on what
                       # actually ran, under the current partition format
                       self._batch_eff, par.checkpoint_every,
                       _PARTITION_VERSION)).encode())
        stride = max(1, self.ds.mzs_flat.size // 65536)
        h.update(np.ascontiguousarray(self.ds.mzs_flat[::stride]).tobytes())
        h.update(np.ascontiguousarray(self.ds.ints_flat[::stride]).tobytes())
        h.update(np.float64(
            self.ds.ints_flat.sum(dtype=np.float64)).tobytes())
        h.update("\x00".join(table.sfs).encode())
        h.update("\x00".join(table.adducts).encode())
        h.update(repr((iso.charge, iso.isocalc_sigma, iso.isocalc_pts_per_mz,
                       iso.n_peaks, ISOCALC_PATTERN_VERSION,
                       bool(self.isocalc.device_blur))).encode())
        return h.hexdigest()

    def _agree_resume_point(self, done: int) -> int:
        """Multi-host: every process must resume from the SAME batch group,
        else they issue different collective sequences and the SPMD program
        deadlocks.  Checkpoints are per-process local files, so agree on
        min(done) across processes (rows below min are valid everywhere)."""
        if self.sm_config.backend != "jax_tpu":
            return done
        import jax

        if jax.process_count() == 1:
            return done
        from jax.experimental import multihost_utils

        all_done = multihost_utils.process_allgather(np.int64(done))
        agreed = int(np.min(all_done))
        if agreed != done:
            logger.info(
                "checkpoint resume point lowered %d -> %d to agree with "
                "other processes", done, agreed)
        return agreed

    _ANN_COLUMNS = ["sf", "adduct", "msm", "fdr", "fdr_level",
                    "chaos", "spatial", "spectral"]
    _ALL_COLUMNS = ["sf", "adduct", "is_target", "chaos", "spatial",
                    "spectral", "msm"]

    def _reduced_slices(self, group: list[tuple[int, int]]) -> list[tuple[int, int]]:
        """Re-split a checkpoint group's batch slices at the degraded
        (breaker-open) batch size.  Group row ranges — and therefore the
        checkpoint partition — are untouched; only the host scoring grain
        shrinks."""
        cap = max(1, self.sm_config.service.breaker_degraded_batch)
        return [(a, min(a + cap, e))
                for s, e in group for a in range(s, e, cap)]

    def _oom_key(self) -> str:
        """Safe-batch registry key: what a batch's HBM footprint depends
        on (models/oom.py).  Keyed on the PIXEL BUCKET, not the raw count
        (ISSUE 13): every dataset size in a lattice bucket runs the same
        executables at the same scratch shapes, so a learned safe batch
        transfers across them."""
        return oom.shape_key(self.ds.n_pixels, self.sm_config.backend,
                             self.device_indices)

    @staticmethod
    def _capped_slices(slices: list[tuple[int, int]],
                       cap: int) -> list[tuple[int, int]]:
        """Re-split scoring slices at ``cap`` ions.  The checkpoint
        partition (group row ranges) is untouched — only the per-call
        scoring grain shrinks, exactly like ``_reduced_slices``.  Callers
        pass lattice-point caps (``_oom_backoff`` snaps them down), so a
        shrunk batch lands on a primer-enumerated executable instead of
        minting a one-off size."""
        return [(a, min(a + cap, e))
                for s, e in slices for a in range(s, e, cap)]

    def _oom_backoff(self, backend, slices: list[tuple[int, int]],
                     cap: int, exc: BaseException) -> int:
        """HBM OOM recovery (ISSUE 10): halve the scoring batch and tell
        the device backend to shrink its static padding size.  Returns the
        new cap, or 0 when the batch is already a single ion (nothing left
        to shrink — the OOM is then a real failure for the retry policy,
        but still NOT a breaker signal)."""
        cur = cap or max(e - s for s, e in slices)
        new = cur // 2
        if new >= 1 and shape_buckets.buckets_enabled(
                self.sm_config.parallel):
            # snap the shrunk cap DOWN to the lattice so the backoff lands
            # on a primer-enumerated executable (ISSUE 13)
            new = shape_buckets.batch_bucket_down(new)
        oom.record_oom_event("score_group", str(exc))
        if new < 1:
            logger.error(
                "device OOM at a single-ion batch — cannot back off "
                "further: %s", exc)
            return 0
        if hasattr(backend, "shrink_batch"):
            backend.shrink_batch(new)
        logger.warning(
            "device OOM while scoring — a SIZING signal, not a device "
            "fault (no breaker count): halving batch %d -> %d and "
            "retrying in place (%s)", cur, new, exc)
        tracing.event("oom_backoff", from_batch=cur, to_batch=new,
                      error=str(exc)[:300])
        return new

    def _score_group(self, backend, table, metrics: np.ndarray,
                     group: list[tuple[int, int]], breaker, use_device: bool,
                     degraded: bool):
        """Score one checkpoint group through the circuit breaker.  Device
        errors feed ``record_failure``; below threshold they fail the
        attempt (the retry may find a healthy device), at threshold the
        breaker OPENS and this group — and the rest of the job — degrades
        in place to the numpy oracle at reduced batch.  Metrics are
        backend-independent (bit-exact parity), so a mid-job switch is
        invisible in the results.

        Every non-cancel exception routes through the ONE fault taxonomy
        (models/faults.py, ISSUE 14).  HBM ``RESOURCE_EXHAUSTED`` is a
        *sizing* signal: the batch halves and the group rescores in place,
        the breaker never counts it, and the converged size is remembered
        so the next job on this shape starts there.  A *transient* fault
        (collective timeout, dying tunnel) fails the attempt into the
        retry policy — same chip, backoff, no breaker count, no
        quarantine.  A *sticky* fault reports the lease chips to the
        health tracker (quarantine / probe attribution) AND counts on the
        per-chip breaker.  Returns the (possibly swapped) backend and
        degraded flag."""
        on_device = use_device and not degraded
        slices = self._reduced_slices(group) if degraded else group
        if self._oom_cap:
            # an earlier group already backed off: the backend's padding
            # batch is shrunk, so later groups must arrive pre-capped
            slices = self._capped_slices(slices, self._oom_cap)
        oom_cap = 0
        while True:
            try:
                if on_device:
                    # injected consecutive-device-error seam (chaos sweep:
                    # breaker opens mid-job, degrades, converges to golden)
                    failpoint(FP_DEVICE_ERROR)
                    # classified chip-fault seam (ISSUE 14): the injected
                    # exception class selects the taxonomy — see faults.py
                    failpoint(FP_CHIP_FAULT)
                # lazy slices: every backend exposes score_batches; the jax
                # one pipelines (async-enqueues all batches in the group
                # before syncing any), the numpy one consumes one at a time
                outs = backend.score_batches(
                    (_slice_table(table, s, e) for s, e in slices),
                    cancel=self.cancel)
            except JobCancelledError:
                raise
            except Exception as exc:
                injected = ("backend.device_error" in str(exc)
                            or "backend.chip_fault" in str(exc))
                if not (on_device or injected):
                    raise             # a host-backend bug is not a device fault
                kind = faults.classify(exc)
                if kind == faults.FAULT_OOM:
                    new_cap = self._oom_backoff(backend, slices, oom_cap, exc)
                    if not new_cap:
                        raise         # single-ion batch still OOMs: let the
                                      # retry policy handle it — no breaker
                    oom_cap = new_cap
                    slices = self._capped_slices(slices, new_cap)
                    continue
                faults.report_device_fault(self.device_indices, kind, exc)
                if kind == faults.FAULT_TRANSIENT:
                    # known-recoverable runtime hiccup: the retry policy
                    # probes the SAME chip after backoff — no breaker
                    # count, no quarantine (the regression the old breaker
                    # test caused: a collective timeout opened it)
                    logger.warning(
                        "transient device fault while scoring — retrying "
                        "via the job retry policy (no breaker count): %s",
                        exc)
                    raise
                now_open = breaker.record_failure()
                logger.warning(
                    "sticky device fault while scoring (breaker %s after "
                    "it; lease chips reported for quarantine): %s",
                    breaker.state, exc)
                if not now_open:
                    raise             # below threshold: let the retry policy
                                      # probe the device again
                record_degraded()
                logger.warning(
                    "device breaker opened mid-job: degrading to the numpy "
                    "backend at batch %d",
                    self.sm_config.service.breaker_degraded_batch)
                backend = NumpyBackend(self.ds, self.ds_config)
                self.last_backend = backend
                degraded = True
                slices = self._reduced_slices(group)
                outs = backend.score_batches(
                    (_slice_table(table, s, e) for s, e in slices),
                    cancel=self.cancel)
                break
            else:
                if on_device:
                    # a cleanly scored device group closes a half-open probe
                    # and resets the consecutive-error count — and clears
                    # the lease chips' suspect state (ISSUE 14)
                    breaker.record_success()
                    faults.report_device_ok(self.device_indices)
                break
        if oom_cap:
            # the group converged at oom_cap: proven-safe — later groups
            # of THIS search stay capped, and later jobs on this
            # (dataset shape, backend, lease) start there
            self._oom_cap = oom_cap
            oom.record_safe_batch(self._oom_key(), oom_cap)
        for (s, e), out in zip(slices, outs):
            metrics[s:e] = out
        return backend, degraded

    def _emit_partial(self, fdr: FDR, assignment: DecoyAssignment,
                      table: IsotopePatternTable, metrics: np.ndarray,
                      n_scored: int, gi: int) -> None:
        """Provisional annotations over the scored prefix (ISSUE 13
        streamed first results): rank the first ``n_scored`` ions' msm
        through the REAL FDR estimator (the decoy set is the prefix's —
        provisional by construction, converging to the final ranking as
        groups land) and publish a small summary to the job trace and the
        ``partial_observer`` (the service threads it into the job record's
        ``partial`` field).  Best-effort: a failure here degrades to no
        preview, never a failed search."""
        if n_scored >= table.n_ions or n_scored <= 0:
            return                    # single group: final results imminent
        if self.partial_observer is None and not tracing.enabled():
            return
        try:
            sub = pd.DataFrame({
                "sf": table.sfs[:n_scored],
                "adduct": table.adducts[:n_scored],
                "msm": metrics[:n_scored, 3],
            })
            ann = fdr.estimate_fdr(sub, assignment)
            top = ann.sort_values("msm", ascending=False).head(5)
            payload = {
                "provisional": True,
                "group": int(gi),
                "n_scored": int(n_scored),
                "n_ions": int(table.n_ions),
                "annotations": int(len(ann)),
                "fdr_10pct": int((ann["fdr"] <= 0.1).sum()),
                "top": [
                    {"sf": str(r.sf), "adduct": str(r.adduct),
                     "msm": round(float(r.msm), 6),
                     "fdr": round(float(r.fdr), 6)}
                    for r in top.itertuples()
                ],
            }
        except Exception:
            logger.warning("provisional partial annotations failed",
                           exc_info=True)
            return
        tracing.event("partial_annotations",
                      **{k: v for k, v in payload.items() if k != "top"})
        obs = self.partial_observer
        if obs is not None:
            try:
                obs(payload)
            except Exception:
                logger.warning("partial-results observer %r failed", obs,
                               exc_info=True)

    def search(self) -> SearchResultsBundle:
        timings: dict[str, float] = {}
        if not self.formulas:
            return SearchResultsBundle(
                annotations=pd.DataFrame(columns=self._ANN_COLUMNS),
                all_metrics=pd.DataFrame(columns=self._ALL_COLUMNS),
            )
        iso_cfg = self.ds_config.isotope_generation
        if self.prefetch is not None:
            # SearchJob started decoys + generation before staging; by the
            # time search() runs, the stream has been computing all along
            fdr, assignment, stream = self.prefetch.result()
            self.isocalc = self.prefetch.isocalc
            timings.update(self.prefetch.timings)
        else:
            fdr = FDR(
                decoy_sample_size=self.sm_config.fdr.decoy_sample_size,
                target_adducts=iso_cfg.adducts,
                seed=self.sm_config.fdr.seed,
            )
            with phase_timer("decoy_selection", timings):
                assignment: DecoyAssignment = fdr.decoy_adduct_selection(
                    self.formulas)
                pairs, flags = assignment.all_ion_tuples(
                    self.formulas, iso_cfg.adducts)
            stream = self.isocalc.stream_table(pairs, flags)
        try:
            return self._score_and_rank(stream, fdr, assignment, timings)
        except BaseException:
            stream.cancel()
            raise

    def _score_and_rank(self, stream, fdr: FDR, assignment: DecoyAssignment,
                        timings: dict[str, float]) -> SearchResultsBundle:
        # Overlapped scoring (ISSUE 3 layer 3): with the host backend, the
        # leading checkpoint groups score as soon as their pattern rows are
        # published — generation and scoring run concurrently.  The device
        # backend consumes the WHOLE table up front (window-union peak
        # restriction + executable presizing), so it waits for the stream
        # instead; its overlap is at the SearchJob level (staging/parse).
        overlap = (self.sm_config.parallel.overlap_isocalc != "off"
                   and self.sm_config.backend == "numpy_ref")
        with phase_timer("isotope_patterns", timings):
            if overlap:
                table = stream.table_view()   # rows fill in as chunks land
            else:
                table = stream.result_table()
                # m/z-localized batch unions (see maybe_order_table):
                # per-ion results are order-independent, so this only
                # changes which extraction variant each batch's plan picks
                table = maybe_order_table(
                    table, self.sm_config.parallel.order_ions,
                    self.sm_config.parallel.formula_batch)
        self.last_table = table
        logger.info(
            "scoring %d ions (%d targets, %d decoys) with backend=%s%s",
            table.n_ions, int(table.targets.sum()),
            int((~table.targets).sum()), self.sm_config.backend,
            " (overlapping isocalc)" if overlap else "",
        )
        # OOM memory (ISSUE 10): a previous job on this (dataset shape,
        # backend, lease) proved a smaller batch fits in HBM — start there
        # instead of rediscovering the RESOURCE_EXHAUSTED.  Must happen
        # BEFORE the fingerprint: the checkpoint partition depends on it.
        safe = oom.safe_batch_for(self._oom_key())
        if safe and safe < self._batch_eff:
            logger.info(
                "oom: starting at learned safe batch %d (config %d) for %s",
                safe, self._batch_eff, self._oom_key())
            self._batch_eff = safe
        fingerprint = (self._fingerprint_pairs(table) if overlap
                       else self._fingerprint(table))

        def build():
            return make_backend(
                self.sm_config.backend, self.ds, self.ds_config,
                self.sm_config, table=table,
                device_indices=self.device_indices,
            )

        # device circuit breaker (models/breaker.py): an OPEN breaker means
        # the device backend recently produced N consecutive errors — skip
        # the build/compile entirely and score on the numpy oracle at
        # reduced batch (bit-identical results; degraded-but-correct beats
        # dead).  allow_device() admits one half-open probe after cooldown.
        use_device = self.sm_config.backend == "jax_tpu"
        # per-chip breaker view (ISSUE 14): a leased job answers to ITS
        # chips' breakers, so one bad chip's history never degrades jobs
        # holding healthy chips; un-leased runs keep the "*" singleton
        breaker = get_device_breaker(self.sm_config.service,
                                     devices=self.device_indices)
        degraded = False
        if use_device and not breaker.allow_device():
            logger.warning(
                "device breaker open: degrading job to the numpy backend "
                "at batch %d", self.sm_config.service.breaker_degraded_batch)
            record_degraded()
            backend = NumpyBackend(self.ds, self.ds_config)
            degraded = True
        elif self.backend_cache is not None:
            par = self.sm_config.parallel
            key = (self.sm_config.backend, fingerprint,
                   par.mz_chunk, par.pixels_axis, par.formulas_axis,
                   par.peak_compaction, par.band_slice, par.order_ions,
                   # a backend is pinned to its lease's chips — a cached one
                   # must never be reused by a job holding DIFFERENT chips
                   self.device_indices)
            backend = self.backend_cache.backend(key, build)
        else:
            backend = build()
        self.last_backend = backend
        batch = self._batch_eff
        if batch < max(1, self.sm_config.parallel.formula_batch) and \
                hasattr(backend, "shrink_batch"):
            # the learned safe size also caps the device backend's static
            # padding batch (padding to the config size would re-OOM)
            backend.shrink_batch(batch)
        metrics = np.zeros((table.n_ions, 4))
        with phase_timer("score", timings):
            slices = [(s, min(s + batch, table.n_ions))
                      for s in range(0, table.n_ions, batch)]
            ckpt_every = self.sm_config.parallel.checkpoint_every
            if self.checkpoint_dir and ckpt_every > 0:
                # group batches so pipelining still happens within a
                # group.  Streamed first results (ISSUE 13,
                # _PARTITION_VERSION 2): the LEADING group is a single
                # batch, so the first FDR-rankable metrics — and the
                # provisional `partial` annotations — land after one
                # batch's compute instead of a whole group's, while later
                # groups keep the full pipelining grain
                groups = [slices[: 1]] + [
                    slices[1:][i : i + ckpt_every]
                    for i in range(0, len(slices) - 1, ckpt_every)]
                if self.sm_config.backend == "jax_tpu":
                    import jax

                    pid = jax.process_index()
                else:
                    pid = 0
                ckpt = SearchCheckpoint(
                    self.checkpoint_dir, fingerprint, process_id=pid)
                row_ranges = [(g[0][0], g[-1][1]) for g in groups]
                done = self._agree_resume_point(
                    ckpt.load(metrics, len(groups), row_ranges))
                if done:
                    logger.info(
                        "resuming search from checkpoint: %d/%d batch groups "
                        "already scored", done, len(groups))
            elif overlap:
                # no checkpoint grain: publish/score per batch, so overlap
                # still engages (the host backend consumes batches one at a
                # time anyway)
                groups, ckpt, done = [[sl] for sl in slices], None, 0
                row_ranges = [sl for sl in slices]
            elif len(slices) > 1:
                # no checkpoint grain: still split the leading batch into
                # its own group so first-annotation latency is one batch,
                # not the whole stream (the tail stays one pipelined group)
                groups, ckpt, done = [slices[:1], slices[1:]], None, 0
                row_ranges = [(g[0][0], g[-1][1]) for g in groups]
            else:
                groups, ckpt, done = [slices], None, 0
                row_ranges = [(0, table.n_ions)] if slices else []
            if len(groups) > 1 and hasattr(backend, "presize"):
                # per-group score_batches calls would otherwise pre-size
                # static shapes per GROUP and recompile when a later group
                # needs a wider band (models/msm_jax.py::presize)
                backend.presize(
                    _slice_table(table, s, e) for s, e in slices)
            first_scored = False
            for gi, group in enumerate(groups):
                if gi < done:
                    continue
                if self.cancel is not None:
                    # THE cooperative cancellation boundary: a timed-out /
                    # deleted / past-deadline job unwinds here, after the
                    # last durable checkpoint and before any new work
                    self.cancel.check("score")
                if overlap:
                    # block until this group's pattern rows are published —
                    # in bounded slices so a cancel still lands while
                    # generation is the laggard
                    need = row_ranges[gi][1]
                    if self.cancel is None:
                        stream.wait_rows(need)
                    else:
                        while stream.wait_rows(need, timeout=0.2) < min(
                                need, stream.n_ions):
                            self.cancel.check("isotope_patterns_wait")
                # device-fault seam: a preempted TPU / failed XLA launch
                # surfaces here, after `done` groups are already durable
                failpoint(FP_DEVICE_SCORE)
                with tracing.span("score_group", group=gi,
                                  rows=list(row_ranges[gi]) if row_ranges
                                  else None, degraded=degraded):
                    backend, degraded = self._score_group(
                        backend, table, metrics, group, breaker, use_device,
                        degraded)
                if not first_scored:
                    # the first FDR-rankable metrics of this search exist
                    # now — the submit→first-annotation SLI's stop clock
                    first_scored = True
                    tracing.event("first_annotation", group=gi)
                    _notify_first_annotation()
                    # streamed first results (ISSUE 13): provisional FDR
                    # over the scored prefix, exposed on the job trace +
                    # the scheduler's `partial` field while later batches
                    # still run
                    self._emit_partial(
                        fdr, assignment, table, metrics,
                        row_ranges[gi][1] if row_ranges else table.n_ions,
                        gi)
                if ckpt is not None:
                    with tracing.span("checkpoint_save", group=gi):
                        ckpt.save(metrics, gi, len(groups), row_ranges)
            # NOT finalized here: downstream FDR/storage can still fail, and
            # the scored metrics must survive a rerun.  The orchestrator
            # (SearchJob) finalizes after results are durably persisted; a
            # leftover checkpoint is harmless (fingerprint-guarded) and makes
            # an identical re-search skip scoring entirely.
            self.last_checkpoint = ckpt
            if not first_scored:
                # fully resumed from checkpoint (or an empty table): the
                # first annotations were available immediately
                _notify_first_annotation()
            if overlap:
                # join generation (shard commits/compaction may trail the
                # last row) and surface any late stream error before FDR
                stream.result_table()
        timings["isocalc_gen"] = stream.gen_seconds
        if self.cancel is not None:
            self.cancel.check("fdr")
        with phase_timer("fdr", timings):
            all_df = pd.DataFrame(
                {
                    "sf": table.sfs,
                    "adduct": table.adducts,
                    "is_target": table.targets,
                    "chaos": metrics[:, 0],
                    "spatial": metrics[:, 1],
                    "spectral": metrics[:, 2],
                    "msm": metrics[:, 3],
                }
            )
            annotations = fdr.estimate_fdr(all_df[["sf", "adduct", "msm"]], assignment)
            annotations = annotations.merge(
                all_df[["sf", "adduct", "chaos", "spatial", "spectral"]],
                on=["sf", "adduct"],
                how="left",
            )
            # keep the declared schema authoritative for empty & non-empty paths
            annotations = annotations[self._ANN_COLUMNS]
            all_df = all_df[self._ALL_COLUMNS]
        return SearchResultsBundle(
            annotations=annotations, all_metrics=all_df, timings=timings
        )
