"""MSM basic search — the framework's "model": images -> metrics -> FDR.

Reference: ``sm/engine/msm_basic/msm_basic_search.py::MSMBasicSearch.search``
[U] (SURVEY.md #12, call stack §3.1): compute_sf_images -> sf_image_metrics ->
FDR.estimate_fdr.  Here the pipeline streams formula batches through a
backend's fused score function; the backend is selected by
``SMConfig.backend`` (numpy_ref | jax_tpu) per the north star.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np
import pandas as pd

from ..io.dataset import SpectralDataset
from ..ops import metrics_np
from ..ops.fdr import FDR, DecoyAssignment
from ..ops.imager_np import SortedPeakView, extract_ion_images
from ..ops.isocalc import IsocalcWrapper, IsotopePatternTable
from ..utils.config import DSConfig, SMConfig
from ..utils.logger import logger, phase_timer


def _slice_table(table: IsotopePatternTable, s: int, e: int) -> IsotopePatternTable:
    return IsotopePatternTable(
        sfs=table.sfs[s:e],
        adducts=table.adducts[s:e],
        mzs=table.mzs[s:e],
        ints=table.ints[s:e],
        n_valid=table.n_valid[s:e],
        targets=table.targets[s:e],
    )


class NumpyBackend:
    """The reference-semantics CPU backend (stand-in for the Spark-RDD
    executor; also the parity oracle for jax_tpu)."""

    name = "numpy_ref"

    def __init__(self, ds: SpectralDataset, ds_config: DSConfig):
        self.ds = ds
        self.ds_config = ds_config
        # sort once, reuse per batch; ppm selects the shared integer
        # intensity grid (exact cross-backend image parity)
        self._view = SortedPeakView.prepare(ds, ds_config.image_generation.ppm)

    def score_batches(self, tables) -> list[np.ndarray]:
        """Score an iterable of batches one at a time (no pipelining on CPU;
        accepts a lazy generator so only one slice is live at once)."""
        return [self.score_batch(t) for t in tables]

    def score_batch(self, table: IsotopePatternTable) -> np.ndarray:
        """(n_ions, 4) array of (chaos, spatial, spectral, msm)."""
        img_cfg = self.ds_config.image_generation
        images = extract_ion_images(self._view, table, img_cfg.ppm)
        out = np.zeros((table.n_ions, 4))
        for i in range(table.n_ions):
            out[i] = metrics_np.ion_metrics(
                images[i],
                table.ints[i],
                int(table.n_valid[i]),
                self.ds.nrows,
                self.ds.ncols,
                nlevels=img_cfg.nlevels,
                do_preprocessing=img_cfg.do_preprocessing,
                q=img_cfg.q,
            )
        return out


def make_backend(name: str, ds: SpectralDataset, ds_config: DSConfig,
                 sm_config: SMConfig):
    if name == "numpy_ref":
        return NumpyBackend(ds, ds_config)
    if name == "jax_tpu":
        from ..parallel.sharded import make_jax_backend  # deferred: jax import is heavy

        return make_jax_backend(ds, ds_config, sm_config)
    raise ValueError(f"unknown backend {name!r}")


@dataclass
class SearchResultsBundle:
    """Everything the orchestrator persists (reference: metrics df + sparse
    ion images handed to SearchResults.store [U])."""

    annotations: pd.DataFrame      # target ions with fdr/fdr_level
    all_metrics: pd.DataFrame      # every scored ion incl. decoys
    timings: dict[str, float] = field(default_factory=dict)


class MSMBasicSearch:
    """End-to-end search over a dataset + formula list (class name kept)."""

    def __init__(
        self,
        ds: SpectralDataset,
        formulas: list[str],
        ds_config: DSConfig,
        sm_config: SMConfig | None = None,
        isocalc_cache_dir: str | None = None,
    ):
        self.ds = ds
        self.formulas = list(dict.fromkeys(formulas))  # dedup, keep order
        self.ds_config = ds_config
        self.sm_config = sm_config or SMConfig.get_conf()
        self.isocalc = IsocalcWrapper(
            ds_config.isotope_generation, cache_dir=isocalc_cache_dir
        )
        # populated by search(); the orchestrator reads these to persist ion
        # images / m/z values for annotated ions (engine/search_job.py) —
        # last_backend lets the jax path export DEVICE images instead of
        # re-extracting on CPU
        self.last_table: IsotopePatternTable | None = None
        self.last_backend = None

    _ANN_COLUMNS = ["sf", "adduct", "msm", "fdr", "fdr_level",
                    "chaos", "spatial", "spectral"]
    _ALL_COLUMNS = ["sf", "adduct", "is_target", "chaos", "spatial",
                    "spectral", "msm"]

    def search(self) -> SearchResultsBundle:
        timings: dict[str, float] = {}
        if not self.formulas:
            return SearchResultsBundle(
                annotations=pd.DataFrame(columns=self._ANN_COLUMNS),
                all_metrics=pd.DataFrame(columns=self._ALL_COLUMNS),
            )
        iso_cfg = self.ds_config.isotope_generation
        fdr = FDR(
            decoy_sample_size=self.sm_config.fdr.decoy_sample_size,
            target_adducts=iso_cfg.adducts,
            seed=self.sm_config.fdr.seed,
        )
        with phase_timer("decoy_selection", timings):
            assignment: DecoyAssignment = fdr.decoy_adduct_selection(self.formulas)
            pairs, flags = assignment.all_ion_tuples(self.formulas, iso_cfg.adducts)
        with phase_timer("isotope_patterns", timings):
            table = self.isocalc.pattern_table(pairs, flags)
        self.last_table = table
        logger.info(
            "scoring %d ions (%d targets, %d decoys) with backend=%s",
            table.n_ions, int(table.targets.sum()),
            int((~table.targets).sum()), self.sm_config.backend,
        )
        backend = make_backend(
            self.sm_config.backend, self.ds, self.ds_config, self.sm_config
        )
        self.last_backend = backend
        batch = max(1, self.sm_config.parallel.formula_batch)
        metrics = np.zeros((table.n_ions, 4))
        with phase_timer("score", timings):
            slices = [(s, min(s + batch, table.n_ions))
                      for s in range(0, table.n_ions, batch)]
            # lazy slices: every backend exposes score_batches; the jax one
            # pipelines (async-enqueues all batches before syncing any), the
            # numpy one consumes one slice at a time
            outs = backend.score_batches(
                _slice_table(table, s, e) for s, e in slices)
            for (s, e), out in zip(slices, outs):
                metrics[s:e] = out
        with phase_timer("fdr", timings):
            all_df = pd.DataFrame(
                {
                    "sf": table.sfs,
                    "adduct": table.adducts,
                    "is_target": table.targets,
                    "chaos": metrics[:, 0],
                    "spatial": metrics[:, 1],
                    "spectral": metrics[:, 2],
                    "msm": metrics[:, 3],
                }
            )
            annotations = fdr.estimate_fdr(all_df[["sf", "adduct", "msm"]], assignment)
            annotations = annotations.merge(
                all_df[["sf", "adduct", "chaos", "spatial", "spectral"]],
                on=["sf", "adduct"],
                how="left",
            )
            # keep the declared schema authoritative for empty & non-empty paths
            annotations = annotations[self._ANN_COLUMNS]
            all_df = all_df[self._ALL_COLUMNS]
        return SearchResultsBundle(
            annotations=annotations, all_metrics=all_df, timings=timings
        )
