"""JAX/TPU backend: the fused extract+score graph.

North star (BASELINE.json): ion-image extraction and MSM scoring become JAX
functions vmapped over formula batches, the spectral cube a device-resident
(pixels x m/z) array, theoretical patterns a device tensor, and target/decoy
scoring one fused XLA graph.  This module is that graph, single-device; the
mesh-sharded variant lives in parallel/ (SURVEY.md §5.8).

The graph compiles ONCE per dataset: formula batches are padded to the static
``formula_batch`` size, so every batch reuses the same executable.
"""

from __future__ import annotations

from functools import partial, update_wrapper

import jax
import jax.numpy as jnp
import numpy as np

from ..analysis.numerics import numerics_surface
from ..analysis.surface import compile_surface
from ..io.dataset import SpectralDataset
from ..ops import buckets as shape_buckets
from ..utils import tracing
from ..ops.imager_jax import (
    BAND_WINDOWS as _BAND_WINDOWS,
)
from ..ops.imager_jax import (
    batch_peak_band,
    batch_peak_runs,
    compact_peaks,
    extract_images,
    extract_images_flat,
    extract_images_flat_banded,
    extract_images_mz_chunked,
    flat_bound_ranks,
    ion_window_chunks,
    ions_per_chunk_for,
    prepare_cube_arrays,
    prepare_flat_sorted_arrays,
    window_chunks,
    window_rank_grid,
)
from ..ops.isocalc import IsotopePatternTable
from ..ops.metrics_jax import (
    batch_metrics,
    batch_metrics_from_partials,
    correlation_from_moments,
    isotope_pattern_match_batch,
    measure_of_chaos_batch,
)
from ..ops.quantize import compact_cube, expand_cube_jnp, quantize_window
from ..ops.score_pallas import cols_padded, fused_fit, fused_window_moments
from ..utils.config import DSConfig, SMConfig
from ..utils.logger import logger

# The declared compile surface of this module (ISSUE 12, analysis/surface.py):
# every jit call site below registers its statics and the shape-bucket policy
# that keeps its signature family FINITE — the jit-compile-surface rule
# cross-checks these entries against the AST, and scripts/compile_census.py
# proves the observed runtime surface matches and stays closed.
COMPILE_SURFACE = compile_surface(__name__, {
    "fused_score_fn_chunked":
        "statics=gc_width,b,k; buckets=one executable per dataset config — "
        "b=formula_batch (batches padded), k=stream max_peaks, "
        "gc_width=mz_chunk knob",
    "fused_score_fn_flat_banded":
        "statics=gc_width,b,k; buckets=b in {lattice formula_batch, 256 "
        "tail}, sticky stream-max gc_width (_grow_for_stream fixpoint), "
        "k=stream max_peaks; dataset shapes snapped to the ops/buckets "
        "lattice (row-bucketed pixels, peak-bucketed residents, traced "
        "n_real) so every dataset size in a bucket shares the executable",
    "fused_score_fn_flat_banded_compact":
        "statics=gc_width,b,k,n_keep; buckets=flat-banded statics + n_keep "
        "rounded to 64k sticky capacity (_grow_compact_capacity)",
    "fused_score_fn_flat_banded_sliced":
        "statics=gc_width,b,k,w_cap; buckets=flat-banded statics + w_cap on "
        "the {1,1.125..1.875}x pow-2 band_bucket ladder "
        "(ops/imager_jax.band_bucket)",
    "fused_score_fn_flat_fused":
        "statics=gc_width,b,k; buckets=flat-banded statics (ISSUE 18): the "
        "fused Pallas kernel's grid/tiling derive from the same lattice "
        "shapes, starts/n_real ride as traced (scalar-prefetch) operands, "
        "and the cube dtype is a per-backend constant — so the fused "
        "family is exactly the plain family's size",
    "expand_cube_jnp":
        "statics=none; buckets=probe-only — one f32 expansion of the "
        "compact resident cube per probed backend (production expands "
        "inside the scoring jits)",
    "extract_images":
        "statics=none; buckets=one executable per backend — cube-path image "
        "export at the padded (b, k) batch shape",
    "extract_images_flat":
        "statics=closure(n_pixels); buckets=one executable per backend — "
        "flat-path image export at the padded (b, k) batch shape on the "
        "row-bucketed pixel lattice",
    "ext_base":
        "statics=closure(n_pixels,gc_width,n_keep,w_cap); buckets=probe-only "
        "re-jit of the production extraction variant (probe_phases inherits "
        "the sticky production statics, so no new shapes are minted)",
    "batch_moments":
        "statics=none; buckets=probe-only — one shape per probed batch "
        "(the padded production (b, k, P) block)",
    "measure_of_chaos_batch":
        "statics=closure(nrows,ncols,nlevels); buckets=probe-only — image "
        "geometry is per-dataset static",
    "correlation_from_moments":
        "statics=none; buckets=probe-only — padded (b, k) metric epilogue",
    "isotope_pattern_match_batch":
        "statics=none; buckets=probe-only — padded (b, k) metric epilogue",
})

# Declared numerics contracts (ISSUE 15, analysis/numerics.py): one per
# COMPILE_SURFACE site — the drift bound vs the site's reference (numpy
# oracle or sibling variant), the committed test that proves it, and the
# lattice-padded operands the masked-reduction rule tracks.  These are
# the gate for ROADMAP item 3: bf16/int8 compaction may not land unless
# every contract still holds (scripts/ulp_sentinel.py is the runtime
# check on the spheroid fixture).
NUMERICS = numerics_surface(__name__, {
    "fused_score_fn_chunked":
        "contract=ulp(8); test=tests/test_mz_chunking.py::"
        "test_chunked_scores_match",
    "fused_score_fn_flat_banded":
        "contract=ulp(16); test=tests/test_buckets.py::"
        "test_bucketed_scoring_bit_identical_fdr; "
        "padded=pixel_sorted,int_sorted",
    "fused_score_fn_flat_banded_compact":
        "contract=bit_exact; test=tests/test_jax_backend.py::"
        "test_peak_compaction_bit_exact; padded=pixel_sorted,int_sorted",
    "fused_score_fn_flat_banded_sliced":
        "contract=bit_exact; test=tests/test_jax_backend.py::"
        "test_band_slice_bit_exact; padded=pixel_sorted,int_sorted",
    "fused_score_fn_flat_fused":
        "contract=ulp(16); test=tests/test_score_pallas.py::"
        "test_fused_variant_matches_plain; padded=pixel_sorted,int_sorted",
    "expand_cube_jnp":
        "contract=bit_exact; test=tests/test_score_pallas.py::"
        "test_compact_expand_roundtrip",
    "extract_images":
        "contract=bit_exact; test=tests/test_jax_backend.py::"
        "test_extraction_parity",
    "extract_images_flat":
        "contract=bit_exact; test=tests/test_jax_backend.py::"
        "test_extraction_flat_bit_identical_to_cube",
    "ext_base":
        "contract=bit_exact; test=tests/test_jax_backend.py::"
        "test_extraction_flat_bit_identical_to_cube",
    "batch_moments":
        "contract=ulp(16); test=tests/test_moments.py::"
        "test_moments_jnp_fallback_matches_f64",
    "measure_of_chaos_batch":
        "contract=bit_exact; test=tests/test_jax_backend.py::"
        "test_chaos_batch_matches_numpy",
    "correlation_from_moments":
        "contract=ulp(16); test=tests/test_jax_backend.py::"
        "test_backend_parity_metrics_and_ranks",
    "isotope_pattern_match_batch":
        "contract=ulp(16); test=tests/test_jax_backend.py::"
        "test_backend_parity_metrics_and_ranks",
})


def _maybe_barrier(imgs: jnp.ndarray, k: int, n_pix: int) -> jnp.ndarray:
    """Materialize the image block before the metric consumers ONLY when
    the metrics run as XLA reductions: there, XLA fusing the extraction
    into the three consumers regressed the step ~3.4x at 65k pixels
    (docs/PERF.md mechanism 3).  On the TPU Pallas metrics route
    (ops/moments_pallas.py + chaos kernels) the consumers are opaque
    kernel calls — the input is materialized once by definition and the
    extra barrier copy is a pure full-block pass wasted (~2.1 GB per
    DESI batch)."""
    from ..ops.moments_pallas import moments_fit

    if jax.default_backend() == "tpu" and moments_fit(k, n_pix):
        return imgs
    return jax.lax.optimization_barrier(imgs)


def fused_score_fn_flat_banded(
    pixel_sorted: jnp.ndarray,  # (N,) int32
    int_sorted: jnp.ndarray,   # (N,) f32
    pos: jnp.ndarray,          # (G,) int32 host-computed bound ranks
    starts: jnp.ndarray,       # (C,) chunk grid offsets
    r_lo_loc: jnp.ndarray,     # (C, Wc)
    r_hi_loc: jnp.ndarray,     # (C, Wc)
    inv: jnp.ndarray,          # (B*K,)
    theor_ints: jnp.ndarray,
    n_valid: jnp.ndarray,
    n_real=None,               # () i32 traced: REAL pixel count (lattice)
    scales=None,               # (N/QTILE,) f32 int8-cube dequant factors
    *,
    gc_width: int,
    b: int,
    k: int,
    nrows: int,
    ncols: int,
    nlevels: int,
    do_preprocessing: bool,
    q: float,
) -> jnp.ndarray:
    """Fused flat-path scoring: banded-matmul extraction (flops linear in
    the batch, so large batches amortize the histogram scatter — see
    ops/imager_jax.py::extract_images_flat_banded) + MSM metrics.

    The chunk plan is ION-MAJOR (ion_window_chunks): extraction emits the
    (b, k, P) block directly — no multi-GB image-row gather; ``inv`` is
    the (b,) ion inverse permutation applied to the (b, 4) METRIC rows,
    and theor_ints / n_valid arrive already ion-sorted.

    Shape-bucket lattice (ISSUE 13): ``nrows`` is the ROW-BUCKETED grid
    (ops/buckets.row_bucket) and the resident peak arrays are padded to a
    lattice capacity, so every dataset size in a bucket shares ONE
    executable; ``n_real`` carries the true pixel count as a traced
    scalar for the masked metric centering (bit-identical to unpadded —
    see batch_metrics).

    ``scales`` + a compact ``int_sorted`` dtype (parallel.cube_dtype,
    ISSUE 18): the resident cube arrives bf16/int8 and is expanded to an
    f32 TRANSIENT in-graph (XLA fuses the cast into the scatter's operand
    read) — with cube_dtype="f32" (legacy default) the expansion is a
    python-level no-op and the traced program is byte-identical."""
    int_sorted = expand_cube_jnp(int_sorted, scales)
    imgs = extract_images_flat_banded(
        pixel_sorted, int_sorted, pos, starts, r_lo_loc, r_hi_loc, None,
        gc_width=gc_width, n_pixels=nrows * ncols)
    imgs = _maybe_barrier(imgs, k, nrows * ncols)
    imgs = imgs.reshape(b, k, -1)
    out = batch_metrics(
        imgs, theor_ints, n_valid, nrows, ncols, nlevels,
        do_preprocessing=do_preprocessing, q=q, n_real=n_real,
    )
    return jnp.take(out, inv, axis=0)


def fused_score_fn_flat_fused(
    pixel_sorted: jnp.ndarray,  # (N,) int32
    int_sorted: jnp.ndarray,   # (N,) f32/bf16/int8 resident intensities
    pos: jnp.ndarray,          # (G,) int32 host-computed bound ranks
    starts: jnp.ndarray,       # (C,) chunk grid offsets
    r_lo_loc: jnp.ndarray,     # (C, Wc)
    r_hi_loc: jnp.ndarray,     # (C, Wc)
    inv: jnp.ndarray,          # (B*K,)
    theor_ints: jnp.ndarray,
    n_valid: jnp.ndarray,
    n_real=None,               # () i32 traced: REAL pixel count (lattice)
    scales=None,               # (N/QTILE,) f32 int8-cube dequant factors
    *,
    gc_width: int,
    b: int,
    k: int,
    nrows: int,
    ncols: int,
    nlevels: int,
    do_preprocessing: bool,
    q: float,
) -> jnp.ndarray:
    """Flat-path scoring through the ONE-PASS fused Pallas kernel
    (ops/score_pallas.py, ISSUE 18): the banded membership matmul and
    every per-window moment reduction happen on VMEM-staged tiles of the
    histogram — the (b, k, P) image block never round-trips HBM; only the
    principal rows (chaos needs their spatial layout) are written back.

    Same argument layout and statics as ``fused_score_fn_flat_banded``
    (the 'plain' variant) — the routing in ``JaxBackend._flat_call`` just
    swaps the jit.  Metric rows come back in the plan's chunk-sorted ion
    order and ``inv`` un-permutes them, exactly like the other variants.

    Numerics: principal images / chaos / spectral / vmax / nn are
    bit-exact vs the plain variant (exact integer-grid sums in any
    association order); the spatial correlation's centered reductions
    re-associate per pixel tile — within the declared ulp(16) ceiling.
    The fused route requires ``do_preprocessing=False`` (hotspot clipping
    needs the full materialized image block); routing enforces it."""
    if do_preprocessing:
        raise ValueError(
            "the fused scoring kernel cannot apply hotspot preprocessing "
            "(no materialized image block); route via the plain variant")
    int_sorted = expand_cube_jnp(int_sorted, scales)
    n_pix = nrows * ncols
    n = pixel_sorted.shape[0]
    g = pos.shape[0]
    # the same bins-major histogram as extract_images_flat_banded, with
    # the scratch rows padded to whole super-rows (score_pallas.SC) plus
    # the spare band the unclamped super-row fetch may touch — spare rows
    # are zero-initialized and outside every window's rank range
    delta = jnp.zeros(n + 1, jnp.int32).at[pos].add(1)
    bins = jnp.cumsum(delta[:-1])
    cols_p = cols_padded(g, gc_width)
    wh = jnp.zeros((cols_p, n_pix + 1), jnp.float32).at[
        bins, pixel_sorted].add(int_sorted)
    whp = wh[:, :n_pix]
    nr = n_real if n_real is not None else np.int32(n_pix)
    # CPU (tests, sentinel, fused_metrics="on" off-TPU) runs the Pallas
    # interpreter — same kernel schedule, no Mosaic tiling constraints
    interpret = jax.default_backend() != "tpu"
    partials, principal = fused_window_moments(
        whp, starts, r_lo_loc, r_hi_loc, nr,
        gc_width=gc_width, k=k, interpret=interpret)
    out = batch_metrics_from_partials(
        partials.reshape(b, k, 5), principal.reshape(b, n_pix),
        theor_ints, n_valid, nrows, ncols, nlevels)
    return jnp.take(out, inv, axis=0)


def _extract_sliced(
    pixel_sorted, int_sorted, w_start, pos_b,
    starts, r_lo_loc, r_hi_loc, inv, *, w_cap, gc_width, n_pixels,
):
    """Band slice + banded extraction (the first half of
    fused_score_fn_flat_banded_sliced) as a standalone probe phase.
    ``inv`` (the ion un-permutation) is unused here — probe consumers work
    in the plan's ion-sorted order with matching permuted side inputs."""
    px_b = jax.lax.dynamic_slice(pixel_sorted, (w_start,), (w_cap,))
    in_b = jax.lax.dynamic_slice(int_sorted, (w_start,), (w_cap,))
    return extract_images_flat_banded(
        px_b, in_b, pos_b, starts, r_lo_loc, r_hi_loc, None,
        gc_width=gc_width, n_pixels=n_pixels)


def fused_score_fn_flat_banded_sliced(
    pixel_sorted: jnp.ndarray,  # (N,) int32 resident peaks
    int_sorted: jnp.ndarray,   # (N,) f32
    w_start: jnp.ndarray,      # () i32 band start rank (host-clamped)
    pos_b: jnp.ndarray,        # (G,) i32 band-space bound ranks
    starts: jnp.ndarray,       # (C,) chunk grid offsets
    r_lo_loc: jnp.ndarray,     # (C, Wc)
    r_hi_loc: jnp.ndarray,     # (C, Wc)
    inv: jnp.ndarray,          # (B*K,)
    theor_ints: jnp.ndarray,
    n_valid: jnp.ndarray,
    n_real=None,               # () i32 traced: REAL pixel count (lattice)
    scales=None,               # (N/QTILE,) f32 int8-cube dequant factors
    *,
    w_cap: int,
    gc_width: int,
    b: int,
    k: int,
    nrows: int,
    ncols: int,
    nlevels: int,
    do_preprocessing: bool,
    q: float,
) -> jnp.ndarray:
    """Flat-banded scoring over a CONTIGUOUS band slice of the resident
    peaks.  With an m/z-ordered ion table (parallel.order_ions="mz") each
    batch's window union spans a narrow contiguous rank band, so extraction
    can scatter a dynamic_slice of the resident arrays directly: scatter
    cost is per-band-peak (like compaction) but WITHOUT the packed-run
    gather (measured ~23 ns/slot, i.e. ~60% of the compact path's cost at
    DESI scale).  Peaks inside the slice but outside every window land in
    gap bins with zero band membership, and ``pos_b`` is host-shifted with
    padding bounds clipped to 0 — both exactly mirror how the full plain
    path treats peaks before/after/between windows, so images (and hence
    metrics) are bit-identical to the uncompacted path.  Ion-major chunk
    plan: see fused_score_fn_flat_banded (``inv`` un-permutes metric
    rows)."""
    int_sorted = expand_cube_jnp(int_sorted, scales)
    px_b = jax.lax.dynamic_slice(pixel_sorted, (w_start,), (w_cap,))
    in_b = jax.lax.dynamic_slice(int_sorted, (w_start,), (w_cap,))
    imgs = extract_images_flat_banded(
        px_b, in_b, pos_b, starts, r_lo_loc, r_hi_loc, None,
        gc_width=gc_width, n_pixels=nrows * ncols)
    imgs = _maybe_barrier(imgs, k, nrows * ncols)
    imgs = imgs.reshape(b, k, -1)
    out = batch_metrics(
        imgs, theor_ints, n_valid, nrows, ncols, nlevels,
        do_preprocessing=do_preprocessing, q=q, n_real=n_real,
    )
    return jnp.take(out, inv, axis=0)


def _extract_compact(
    pixel_sorted, int_sorted, run_pos, run_delta, n_b, pos_b,
    starts, r_lo_loc, r_hi_loc, inv, *, n_keep, gc_width, n_pixels,
):
    """Compaction + banded extraction (the first half of
    fused_score_fn_flat_banded_compact) as a standalone probe phase.
    ``inv`` unused — see _extract_sliced."""
    px_b, in_b = compact_peaks(
        pixel_sorted, int_sorted, run_pos, run_delta, n_b,
        n_keep=n_keep, n_pixels=n_pixels)
    return extract_images_flat_banded(
        px_b, in_b, pos_b, starts, r_lo_loc, r_hi_loc, None,
        gc_width=gc_width, n_pixels=n_pixels)


def fused_score_fn_flat_banded_compact(
    pixel_sorted: jnp.ndarray,  # (N,) int32 resident peaks
    int_sorted: jnp.ndarray,   # (N,) f32
    run_pos: jnp.ndarray,      # (R_pad,) i32 kept-space run starts
    run_delta: jnp.ndarray,    # (R_pad,) i32 per-run source-offset jumps
    n_b: jnp.ndarray,          # () i32 kept peaks this batch
    pos_b: jnp.ndarray,        # (G,) i32 kept-space bound ranks
    starts: jnp.ndarray,       # (C,) chunk grid offsets
    r_lo_loc: jnp.ndarray,     # (C, Wc)
    r_hi_loc: jnp.ndarray,     # (C, Wc)
    inv: jnp.ndarray,          # (B*K,)
    theor_ints: jnp.ndarray,
    n_valid: jnp.ndarray,
    n_real=None,               # () i32 traced: REAL pixel count (lattice)
    scales=None,               # (N/QTILE,) f32 int8-cube dequant factors
    *,
    n_keep: int,
    gc_width: int,
    b: int,
    k: int,
    nrows: int,
    ncols: int,
    nlevels: int,
    do_preprocessing: bool,
    q: float,
) -> jnp.ndarray:
    """Flat-banded scoring with PER-BATCH peak compaction: only the peaks
    inside this batch's window union are gathered and histogrammed, so the
    scatter cost is per-hit, not per-resident-peak (the dominant cost in the
    many-batch large-pixel regime — see ops/imager_jax.py compaction notes).
    Images, and hence metrics, are bit-identical to the uncompacted path.
    Ion-major chunk plan: see fused_score_fn_flat_banded (``inv``
    un-permutes metric rows)."""
    int_sorted = expand_cube_jnp(int_sorted, scales)
    px_b, in_b = compact_peaks(
        pixel_sorted, int_sorted, run_pos, run_delta, n_b,
        n_keep=n_keep, n_pixels=nrows * ncols)
    imgs = extract_images_flat_banded(
        px_b, in_b, pos_b, starts, r_lo_loc, r_hi_loc, None,
        gc_width=gc_width, n_pixels=nrows * ncols)
    imgs = _maybe_barrier(imgs, k, nrows * ncols)
    imgs = imgs.reshape(b, k, -1)
    out = batch_metrics(
        imgs, theor_ints, n_valid, nrows, ncols, nlevels,
        do_preprocessing=do_preprocessing, q=q, n_real=n_real,
    )
    return jnp.take(out, inv, axis=0)


def fused_score_fn_chunked(
    mz_q_cube: jnp.ndarray,
    int_cube: jnp.ndarray,
    grid: jnp.ndarray,
    starts: jnp.ndarray,       # (C,) chunk grid offsets
    r_lo_loc: jnp.ndarray,     # (C, Wc)
    r_hi_loc: jnp.ndarray,     # (C, Wc)
    inv: jnp.ndarray,          # (B*K,)
    theor_ints: jnp.ndarray,
    n_valid: jnp.ndarray,
    *,
    gc_width: int,
    b: int,
    k: int,
    nrows: int,
    ncols: int,
    nlevels: int,
    do_preprocessing: bool,
    q: float,
) -> jnp.ndarray:
    """Fused cube-path scoring: extraction loops over m/z chunks so the
    histogram scratch is bounded at (P, gc_width+2) — SURVEY §5.7 m/z-segment
    axis.  Ion images (and hence chaos, which is integer-count based) are
    bit-identical to the unchunked path; spatial/spectral can differ by ulps
    because XLA picks different reduction fusions for the two program
    variants (observed at 128x128 px on TPU)."""
    imgs = extract_images_mz_chunked(
        mz_q_cube, int_cube, grid, starts, r_lo_loc, r_hi_loc, inv,
        gc_width=gc_width)
    imgs = imgs.reshape(b, k, -1)[:, :, : nrows * ncols]
    return batch_metrics(
        imgs, theor_ints, n_valid, nrows, ncols, nlevels,
        do_preprocessing=do_preprocessing, q=q,
    )


# One row per extraction variant so the dispatch/probe sites cannot drift:
# (jitted-scorer attr on JaxBackend, standalone extract fn, #args consumed
# by extraction (the rest are (theor_ints, n_valid, n_real)), index of the
# bound-ranks array in the args list)
_VARIANTS = {
    "plain": ("_fn", extract_images_flat_banded, 5, 0),
    "compact": ("_fn_c", _extract_compact, 8, 3),
    "band": ("_fn_bs", _extract_sliced, 6, 1),
    # the fused Pallas scorer (ISSUE 18) shares the plain variant's
    # argument layout and statics — only the jit differs; its extraction
    # probe is the plain banded extraction (the fused kernel has no
    # standalone image phase — that is the point)
    "fused": ("_fn_f", extract_images_flat_banded, 5, 0),
}


def named_partial(fn, **kwargs) -> partial:
    """``partial`` that keeps ``fn``'s name, so ``jax.jit`` labels the
    compiled program ``jit_<fn.__name__>`` instead of
    ``jit__unnamed_wrapped_function_``.  The on-demand device profiler
    (service/fleetview.py, ISSUE 20) attributes per-kernel device time by
    HLO module name — an anonymous partial makes the entire scoring path
    unattributable in /debug/profile and the roofline bench."""
    p = partial(fn, **kwargs)
    update_wrapper(p, fn)
    return p


def make_flat_jits(common: dict) -> dict:
    """The flat-path jitted scorers for one metric geometry, keyed by
    variant name.  ``common`` is the closure dict (nrows — row-bucketed
    under the lattice — ncols, nlevels, do_preprocessing, q).

    THE one place these jits are constructed: ``JaxBackend.__init__``
    binds them to ``self._fn*`` and the AOT cache primer
    (``service/primer.py``) builds byte-identical programs from a recorded
    BucketSpec — same function objects, same partial closure, same
    static_argnames — so a primed persistent-cache entry is exactly the
    entry a later real job looks up (ISSUE 13)."""
    return {
        "plain": jax.jit(
            named_partial(fused_score_fn_flat_banded, **common),
            static_argnames=("gc_width", "b", "k")),
        "compact": jax.jit(
            named_partial(fused_score_fn_flat_banded_compact, **common),
            static_argnames=("n_keep", "gc_width", "b", "k")),
        "band": jax.jit(
            named_partial(fused_score_fn_flat_banded_sliced, **common),
            static_argnames=("w_cap", "gc_width", "b", "k")),
        "fused": jax.jit(
            named_partial(fused_score_fn_flat_fused, **common),
            static_argnames=("gc_width", "b", "k")),
    }


def to_numpy_global(arr) -> np.ndarray:
    """Fetch a (possibly multi-process sharded) jax.Array to host numpy.

    In a real multi-host run the per-batch output spans processes, so plain
    ``np.asarray`` raises on the non-addressable shards.  The output is
    replicated over the "pixels" mesh axis, so each process's devices
    normally hold every formula shard — assemble them; if any process's
    local shards don't cover the array (asymmetric device-to-process
    layout), fall back to an explicit cross-process allgather.  The
    fallback decision is computed from the GLOBAL sharding metadata, not
    this process's shards, so every process reaches the same verdict —
    a per-process decision could leave only some processes entering the
    collective and deadlock the SPMD program (advisor r3)."""
    if getattr(arr, "is_fully_addressable", True):
        # smlint: host-sync-ok[the designed result-fetch point; callers sync only after the whole group is enqueued]
        return np.asarray(arr)

    def _key(idx) -> tuple:
        return tuple((s.start, s.stop, s.step) for s in idx)

    # a process covers the array iff its devices hold every distinct shard
    # index the full device set holds (the full set covers by definition;
    # this subset test is exact for disjoint tilings + replication, and for
    # any exotic overlapping sharding it errs toward the collective)
    index_map = arr.sharding.devices_indices_map(arr.shape)
    global_keys = {_key(idx) for idx in index_map.values()}
    by_proc: dict[int, set] = {}
    for d, idx in index_map.items():
        by_proc.setdefault(d.process_index, set()).add(_key(idx))
    # a process with NO device in this sharding (sub-mesh array) holds no
    # shards at all — it must take the collective with everyone else
    if (len(by_proc) != jax.process_count()
            or any(keys != global_keys for keys in by_proc.values())):
        from jax.experimental import multihost_utils

        # smlint: host-sync-ok[multi-host fetch fallback; the allgather IS the sync, every process takes it in lockstep]
        return np.asarray(multihost_utils.process_allgather(arr, tiled=True))
    out = np.empty(arr.shape, arr.dtype)
    for sh in arr.addressable_shards:
        # smlint: host-sync-ok[per-shard assembly of a replicated output]
        out[sh.index] = np.asarray(sh.data)
    return out


def fetch_scored_batches(pending) -> list[np.ndarray]:
    """Fetch (device_out, n) pairs concurrently, preserving order.

    Each result fetch is a blocking round-trip (~80-100 ms through a
    tunneled TPU); done serially those round-trips WERE the pipeline's
    critical path (18 batches -> 1.8 s of latency).  A thread pool overlaps
    them (the GIL is released during transfers), leaving device compute as
    the floor — measured 7.2k -> 15.7k ions/s on the bench workload.  (A
    device-side jnp.stack + single fetch was tried first: its one-off concat
    compile costs ~3 s per distinct batch count, worse than it saves.)
    """
    from concurrent.futures import ThreadPoolExecutor

    if not pending:
        return []
    if any(not getattr(p[0], "is_fully_addressable", True) for p in pending):
        # multi-process outputs: to_numpy_global may fall back to a
        # process_allgather COLLECTIVE, and threads could issue collectives
        # in different orders on different processes (SPMD deadlock) —
        # fetch sequentially, in pending order, on every process
        return [to_numpy_global(p[0])[:p[1]].astype(np.float64)
                for p in pending]
    with ThreadPoolExecutor(max_workers=min(8, len(pending))) as pool:
        return list(pool.map(
            lambda p: to_numpy_global(p[0])[:p[1]].astype(np.float64), pending))


# Warmup persistent-cache outcomes (ISSUE 6): "hit" = the warmup manifest
# proved the cache already held every executable kind (executions skipped),
# "miss" = representative batches actually ran (compile or cache-load).
# Module-level plain ints (GIL-atomic increments); the service telemetry
# collector pulls them lazily — this module stays service-agnostic.
_WARMUP_CACHE_EVENTS = {"hit": 0, "miss": 0}


def warmup_cache_events() -> dict:
    return dict(_WARMUP_CACHE_EVENTS)


class JaxBackend:
    """Fused-graph scorer selected by ``SMConfig.backend == 'jax_tpu'``."""

    name = "jax_tpu"

    def __init__(self, ds: SpectralDataset, ds_config: DSConfig,
                 sm_config: SMConfig,
                 restrict_table: IsotopePatternTable | None = None,
                 device=None):
        from ..parallel.distributed import enable_compile_cache

        self.ds = ds
        self.ds_config = ds_config
        # chip pinning (ISSUE 7): a 1-chip device-pool lease pins this
        # backend's RESIDENT arrays (and therefore every jitted program —
        # committed inputs anchor placement, uncommitted batch args follow)
        # to that jax Device, so two 1-chip jobs compute on distinct chips
        # concurrently.  None = the process default device (pre-pool
        # behavior).
        self.device = device
        enable_compile_cache(sm_config)
        from ..parallel.distributed import compile_cache_path

        # warm-start trim (ISSUE 3 satellite): when the persistent XLA
        # cache already proved it holds this stream's executables (warmup
        # manifest), warmup skips the representative-batch EXECUTIONS
        self._compile_cache = compile_cache_path(sm_config)
        shape_buckets.bind_manifest_dir(self._compile_cache)
        self.last_warmup_skipped = False
        # shape-bucket lattice (ISSUE 13, ops/buckets.py): the pad-to batch
        # snaps DOWN to a lattice point (msm_basic slices at the same
        # point), image rows snap UP with zero-row padding masked by the
        # traced real-pixel count, and the resident peak arrays pad to a
        # lattice capacity — so every dataset size maps into the closed
        # signature set the census proves and the primer precompiles
        self._buckets = shape_buckets.buckets_enabled(sm_config.parallel)
        self.batch = shape_buckets.effective_batch(sm_config.parallel)
        img_cfg = ds_config.image_generation
        self.ppm = img_cfg.ppm
        self._nrows_b = (shape_buckets.row_bucket(ds.nrows)
                         if self._buckets else ds.nrows)
        self._n_pix_b = self._nrows_b * ds.ncols
        # traced real-pixel count: None when the lattice is off (the
        # legacy unpadded program), a host scalar shipped per batch when on
        self._n_real = np.int32(ds.n_pixels) if self._buckets else None

        self.int_scale = ds.intensity_quantization(self.ppm)[1]
        self.mz_chunk = max(0, sm_config.parallel.mz_chunk)
        common = dict(
            nrows=self._nrows_b,
            ncols=ds.ncols,
            nlevels=img_cfg.nlevels,
            do_preprocessing=img_cfg.do_preprocessing,
            q=img_cfg.q,
        )
        self._common = dict(common)
        if self.mz_chunk:
            # chunked path stays on the padded cube: its scratch bound
            # (gc_width) is the point, and the cube shards cleanly.  It
            # also stays OFF the pixel lattice — the cube's row layout is
            # per-dataset anyway, so bucketing rows would not close its
            # signature family (COMPILE_SURFACE declares it per-dataset)
            if restrict_table is not None:
                logger.info(
                    "window-union restriction not applicable on the "
                    "mz_chunk cube path (dense per-pixel rows); scoring "
                    "the full cube")
            mz_q, int_cube = prepare_cube_arrays(ds, ppm=self.ppm)
            self._mz_q = jax.device_put(mz_q, self.device)
            self._ints = jax.device_put(int_cube, self.device)
            logger.info(
                "jax_tpu cube resident: %s int32 + %s f32 on %s",
                mz_q.shape, int_cube.shape, self._mz_q.devices(),
            )
            self._nrows_b = ds.nrows
            self._n_pix_b = ds.n_pixels
            self._n_real = None
            self._fn = jax.jit(
                named_partial(fused_score_fn_chunked, **{**common,
                                                         "nrows": ds.nrows}),
                static_argnames=("gc_width", "b", "k"),
            )
        else:
            # flat globally-sorted layout: no padding slots; per-batch bound
            # ranks computed ON HOST against the host copy of the sorted m/z
            # array and shipped as (G,) int32 (see ops/imager_jax.py)
            # guard: the histogram scratch is (P+1, 2BK+gc) f32 — beyond a
            # few GB the device OOM is opaque, so fail early with guidance
            k_est = ds_config.isotope_generation.n_peaks
            # scratch cols = max(G+1, gc+2): bins live in [0, G=2BK]; chunk
            # slices clamp+shift instead of spilling past G (imager_jax);
            # rows are the BUCKETED pixel count — that is what allocates
            scratch = 4 * (self._n_pix_b + 1) * max(
                2 * self.batch * k_est + 1, 4098)
            if scratch > (8 << 30):
                raise ValueError(
                    f"flat-path histogram scratch would be ~{scratch / 2**30:.0f}"
                    f" GiB ({ds.n_pixels} pixels x formula_batch={self.batch}"
                    f" x {k_est} peaks); reduce parallel.formula_batch, shard"
                    " pixels over a mesh (parallel.pixels_axis), or set"
                    " parallel.mz_chunk to use the bounded-scratch cube path")
            mz_s, px_s, in_s = prepare_flat_sorted_arrays(ds, self.ppm)
            if restrict_table is not None:
                # drop peaks outside EVERY window of the search up front —
                # the reference's "only hits shuffle" property [U]: on noisy
                # data most peaks match nothing, and the per-peak scatter is
                # the dominant extraction cost
                from ..ops.imager_jax import restrict_flat_to_windows

                lo_q, hi_q = quantize_window(restrict_table.mzs, self.ppm)
                mzk, pxk, ink, n_eff = restrict_flat_to_windows(
                    mz_s[None], px_s[None], in_s[None],
                    lo_q, hi_q, overflow_row=ds.n_pixels)
                logger.info(
                    "window-union restriction: %d -> %d peaks (%.0f%% dropped)",
                    mz_s.size, n_eff,
                    100.0 * (1 - n_eff / max(mz_s.size, 1)))
                mz_s, px_s, in_s = mzk[0], pxk[0], ink[0]
            if self._buckets:
                # lattice-pad the resident arrays (ops/buckets.peak_bucket)
                # with the SAME slot shape the 1024-multiple rounding
                # already uses: m/z saturates to the MZ_PAD_Q sentinel
                # (outside every window), pixel points at the overflow row,
                # intensity 0 — bit-exact, and every dataset whose peak
                # count shares the bucket shares the executable
                n_pad = shape_buckets.peak_bucket(mz_s.size)
                if n_pad > mz_s.size:
                    from ..ops.quantize import MZ_PAD_Q

                    tail = n_pad - mz_s.size
                    mz_s = np.concatenate(
                        [mz_s, np.full(tail, MZ_PAD_Q, mz_s.dtype)])
                    px_s = np.concatenate(
                        [px_s, np.full(tail, ds.n_pixels, px_s.dtype)])
                    in_s = np.concatenate(
                        [in_s, np.zeros(tail, in_s.dtype)])
            # resident-cube intensity compaction (ISSUE 18): bf16 halves /
            # int8 quarters the HBM-resident cube; the f32 view is a
            # per-batch transient inside the scoring jits.  int8 needs
            # QTILE-aligned peaks — lattice points are 1024-multiples, so
            # only the lattice-off int8 combination pads here (same
            # zero-intensity overflow-row slots as the lattice pad).
            self._cube_dtype = sm_config.parallel.cube_dtype
            from ..ops.quantize import MZ_PAD_Q, QTILE
            if self._cube_dtype == "int8" and in_s.size % QTILE != 0:
                tail = -in_s.size % QTILE
                mz_s = np.concatenate(
                    [mz_s, np.full(tail, MZ_PAD_Q, mz_s.dtype)])
                px_s = np.concatenate(
                    [px_s, np.full(tail, ds.n_pixels, px_s.dtype)])
                in_s = np.concatenate([in_s, np.zeros(tail, in_s.dtype)])
            codes, scales = compact_cube(in_s, self._cube_dtype)
            self._mz_host = mz_s
            self._px_s = jax.device_put(px_s, self.device)
            self._in_s = jax.device_put(codes, self.device)
            self._scales = (jax.device_put(scales, self.device)
                            if scales is not None else None)
            logger.info(
                "jax_tpu flat peaks resident: %d sorted peaks (%.1f MB, "
                "cube_dtype=%s) on %s",
                mz_s.size,
                (px_s.nbytes + codes.nbytes) / 1e6,
                self._cube_dtype, self._px_s.devices(),
            )
            fns = make_flat_jits(common)
            self._fn = fns["plain"]
            self._fn_c = fns["compact"]
            self._fn_bs = fns["band"]
            self._fn_f = fns["fused"]
            # fused-kernel routing (ISSUE 18): "auto" fuses on TPU when
            # the plan shape fits the kernel's VMEM budget; "on" forces
            # the fused variant everywhere (interpret-mode off-TPU — the
            # tests/sentinel path); hotspot preprocessing excludes fusion
            self._fused_mode = sm_config.parallel.fused_metrics
            # sticky static shapes: grow to the max seen so one executable
            # serves (almost) all batches instead of recompiling per batch
            self._gc_width = 0
            self._gc_tail = 0         # band width of the small-batch variant
            self._n_keep = 0          # compacted peak capacity
            self._r_pad = 0           # compaction run-list capacity
            self._compaction = sm_config.parallel.peak_compaction
            self._band_mode = sm_config.parallel.band_slice

    # static batch size for SMALL tables (the stream's tail): a 212-ion
    # final slice padded to formula_batch=2048 pays the full batch's
    # histogram zero-fill + chaos + metrics cost — a second executable at
    # this size cuts that to ~1/8th for one extra (cached) compile
    _TAIL_BATCH = 256

    def _batch_for(self, n: int) -> int:
        # cube path and small formula_batch configs keep one executable
        if self.mz_chunk or self.batch <= self._TAIL_BATCH:
            return self.batch
        return self._TAIL_BATCH if n <= self._TAIL_BATCH else self.batch

    def shrink_batch(self, batch: int) -> None:
        """HBM-OOM backoff hook (ISSUE 10, models/oom.py): cap the static
        padding batch.  Smaller tables compile (cached) executables at the
        new size; per-ion metrics are unchanged — batch size only sets
        padding and scratch shape.  Shrink-only: growing mid-stream would
        recompile for no benefit.  Under the lattice (ISSUE 13) the new
        cap snaps DOWN to a lattice point, so an OOM-shrunk batch lands
        on an executable the primer enumerated instead of minting a
        one-off size."""
        new = max(1, int(batch))
        if self._buckets:
            new = shape_buckets.batch_bucket_down(new)
        if new < self.batch:
            logger.warning("jax_tpu backend: formula batch %d -> %d "
                           "(OOM backoff)", self.batch, new)
            self.batch = new

    def _padded_windows(self, table: IsotopePatternTable, b: int | None = None):
        """Pad one batch's quantized windows to the static batch size
        (padded ions: bounds (0, 0), n_valid=0 -> all metrics 0) and rank
        the bounds: (grid, r_lo, r_hi, ints_p, nv_p)."""
        n, b = table.n_ions, b or self.batch
        if n > b:
            raise ValueError(f"batch of {n} ions exceeds formula_batch={b}")
        k = table.max_peaks
        lo_q, hi_q = quantize_window(table.mzs, self.ppm)
        lo_p = np.zeros((b, k), dtype=np.int32)
        hi_p = np.zeros((b, k), dtype=np.int32)
        ints_p = np.zeros((b, k), dtype=np.float32)
        nv_p = np.zeros(b, dtype=np.int32)
        lo_p[:n], hi_p[:n] = lo_q, hi_q
        ints_p[:n] = table.ints
        nv_p[:n] = table.n_valid
        grid, r_lo, r_hi = window_rank_grid(lo_p, hi_p)
        return grid, r_lo, r_hi, ints_p, nv_p

    def _flat_plan(self, table: IsotopePatternTable):
        """Host prep of one batch for the flat-banded path: padded windows,
        the window-chunk plan, bound ranks, and (unless disabled) the
        per-batch peak-compaction runs.  Computed once per table
        (score_batches builds the plans up front to pre-size the static
        shapes, then reuses them)."""
        b_eff = self._batch_for(table.n_ions)
        grid, r_lo, r_hi, ints_p, nv_p = self._padded_windows(table, b_eff)
        # ion-major plan: whole ions per chunk (largest divisor of the
        # static batch within the BAND_WINDOWS budget), so extraction
        # emits (b, k, P) directly and only metric rows get un-permuted
        k_eff = max(1, table.max_peaks)
        chunks = ion_window_chunks(
            r_lo, r_hi, b_eff, k_eff,
            ions_per_chunk_for(b_eff, k_eff, _BAND_WINDOWS))
        pos = flat_bound_ranks(self._mz_host, grid)
        runs, band = None, None
        if self._compaction != "off" or self._band_mode != "off":
            lo_q, hi_q = quantize_window(table.mzs, self.ppm)
            if self._compaction != "off":
                runs = batch_peak_runs(self._mz_host, lo_q, hi_q, pos)
            if self._band_mode != "off":
                band = batch_peak_band(self._mz_host, lo_q, hi_q)
        return (grid, r_lo, r_hi, ints_p, nv_p, chunks, pos, runs, b_eff,
                band)

    # band-slice w_cap buckets: the shared {1, 1.5} x pow-2 ladder
    # (ops/imager_jax.band_bucket — the sharded backend uses the same one)
    _BAND_MIN = 1 << 21

    def _band_bucket(self, width: int) -> int:
        from ..ops.imager_jax import band_bucket

        return band_bucket(width, self._BAND_MIN)

    def _variant_for(self, runs, band) -> str:
        """Pick the extraction variant for one batch: 'band' (scatter a
        contiguous dynamic slice of the resident peaks), 'compact' (gather
        the packed window-union runs, then scatter), or 'plain' (scatter
        everything).  Auto mode minimizes estimated scatter/gather cost
        with the measured v5e per-slot rates (docs/PERF.md: scatter ~14
        ns/slot, packed-run gather ~23 ns/slot -> compact ~37 ns per
        capacity slot); 'on' modes force a variant for tests, band first.

        The compact estimate charges the sticky ``_n_keep`` capacity, so
        the choice depends on the capacities in effect: presize/warmup/
        score_batches grow them to a stream-wide FIXPOINT first
        (_grow_for_stream), making decisions order-independent for a
        planned stream.  Bare repeated ``score_batch`` calls (no presize)
        still grow capacities batch by batch, so an identical batch seen
        later in such a sequence can legitimately pick a different
        variant (advisor r4)."""
        if self._band_mode == "on" and band is not None:
            return "band"
        if self._compaction == "on" and runs is not None:
            return "compact"
        n = int(self._mz_host.size)
        est = {"plain": 14.0 * n}
        if runs is not None and self._compaction != "off":
            # charge the PADDED capacity, like the band branch: dispatch
            # pads every compact batch to the sticky 64k-rounded stream
            # max, and padded slots gather+scatter all the same
            cap_c = max(-(-max(runs[2], 1) // (1 << 16)) * (1 << 16),
                        self._n_keep)
            est["compact"] = 37.0 * min(cap_c, n)
        if band is not None and self._band_mode != "off":
            cap = self._band_bucket(band[1])
            if cap < n:
                est["band"] = 14.0 * cap
        return min(est, key=est.get)

    def _maybe_fuse(self, variant: str, wc: int, gc_eff: int, k: int) -> str:
        """Fused-kernel routing (ISSUE 18).  'on' forces the fused variant
        from ANY cost-model choice (tests/sentinel: interpret-mode off-TPU);
        'auto' upgrades only the plain variant — band/compact reshape the
        resident cube before scatter, which the fused kernel's unblocked
        band staging does not model — and only on a real TPU where the
        (wc, cols_p, pt) plan fits the kernel's VMEM budget (fused_fit).
        Hotspot preprocessing needs materialized images, so it excludes
        fusion entirely."""
        if self._fused_mode == "off" or self._common["do_preprocessing"]:
            return variant
        if self._fused_mode == "on":
            return "fused"
        if (variant == "plain" and jax.default_backend() == "tpu"
                and fused_fit(wc, wc // max(k, 1), self._n_pix_b, gc_eff)):
            return "fused"
        return variant

    def _in_f32(self):
        """f32 view of the (possibly compacted) resident intensity cube for
        the probe/export paths that bypass the scoring jits.  Materialized
        once, lazily — probe-only (COMPILE_SURFACE: expand_cube_jnp); the
        production jits expand in-graph instead."""
        if self._cube_dtype == "f32":
            return self._in_s
        if not hasattr(self, "_in_f32_cache"):
            self._in_f32_cache = jax.jit(expand_cube_jnp)(
                self._in_s, self._scales)
        return self._in_f32_cache

    def _grow_compact_capacity(self, runs) -> None:
        # clamp at the resident peak count: padded slots still gather and
        # scatter, so a 64k rounding floor on a tiny dataset would cost
        # more than the plain path
        cap = max(1, int(self._px_s.shape[0]))
        rnd = 1 << 16
        want = min(-(-max(runs[2], 1) // rnd) * rnd, cap)
        self._n_keep = max(self._n_keep, want)
        self._r_pad = max(
            self._r_pad, -(-max(runs[0].size, 1) // 4096) * 4096)

    def _flat_call(self, table: IsotopePatternTable, flat_plan=None):
        """(use_compact, device_args, statics) for one flat-path batch —
        the ONE place the production call shape is decided; _dispatch and
        probe_phases both consume it, so probes can't drift."""
        k = table.max_peaks
        if flat_plan is None:
            flat_plan = self._flat_plan(table)
        (_grid, _r_lo, _r_hi, ints_p, nv_p, chunks, pos, runs,
         b_eff, band) = flat_plan
        starts, r_lo_loc, r_hi_loc, inv, gc_width, order = chunks
        # per-ion side inputs follow the plan's ion sort; the fused fn
        # un-permutes the metric rows with ``inv``
        ints_p = ints_p[order]
        nv_p = nv_p[order]
        # the tail executable keeps its own sticky band width: sharing
        # the full-size band would blow the small batch's matmul cost
        if b_eff == self.batch:
            self._gc_width = max(self._gc_width, gc_width)
            gc_eff = self._gc_width
        else:
            self._gc_tail = max(self._gc_tail, gc_width)
            gc_eff = self._gc_tail
        variant = self._maybe_fuse(
            self._variant_for(runs, band), r_lo_loc.shape[1], gc_eff, k)
        # explicit async device_put: the transfers overlap device compute
        # of previously enqueued batches instead of blocking dispatch
        if variant == "band":
            b_lo, b_w = band
            n = int(self._mz_host.size)
            cap = min(self._band_bucket(b_w), n)
            # clamp so the static-width slice stays inside the resident
            # array; bounds below w_start are batch-padding zeros — clip
            # them to 0, which mirrors the full path exactly (their grid
            # entry ranks below every real window)
            w_start = max(0, min(b_lo, n - cap))
            pos_b = np.clip(pos - w_start, 0, cap).astype(np.int32)
            args = [jax.device_put(a) for a in (
                np.int32(w_start), pos_b,
                starts, r_lo_loc, r_hi_loc, inv, ints_p, nv_p)]
            statics = dict(w_cap=cap, gc_width=gc_eff, b=b_eff, k=k)
        elif variant == "compact":
            run_pos, run_delta, n_b, pos_b = runs
            self._grow_compact_capacity(runs)
            rp = np.full(self._r_pad, self._n_keep, np.int32)
            rp[: run_pos.size] = run_pos
            rd = np.zeros(self._r_pad, np.int32)
            rd[: run_delta.size] = run_delta
            args = [jax.device_put(a) for a in (
                rp, rd, np.int32(n_b), pos_b,
                starts, r_lo_loc, r_hi_loc, inv, ints_p, nv_p)]
            statics = dict(n_keep=self._n_keep, gc_width=gc_eff,
                           b=b_eff, k=k)
        else:
            args = [jax.device_put(a) for a in (
                pos, starts, r_lo_loc, r_hi_loc, inv, ints_p, nv_p)]
            statics = dict(gc_width=gc_eff, b=b_eff, k=k)
        if self._n_real is not None:
            # the lattice's traced real-pixel scalar rides after n_valid
            args.append(jax.device_put(self._n_real))
        if self._scales is not None:
            # int8 cube: the per-tile dequant scales ride last; off-lattice
            # they still need the n_real slot filled (None traces as an
            # empty pytree) so positions match the fn signatures
            if self._n_real is None:
                args.append(None)
            args.append(self._scales)
        if self._n_real is not None:
            shape_buckets.record_spec(
                self._bucket_spec(variant, args, statics))
        return variant, args, statics

    def _bucket_spec(self, variant: str, args, statics) -> dict:
        """The BucketSpec of the executable this call shape resolves to
        (ops/buckets.py): everything the AOT primer needs to rebuild the
        byte-identical program — variant, metric geometry, statics, and
        the argument shapes (read off the actual arrays, so the spec can
        never drift from what dispatched)."""
        pos_ix = _VARIANTS[variant][3]
        rlo = args[pos_ix + 2]
        spec = {
            "kind": "flat", "variant": variant,
            "nrows": int(self._common["nrows"]),
            "ncols": int(self._common["ncols"]),
            "nlevels": int(self._common["nlevels"]),
            "do_preprocessing": bool(self._common["do_preprocessing"]),
            "q": float(self._common["q"]),
            "n_resident": int(self._px_s.shape[0]),
            "b": int(statics["b"]), "k": int(statics["k"]),
            "gc_width": int(statics["gc_width"]),
            "n_keep": int(statics.get("n_keep", 0)),
            "r_pad": (int(args[0].shape[0]) if variant == "compact" else 0),
            "w_cap": int(statics.get("w_cap", 0)),
            "g": int(args[pos_ix].shape[0]),
            "c": int(rlo.shape[0]), "wc": int(rlo.shape[1]),
            "devices": 1,
        }
        # recorded only when compacted: legacy f32 spec strings (and the
        # primed cache keys built from them) stay byte-identical
        if self._cube_dtype != "f32":
            spec["cube_dtype"] = self._cube_dtype
        return spec

    def _dispatch(self, table: IsotopePatternTable, flat_plan=None):
        """Async: enqueue one padded batch on device, return (device_out, n)."""
        n, b, k = table.n_ions, self.batch, table.max_peaks
        if self.mz_chunk:
            grid, r_lo, r_hi, ints_p, nv_p = self._padded_windows(table)
            starts, r_lo_loc, r_hi_loc, inv, gc_width = window_chunks(
                r_lo, r_hi, self.mz_chunk)
            args = [jax.device_put(a) for a in (
                grid, starts, r_lo_loc, r_hi_loc, inv, ints_p, nv_p)]
            out = self._fn(self._mz_q, self._ints, *args,
                           gc_width=gc_width, b=b, k=k)
        else:
            variant, args, statics = self._flat_call(table, flat_plan)
            fn = getattr(self, _VARIANTS[variant][0])
            out = fn(self._px_s, self._in_s, *args, **statics)
        return out, n

    def probe_phases(self, table: IsotopePatternTable):
        """Per-phase dispatch hooks for profiling (VERDICT r3 item 5):
        ``(phases, info)`` where ``phases`` maps phase name to a zero-arg
        callable enqueueing that phase on device — with EXACTLY the
        arrays, static shapes, and plain/compaction variant score_batch
        would use — and returning the device output.  ``info`` carries the
        plan shape for logging.  Callers time the callables (forcing a
        readback); nothing here reaches into plan-tuple internals."""
        if self.mz_chunk:
            return {"fused_full": lambda: self._dispatch(table)[0]}, {
                "path": "mz_chunk"}
        plan = self._flat_plan(table)
        variant, fargs, statics = self._flat_call(table, plan)
        fn_attr, ext_base, n_ext, pos_ix = _VARIANTS[variant]
        fn = getattr(self, fn_attr)
        phases = {"fused_full": lambda: fn(
            self._px_s, self._in_s, *fargs, **statics)}
        # the sub-phase probes index the tail below (n_valid / theor_ints /
        # n_real) — strip the int8 scales (and their off-lattice n_real
        # placeholder) first, and give them the expanded f32 cube the
        # unfused probe fns expect
        args = list(fargs)
        if self._scales is not None:
            args = args[:-1] if self._n_real is not None else args[:-2]
        in_probe = self._in_f32()
        img_cfg = self.ds_config.image_generation
        ext_statics = {kk: v for kk, v in statics.items()
                       if kk in ("n_keep", "w_cap", "gc_width")}
        ext_fn = jax.jit(named_partial(
            ext_base, n_pixels=self._n_pix_b, **ext_statics))
        # extraction args = everything before (theor_ints, n_valid[,
        # n_real]); the trailing ``inv`` is the ION un-permutation consumed
        # by the fused fn's metric output, not by extraction — probes keep
        # the plan's ion-sorted order (side inputs below permuted to match)
        ext_args = list(args[: n_ext - 1]) + [None]
        phases["extract"] = lambda: ext_fn(
            self._px_s, in_probe, *ext_args)
        # the metric probes run on the PRODUCTION image block: the padded
        # (b, k, P_bucket) lattice grid with the traced real-pixel count
        # masking the centering, exactly like the fused graph
        imgs = phases["extract"]().reshape(statics["b"], statics["k"], -1)
        if self._n_real is not None:
            n_real_d, nv_p, ints_p = args[-1], args[-2], args[-3]
        else:
            n_real_d, nv_p, ints_p = None, args[-1], args[-2]
        valid_d = jax.device_put(
            # smlint: host-sync-ok[probe-only fetch of the tiny n_valid vector; probes time phases, not dispatch]
            np.arange(statics["k"])[None, :] < np.asarray(nv_p)[:, None])
        # the metric probes mirror the PRODUCTION route exactly
        # (batch_metrics): one fused moments pass feeds chaos thresholds
        # and the correlation/pattern epilogues — timing the old separate
        # XLA reductions here would attribute phantom cost the fused
        # graph no longer pays (advisor r5)
        from ..ops.moments_pallas import batch_moments

        mom_fn = jax.jit(batch_moments)
        phases["moments"] = lambda: mom_fn(imgs, n_real_d)
        _sums, _normsq, _dots, _vmax, _nn = mom_fn(imgs, n_real_d)
        chaos_fn = jax.jit(named_partial(
            measure_of_chaos_batch, nrows=self._nrows_b, ncols=self.ds.ncols,
            nlevels=img_cfg.nlevels))
        phases["chaos"] = lambda: chaos_fn(
            imgs[:, 0, :], vmax=_vmax, n_notnull=_nn)
        corr_fn = jax.jit(correlation_from_moments)
        phases["correlation"] = lambda: corr_fn(
            _normsq, _dots, ints_p, valid_d)
        pat_fn = jax.jit(isotope_pattern_match_batch)
        phases["pattern"] = lambda: pat_fn(_sums, ints_p, valid_d)
        info = dict(path="flat", variant=variant, **statics,
                    resident_peaks=int(self._px_s.shape[0]),
                    grid_bins=int(args[pos_ix].shape[0]))
        return phases, info

    def score_batch(self, table: IsotopePatternTable) -> np.ndarray:
        out, n = self._dispatch(table)
        # smlint: host-sync-ok[single-batch API; the caller asked for the result — pipelined callers use score_batches]
        return np.asarray(out)[:n].astype(np.float64)

    def extract_ion_images(self, table: IsotopePatternTable) -> np.ndarray:
        """(n_ions, K, n_pix) de-quantized ion images from the DEVICE cube —
        the annotated-subset image export no longer re-extracts on CPU
        (VERDICT r1 item 9).  Bit-identical to the numpy path (shared
        integer grids).  Compiles one extraction-only executable per
        backend, padded to the scoring batch shape."""
        n = table.n_ions
        b = self.batch
        if n > b:
            # batch internally: annotated subsets can exceed formula_batch
            from .msm_basic import _slice_table

            return np.concatenate([
                self.extract_ion_images(_slice_table(table, s, min(s + b, n)))
                for s in range(0, n, b)
            ])
        k = table.max_peaks
        grid, r_lo, r_hi, _ints, _nv = self._padded_windows(table)
        if self.mz_chunk:
            if not hasattr(self, "_extract_fn"):
                self._extract_fn = jax.jit(extract_images)
            imgs = self._extract_fn(
                self._mz_q, self._ints, jax.device_put(grid),
                jax.device_put(r_lo), jax.device_put(r_hi))
        else:
            if not hasattr(self, "_extract_fn"):
                # bucketed extraction grid (lattice): the host-side slice
                # below takes the exact-pixel prefix, so the export is
                # bit-identical while the executable is shared per bucket
                self._extract_fn = jax.jit(
                    named_partial(extract_images_flat,
                                  n_pixels=self._n_pix_b))
            pos = flat_bound_ranks(self._mz_host, grid)
            imgs = self._extract_fn(
                self._px_s, self._in_f32(), jax.device_put(pos),
                jax.device_put(r_lo), jax.device_put(r_hi))
        # smlint: host-sync-ok[image EXPORT; the annotated-subset fetch to host is the product of this method]
        imgs = np.array(imgs).reshape(b, k, -1)[:n, :, : self.ds.n_pixels]
        imgs /= np.float32(self.int_scale)  # exact power-of-two division
        # zero out padded isotope peaks (window [0,0) is empty anyway, but
        # keep the contract explicit)
        valid = np.arange(k)[None, :] < table.n_valid[:, None]
        imgs[~valid] = 0.0
        return imgs

    def presize(self, tables) -> None:
        """Grow the sticky static shapes to cover ``tables`` WITHOUT scoring.

        score_batches pre-sizes its own stream, but a checkpointed search
        calls score_batches once per batch GROUP — a later group with a
        wider window-chunk span would otherwise grow gc_width mid-search
        and recompile (~15 s on a tunneled TPU).  The orchestrator calls
        this once with every slice before the group loop."""
        if self.mz_chunk:
            return
        self._grow_for_stream([self._flat_plan(t) for t in tables])

    def _grow_for_stream(self, plans) -> None:
        """Grow the sticky capacities over ``plans`` to a FIXPOINT.

        One pass is order-dependent: growing ``_n_keep`` raises the compact
        estimate, which can flip a later identical batch's variant choice —
        and a batch warmed as one variant could then dispatch as another,
        recompiling mid-stream (advisor r4).  Capacities are monotone and
        bounded, so repeating the pass until nothing grows terminates (2
        passes in practice) and leaves every decision consistent with the
        final capacities — dispatch re-evaluates against exactly these."""
        while True:
            before = (self._gc_width, self._gc_tail, self._n_keep,
                      self._r_pad)
            for plan in plans:
                self._grow_from_plan(plan)
            if before == (self._gc_width, self._gc_tail, self._n_keep,
                          self._r_pad):
                return

    def _grow_from_plan(self, plan) -> None:
        if plan[8] == self.batch:
            self._gc_width = max(self._gc_width, plan[5][4])
        else:
            self._gc_tail = max(self._gc_tail, plan[5][4])
        if self._variant_for(plan[7], plan[9]) == "compact":
            self._grow_compact_capacity(plan[7])

    def warmup(self, tables) -> None:
        """Compile every executable ``tables`` will use, scoring ONE
        representative batch per variant (plain vs peak-compaction — the
        auto rule can pick either per batch).  Pre-sizes sticky static
        shapes first so the warmed executables serve the whole stream.

        Warm-start trim (ISSUE 3 satellite): executing the representative
        batches is only there to force compile+cache-load, and at 262k
        pixels those executions are real seconds.  After a successful
        warmup a MANIFEST of the warmed executable kinds is written next to
        the persistent XLA cache; when a later process's warmup computes the
        SAME kinds under the same environment key and the cache holds
        entries, the executions are skipped (``last_warmup_skipped``) — the
        first real batch loads each executable from the cache instead."""
        tables = list(tables)
        self.last_warmup_skipped = False
        if self.mz_chunk:
            if tables:
                self.score_batch(tables[0])
            return
        plans = [self._flat_plan(t) for t in tables]
        self._grow_for_stream(plans)
        reps, seen = [], set()
        for t, plan in zip(tables, plans):
            b_eff = plan[8]
            gc_eff = self._gc_width if b_eff == self.batch else self._gc_tail
            variant = self._maybe_fuse(
                self._variant_for(plan[7], plan[9]),
                plan[5][1].shape[1], gc_eff, t.max_peaks)
            # each band w_cap bucket is its own executable
            bucket = (self._band_bucket(plan[9][1])
                      if variant == "band" else 0)
            kind = (variant, b_eff, bucket)
            if kind not in seen:
                seen.add(kind)
                reps.append((t, plan))
        manifest_key = self._warmup_manifest_key(sorted(seen))
        if self._warmup_manifest_hit(manifest_key):
            self.last_warmup_skipped = True
            _WARMUP_CACHE_EVENTS["hit"] += 1
            logger.info(
                "warmup skipped: persistent cache manifest covers all %d "
                "executable kinds", len(seen))
            return
        _WARMUP_CACHE_EVENTS["miss"] += 1
        fetch_scored_batches([self._dispatch(t, plan) for t, plan in reps])
        self._write_warmup_manifest(manifest_key)

    def _warmup_manifest_key(self, kinds) -> str | None:
        """Environment + stream identity for the warmup manifest: the
        executable kinds, sticky capacities, BUCKET ids, and the
        jax/backend versions (the same components that key the persistent
        cache, minus the HLO itself).

        Keyed on bucket ids, not raw shapes (ISSUE 13 satellite): the
        pixel geometry enters as (row_bucket, ncols) and the resident
        count as its lattice capacity (``_mz_host`` is already padded to
        it), so a cache primed — or warmed by ANY dataset size in the
        bucket — is recognized as warm for every other size in it, with
        no redundant representative-batch executions."""
        if self._compile_cache is None:
            return None
        import hashlib

        dev = jax.devices()[0]
        blob = repr((
            sorted(kinds),
            (self._gc_width, self._gc_tail, self._n_keep, self._r_pad),
            (self._nrows_b, self.ds.ncols, int(self._mz_host.size),
             self.batch, bool(self._buckets)),
            (self.ds_config.image_generation.nlevels,
             self.ds_config.image_generation.do_preprocessing),
            # ISSUE 18 knobs change the compiled program family
            (self._cube_dtype, self._fused_mode),
            (jax.__version__, dev.platform, str(dev.device_kind)),
        ))
        return hashlib.sha256(blob.encode()).hexdigest()

    def _manifest_path(self):
        return self._compile_cache / "warmup_manifest.json"

    def _warmup_manifest_hit(self, key: str | None) -> bool:
        if key is None:
            return False
        import json

        path = self._manifest_path()
        try:
            recorded = json.loads(path.read_text())
        except (OSError, ValueError):
            return False
        if key not in recorded.get("keys", []):
            return False
        # the manifest promises the cache HELD these executables when it was
        # written; an emptied cache dir (eviction, fresh checkout) voids it.
        # Exception: when the WRITE itself observed zero entries (XLA skips
        # persisting compiles under jax_persistent_cache_min_compile_time —
        # warm-process compiles of tiny fixtures finish in <1 s), the
        # executables were never going to be on disk, and skipping the
        # warmup executions is still correct: re-compiling them is exactly
        # as cheap as it was when the manifest was written.
        cache_entries = self._cache_entry_count()
        recorded_entries = recorded.get("entries", {}).get(key)
        if recorded_entries == 0:
            return True
        return cache_entries > 0

    def _cache_entry_count(self) -> int:
        return sum(
            1 for p in self._compile_cache.glob("*")
            if p.is_file() and not p.name.startswith(".")
            and p.suffix not in (".lock", ".tmp", ".json"))

    def _write_warmup_manifest(self, key: str | None) -> None:
        if key is None:
            return
        import json
        import os

        path = self._manifest_path()
        try:
            recorded = json.loads(path.read_text())
        except (OSError, ValueError):
            recorded = {"keys": []}
        if key in recorded["keys"]:
            return
        recorded["keys"] = (recorded["keys"] + [key])[-64:]  # bounded
        # entry count at write time: 0 records that XLA never persisted
        # these (too-fast compiles), so a later hit must not demand entries
        entries = dict(recorded.get("entries", {}))
        entries[key] = self._cache_entry_count()
        recorded["entries"] = {k: v for k, v in entries.items()
                               if k in recorded["keys"]}
        tmp = path.with_name(path.name + ".tmp")
        try:
            path.parent.mkdir(parents=True, exist_ok=True)
            tmp.write_text(json.dumps(recorded))
            os.replace(tmp, path)
        except OSError:
            logger.warning("could not write warmup manifest %s", path,
                           exc_info=True)

    def score_batches(self, tables, cancel=None) -> list[np.ndarray]:
        """Pipelined scoring: enqueue every batch before syncing any result
        (JAX dispatch is async, so device compute of all batches overlaps the
        ~0.3 ms/batch host prep), then fetch all results concurrently.
        ``cancel`` (utils/cancel.CancelToken) is checked once before the
        group enqueues — the device pipeline is all-or-nothing, so the
        cooperative boundary is the checkpoint group."""
        tables = list(tables)
        if cancel is not None:
            cancel.check("score_batches")
        if self.mz_chunk:
            pending = [self._enqueue_traced(t) for t in tables]
            with tracing.span("device_sync", batches=len(pending)):
                return fetch_scored_batches(pending)
        # plan every batch up front: pre-sizes the static shapes (band width,
        # compaction capacities) to the stream's max so ONE executable serves
        # every batch (a mid-stream growth would recompile, ~15 s through a
        # tunneled TPU), and each plan is reused by its dispatch
        plans = [self._flat_plan(t) for t in tables]
        self._grow_for_stream(plans)
        pending = [self._enqueue_traced(t, plan)
                   for t, plan in zip(tables, plans)]
        with tracing.span("device_sync", batches=len(pending)):
            return fetch_scored_batches(pending)

    def _enqueue_traced(self, table, plan=None):
        """One async device dispatch, wrapped in a per-batch scoring span.
        The span measures ENQUEUE time (dispatch is async; device compute
        overlaps the stream and is settled by the device_sync span)."""
        dev_attr = ({"device": int(self.device.id)}
                    if self.device is not None else {})
        with tracing.span("score_batch", backend="jax_tpu",
                          ions=int(table.n_ions), enqueue=True, **dev_attr):
            return self._dispatch(table, plan) if plan is not None \
                else self._dispatch(table)
