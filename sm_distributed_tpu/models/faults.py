"""Device-fault taxonomy: one classification for every backend exception.

ISSUE 14 tentpole, layer 1.  Before this module the engine had THREE
uncoordinated opinions about a device exception:

- ``models/oom.py::is_oom_error`` recognized memory exhaustion (a *sizing*
  signal — batch backoff, never a health verdict);
- the circuit breaker (``models/breaker.py``) counted every non-OOM device
  error toward its consecutive-failure threshold — including known-
  transient collective timeouts that the next attempt would survive;
- the device pool had no opinion at all: a chip whose hardware died kept
  getting re-leased forever, because nothing between the scoring seam and
  the pool carried the verdict.

This module is the single classifier (the GSPMD pod-scale framing,
arXiv:2105.04663: device health is *pool state*, fed by classified
faults).  Every backend exception maps to exactly one kind:

``oom``
    Memory exhaustion (``models/oom.py`` is the authority).  A sizing
    signal: the scoring batch halves and rescores in place.  NEVER a
    device fault — no breaker count, no quarantine.
``transient``
    Known-recoverable runtime hiccups: collective/DCN timeouts,
    ``DEADLINE_EXCEEDED`` / ``UNAVAILABLE`` / ``ABORTED`` status codes,
    dying tunnels, connection resets.  The attempt fails into the normal
    retry policy (same chip, exponential backoff) — no breaker count;
    the chip is marked *suspect* and quarantined only if transients keep
    repeating (``service.health_fault_quarantine``).
``sticky``
    Everything else at the device seam — ``INTERNAL``/``DATA_LOSS`` XLA
    status, launch failures, wedged cores.  The chip (or, for a sharded
    lease, the probe-attributed culprit) is **quarantined** out of the
    device pool (``service/health.py``) and the per-chip breaker counts
    the failure, so the retry re-leases *healthy* chips instead of
    degrading the whole process to numpy.

The health tracker subscribes through :func:`set_fault_listener` (the
same producer-side pattern as breaker/oom ``attach_metrics``), so this
module never imports the service layer.  ``sm_device_faults_total{kind=}``
rides the usual attach seam; docs/RECOVERY.md "Device faults" carries the
taxonomy table.
"""

from __future__ import annotations

import threading

from ..utils import tracing
from ..utils.failpoints import register_failpoint
from ..utils.logger import logger
from . import oom

FAULT_OOM = "oom"
FAULT_TRANSIENT = "transient"
FAULT_STICKY = "sticky"

# The injectable chip-fault seam (fired in MSMBasicSearch._score_group next
# to backend.device_error): the raised exception CLASS selects the
# taxonomy — raise:ConnectionError / raise:TimeoutError inject a transient,
# raise:RuntimeError (the default classification) a sticky chip death, and
# raise:MemoryError still lands in the OOM sizing path.
FP_CHIP_FAULT = register_failpoint(
    "backend.chip_fault",
    "inside a device score_batches call — the classified chip-fault seam "
    "(models/faults.py): ConnectionError/TimeoutError = transient (retry "
    "same chip, no quarantine), other exceptions = sticky (chip "
    "quarantined out of the device pool, per-chip breaker count)")

# Status texts that mark an exception as KNOWN-transient.  The XLA client
# surfaces gRPC/absl status codes in the message text (the same reason
# oom.is_oom_error is string-based: exception classes moved across jaxlib
# versions, status texts did not).
_TRANSIENT_MARKERS = (
    "deadline_exceeded",
    "deadline exceeded",
    "unavailable",
    "aborted",
    "cancelled by peer",
    "collective",            # collective timeout / all-reduce stall
    "all-reduce",
    "all_reduce",
    "tunnel",                # dying proxy/tunnel (the bench warmup class)
    "connection reset",
    "broken pipe",
    "temporarily unavailable",
    "too many requests",
)


def classify(exc: BaseException) -> str:
    """Map one backend exception to its fault kind.  OOM is checked FIRST
    (``models/oom.py`` stays the single memory-exhaustion authority, so
    the PR 10 contract — OOM is never a device fault — cannot regress);
    then the known-transient markers; everything else is sticky."""
    if oom.is_oom_error(exc):
        return FAULT_OOM
    if isinstance(exc, (TimeoutError, ConnectionError)):
        return FAULT_TRANSIENT
    text = str(exc).lower()
    if any(m in text for m in _TRANSIENT_MARKERS):
        return FAULT_TRANSIENT
    return FAULT_STICKY


# ------------------------------------------------------- listener + metrics
_lock = threading.Lock()
_listener = None                       # the service's HealthTracker
_metrics = None


def set_fault_listener(listener) -> None:
    """Subscribe a health tracker (``service/health.py``): it receives
    every classified non-OOM device fault as ``report_fault(devices,
    kind, error)`` and every clean device group as ``report_ok(devices)``.
    One listener per process (last registration wins — the live
    scheduler's pool)."""
    global _listener
    with _lock:
        _listener = listener


def clear_fault_listener(listener=None) -> None:
    """Detach (tests / service shutdown).  With ``listener`` given, only
    detaches when it is still the registered one — a newer scheduler's
    registration survives an older service's teardown."""
    global _listener
    with _lock:
        if listener is None or _listener is listener:
            _listener = None


def report_device_fault(devices, kind: str, error: BaseException | str) -> None:
    """A classified device fault at the scoring seam.  ``devices`` is the
    job's lease chip tuple (None for un-leased/offline runs — nothing to
    attribute then).  Dispatches to the health listener, exports
    ``sm_device_faults_total{kind=}``, and stamps the job trace."""
    err = str(error)
    tracing.event("device_fault", kind=kind, error=err[:300],
                  **({"devices": [int(d) for d in devices]}
                     if devices else {}))
    m = _metrics
    if m is not None:
        m.counter("sm_device_faults_total",
                  "Classified device faults at the scoring seam, by kind",
                  ("kind",)).labels(kind=kind).inc()
    with _lock:
        listener = _listener
    if listener is None or not devices:
        return
    try:
        listener.report_fault(tuple(int(d) for d in devices), kind, err)
    except Exception:
        logger.warning("device-fault listener %r failed", listener,
                       exc_info=True)


def report_device_ok(devices) -> None:
    """A clean device scoring group: clears the lease chips' suspect
    state/fault counters (quarantine is only undone by a re-probe)."""
    with _lock:
        listener = _listener
    if listener is None or not devices:
        return
    try:
        listener.report_ok(tuple(int(d) for d in devices))
    except Exception:
        logger.warning("device-fault listener %r failed", listener,
                       exc_info=True)


def attach_metrics(registry) -> None:
    """Export ``sm_device_faults_total{kind=}`` through a service
    ``MetricsRegistry`` (same attach pattern as breaker/oom)."""
    global _metrics
    with _lock:
        _metrics = registry
    registry.counter("sm_device_faults_total",
                     "Classified device faults at the scoring seam, by kind",
                     ("kind",))


def reset() -> None:
    """Detach listener + metrics (tests)."""
    global _listener, _metrics
    with _lock:
        _listener = None
        _metrics = None
