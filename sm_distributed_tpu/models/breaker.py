"""Device-backend circuit breaker (ISSUE 4 degradation layer).

A flaky device backend — preempted TPU, dying tunnel, XLA launch failures —
used to be retried forever by the scheduler's failure policy, burning every
attempt of every job on the same broken path.  The breaker wraps the device
scoring seam in ``MSMBasicSearch._score_and_rank``:

- **closed**: device scoring as normal; each cleanly scored group counts as
  a success and resets the consecutive-error count;
- **open**: after ``service.breaker_threshold`` consecutive device errors.
  Jobs score on the numpy oracle at ``service.breaker_degraded_batch``
  instead (metrics are backend-independent, so results are bit-identical to
  a healthy numpy run) — degraded but correct beats dead;
- **half-open**: once ``service.breaker_cooldown_s`` has elapsed, the next
  job's device build is allowed through as a probe.  A clean group closes
  the breaker; another device error re-opens it and restarts the cooldown.

The breaker is a process-global singleton (one device per process — the
scheduler's TPU token already serializes device phases), shared across the
service's jobs so one job's failures protect the next.
"""

from __future__ import annotations

import threading
import time

from ..utils import tracing
from ..utils.logger import logger

STATE_CLOSED = "closed"
STATE_OPEN = "open"
STATE_HALF_OPEN = "half_open"
_STATE_CODE = {STATE_CLOSED: 0, STATE_HALF_OPEN: 1, STATE_OPEN: 2}


class CircuitBreaker:
    """Consecutive-failure breaker with half-open recovery probes."""

    # shared-state registry checked by the smlint guarded-by rule
    # (docs/ANALYSIS.md): these attrs may only be mutated under _lock
    _GUARDED_BY = {"_state": "_lock", "_failures": "_lock",
                   "_opened_at": "_lock", "transitions": "_lock"}

    def __init__(self, threshold: int = 3, cooldown_s: float = 30.0):
        self.threshold = int(threshold)
        self.cooldown_s = float(cooldown_s)
        self._lock = threading.Lock()
        self._state = STATE_CLOSED
        self._failures = 0
        self._opened_at = 0.0
        # (monotonic time, from, to) — bounded history for probes/tests
        self.transitions: list[tuple[float, str, str]] = []

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def _transition_locked(self, to: str) -> None:
        # callers hold self._lock (the _locked suffix is the guarded-by
        # rule's caller-holds-lock convention, docs/ANALYSIS.md)
        if self._state == to:
            return
        self.transitions.append((time.monotonic(), self._state, to))
        if len(self.transitions) > 256:
            del self.transitions[:-256]
        logger.warning("device breaker: %s -> %s (%d consecutive failures)",
                       self._state, to, self._failures)
        # trace/flight-recorder visibility (ISSUE 5): attached to the job
        # span that tripped it when one is ambient, ring-only otherwise
        tracing.event("breaker", from_state=self._state, to_state=to,
                      failures=self._failures)
        self._state = to
        _export_state(to)

    def allow_device(self) -> bool:
        """May the next job use the device backend?  In OPEN state this
        flips to HALF_OPEN once the cooldown has elapsed and admits that one
        caller as the recovery probe."""
        with self._lock:
            if self._state == STATE_CLOSED or self._state == STATE_HALF_OPEN:
                return True
            if time.monotonic() - self._opened_at >= self.cooldown_s:
                self._transition_locked(STATE_HALF_OPEN)
                return True
            return False

    def record_success(self) -> None:
        """A device scoring group completed cleanly."""
        with self._lock:
            self._failures = 0
            if self._state != STATE_CLOSED:
                self._transition_locked(STATE_CLOSED)

    def record_failure(self) -> bool:
        """A device error occurred; returns True when the breaker is now
        open (callers degrade to the numpy fallback)."""
        with self._lock:
            self._failures += 1
            if self._state == STATE_HALF_OPEN or (
                    self._state == STATE_CLOSED
                    and self._failures >= self.threshold):
                self._opened_at = time.monotonic()
                self._transition_locked(STATE_OPEN)
            elif self._state == STATE_OPEN:
                self._opened_at = time.monotonic()
            return self._state == STATE_OPEN

    def snapshot(self) -> dict:
        with self._lock:
            return {"state": self._state, "failures": self._failures,
                    "threshold": self.threshold,
                    "cooldown_s": self.cooldown_s}


# ------------------------------------------------------- process singleton
_lock = threading.Lock()
_breaker: CircuitBreaker | None = None
_metrics = None


def get_device_breaker(service_cfg=None) -> CircuitBreaker:
    """The process-global breaker.  ``service_cfg`` (a ``ServiceConfig``)
    refreshes the thresholds in place — the state machine is untouched, so
    a service and its jobs reading the same config always agree."""
    global _breaker
    with _lock:
        if _breaker is None:
            _breaker = CircuitBreaker()
        if service_cfg is not None:
            _breaker.threshold = int(service_cfg.breaker_threshold)
            _breaker.cooldown_s = float(service_cfg.breaker_cooldown_s)
        return _breaker


def reset_device_breaker() -> None:
    """Fresh breaker + detach metrics (tests)."""
    global _breaker, _metrics
    with _lock:
        _breaker = None
        _metrics = None


def _export_state(state: str) -> None:
    m = _metrics
    if m is None:
        return
    m.gauge("sm_breaker_state",
            "Device breaker state (0=closed, 1=half_open, 2=open)").set(
        _STATE_CODE[state])
    m.counter("sm_breaker_transitions_total",
              "Device breaker state transitions, by destination",
              ("to",)).labels(to=state).inc()


def attach_metrics(registry) -> None:
    """Export breaker state through a service ``MetricsRegistry``:
    ``sm_breaker_state`` gauge + ``sm_breaker_transitions_total{to=}`` and
    a degraded-scoring counter (incremented by the scoring seam)."""
    global _metrics
    with _lock:
        _metrics = registry
        b = _breaker
    registry.gauge("sm_breaker_state",
                   "Device breaker state (0=closed, 1=half_open, 2=open)").set(
        _STATE_CODE[b.state if b is not None else STATE_CLOSED])
    registry.counter("sm_breaker_transitions_total",
                     "Device breaker state transitions, by destination", ("to",))
    registry.counter("sm_breaker_degraded_total",
                     "Scoring runs degraded to the numpy fallback")


def record_degraded() -> None:
    m = _metrics
    if m is not None:
        m.counter("sm_breaker_degraded_total",
                  "Scoring runs degraded to the numpy fallback").inc()
