"""Device-backend circuit breaker (ISSUE 4 degradation layer; per-chip
labels since ISSUE 14).

A flaky device backend — preempted TPU, dying tunnel, XLA launch failures —
used to be retried forever by the scheduler's failure policy, burning every
attempt of every job on the same broken path.  The breaker wraps the device
scoring seam in ``MSMBasicSearch._score_and_rank``:

- **closed**: device scoring as normal; each cleanly scored group counts as
  a success and resets the consecutive-error count;
- **open**: after ``service.breaker_threshold`` consecutive device errors.
  Jobs score on the numpy oracle at ``service.breaker_degraded_batch``
  instead (metrics are backend-independent, so results are bit-identical to
  a healthy numpy run) — degraded but correct beats dead;
- **half-open**: once ``service.breaker_cooldown_s`` has elapsed, the next
  job's device build is allowed through as a probe.  A clean group closes
  the breaker; another device error re-opens it and restarts the cooldown.

**Per-chip labelling (ISSUE 14):** PR 4's breaker was a process-global
singleton — correct when one device served the whole process, but on the
multi-chip pool one sticky chip's failures opened the ONE breaker and
degraded every job on every healthy chip to numpy.  The singleton is now a
*registry* of breakers keyed per chip: a job holding a device-pool lease
gets a :class:`LeaseBreaker` view over its chips' breakers (a failure
counts on every leased chip, a success resets them, the device is allowed
only when every chip's breaker allows it), and ``sm_breaker_state`` /
``sm_breaker_transitions_total`` carry a ``device`` label.  Un-leased
callers (offline CLI, legacy tests) keep the old single-breaker semantics
under the ``"*"`` label.  Chip-level *quarantine* (``service/health.py``)
is the first line of defense — a sticky chip leaves the pool entirely —
and the per-chip breaker is the backstop beneath it: if every healthy
chip keeps failing too, jobs still degrade to the numpy oracle instead of
dying.
"""

from __future__ import annotations

import threading
import time

from ..utils import tracing
from ..utils.logger import logger

STATE_CLOSED = "closed"
STATE_OPEN = "open"
STATE_HALF_OPEN = "half_open"
_STATE_CODE = {STATE_CLOSED: 0, STATE_HALF_OPEN: 1, STATE_OPEN: 2}

# the un-leased / process-wide breaker key (old single-device semantics)
GLOBAL_LABEL = "*"


class CircuitBreaker:
    """Consecutive-failure breaker with half-open recovery probes."""

    # shared-state registry checked by the smlint guarded-by rule
    # (docs/ANALYSIS.md): these attrs may only be mutated under _lock
    _GUARDED_BY = {"_state": "_lock", "_failures": "_lock",
                   "_opened_at": "_lock", "transitions": "_lock"}

    def __init__(self, threshold: int = 3, cooldown_s: float = 30.0,
                 label: str = GLOBAL_LABEL):
        self.threshold = int(threshold)
        self.cooldown_s = float(cooldown_s)
        self.label = str(label)        # chip index, or "*" for un-leased
        self._lock = threading.Lock()
        self._state = STATE_CLOSED
        self._failures = 0
        self._opened_at = 0.0
        # (monotonic time, from, to) — bounded history for probes/tests
        self.transitions: list[tuple[float, str, str]] = []

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def _transition_locked(self, to: str) -> None:
        # callers hold self._lock (the _locked suffix is the guarded-by
        # rule's caller-holds-lock convention, docs/ANALYSIS.md)
        if self._state == to:
            return
        self.transitions.append((time.monotonic(), self._state, to))
        if len(self.transitions) > 256:
            del self.transitions[:-256]
        logger.warning("device breaker[%s]: %s -> %s (%d consecutive "
                       "failures)", self.label, self._state, to,
                       self._failures)
        # trace/flight-recorder visibility (ISSUE 5): attached to the job
        # span that tripped it when one is ambient, ring-only otherwise
        tracing.event("breaker", device=self.label, from_state=self._state,
                      to_state=to, failures=self._failures)
        self._state = to
        _export_state(to, self.label)

    def allow_device(self) -> bool:
        """May the next job use the device backend?  In OPEN state this
        flips to HALF_OPEN once the cooldown has elapsed and admits that one
        caller as the recovery probe."""
        with self._lock:
            if self._state == STATE_CLOSED or self._state == STATE_HALF_OPEN:
                return True
            if time.monotonic() - self._opened_at >= self.cooldown_s:
                self._transition_locked(STATE_HALF_OPEN)
                return True
            return False

    def record_success(self) -> None:
        """A device scoring group completed cleanly."""
        with self._lock:
            self._failures = 0
            if self._state != STATE_CLOSED:
                self._transition_locked(STATE_CLOSED)

    def record_failure(self) -> bool:
        """A device error occurred; returns True when the breaker is now
        open (callers degrade to the numpy fallback)."""
        with self._lock:
            self._failures += 1
            if self._state == STATE_HALF_OPEN or (
                    self._state == STATE_CLOSED
                    and self._failures >= self.threshold):
                self._opened_at = time.monotonic()
                self._transition_locked(STATE_OPEN)
            elif self._state == STATE_OPEN:
                self._opened_at = time.monotonic()
            return self._state == STATE_OPEN

    def snapshot(self) -> dict:
        with self._lock:
            return {"state": self._state, "failures": self._failures,
                    "threshold": self.threshold,
                    "cooldown_s": self.cooldown_s}


class LeaseBreaker:
    """Per-chip breaker view over one device-pool lease (ISSUE 14).

    A failure at the scoring seam counts on EVERY leased chip's breaker
    (the seam cannot attribute deeper — the health probe does that), a
    clean group resets them all, and the device path is allowed only when
    every chip's breaker allows it.  One bad chip therefore opens only its
    own breaker; the next lease over different chips scores on the device
    as if nothing happened."""

    def __init__(self, breakers: list[CircuitBreaker]):
        self._breakers = list(breakers)

    @property
    def state(self) -> str:
        # worst state across the lease: open > half_open > closed
        states = [b.state for b in self._breakers]
        for s in (STATE_OPEN, STATE_HALF_OPEN):
            if s in states:
                return s
        return STATE_CLOSED

    def allow_device(self) -> bool:
        # note: evaluated for every chip (no short-circuit), so each
        # open-past-cooldown breaker flips to its half-open probe together
        return all([b.allow_device() for b in self._breakers])

    def record_success(self) -> None:
        for b in self._breakers:
            b.record_success()

    def record_failure(self) -> bool:
        return any([b.record_failure() for b in self._breakers])

    def snapshot(self) -> dict:
        return {b.label: b.snapshot() for b in self._breakers}


# ------------------------------------------------------- process registry
_lock = threading.Lock()
_breakers: dict[str, CircuitBreaker] = {}
_metrics = None


def _breaker_locked(label: str) -> CircuitBreaker:
    b = _breakers.get(label)
    if b is None:
        b = _breakers[label] = CircuitBreaker(label=label)
    return b


def get_device_breaker(service_cfg=None, devices=None):
    """The process-global breaker for a device scope.  ``devices`` (a
    device-pool lease's chip tuple) selects per-chip breakers wrapped in a
    :class:`LeaseBreaker`; ``None`` keeps the old un-leased singleton
    (label ``"*"``).  ``service_cfg`` (a ``ServiceConfig``) refreshes the
    thresholds in place — the state machines are untouched, so a service
    and its jobs reading the same config always agree."""
    labels = ([GLOBAL_LABEL] if not devices
              else [str(int(d)) for d in devices])
    with _lock:
        picked = [_breaker_locked(lb) for lb in labels]
        if service_cfg is not None:
            for b in picked:
                b.threshold = int(service_cfg.breaker_threshold)
                b.cooldown_s = float(service_cfg.breaker_cooldown_s)
    if not devices:
        return picked[0]
    return LeaseBreaker(picked)


def breaker_for(label) -> CircuitBreaker | None:
    """The per-chip breaker for one label (chip index or ``"*"``), or
    None if this process never touched it — test/harness introspection."""
    with _lock:
        return _breakers.get(str(label))


def breakers_snapshot() -> dict:
    """{label: breaker snapshot} of every breaker this process has touched
    (the ``GET /debug/devices`` body's breaker half)."""
    with _lock:
        picked = list(_breakers.values())
    return {b.label: b.snapshot() for b in picked}


def reset_device_breaker() -> None:
    """Fresh breakers + detach metrics (tests)."""
    global _metrics
    with _lock:
        _breakers.clear()
        _metrics = None


def _export_state(state: str, label: str) -> None:
    m = _metrics
    if m is None:
        return
    m.gauge("sm_breaker_state",
            "Device breaker state (0=closed, 1=half_open, 2=open), per "
            "chip ('*' = the un-leased process breaker)",
            ("device",)).labels(device=label).set(_STATE_CODE[state])
    m.counter("sm_breaker_transitions_total",
              "Device breaker state transitions, by chip and destination",
              ("device", "to")).labels(device=label, to=state).inc()


def attach_metrics(registry) -> None:
    """Export breaker state through a service ``MetricsRegistry``:
    ``sm_breaker_state{device=}`` gauge + ``sm_breaker_transitions_total
    {device=,to=}`` and a degraded-scoring counter (incremented by the
    scoring seam)."""
    global _metrics
    with _lock:
        _metrics = registry
        existing = list(_breakers.values())
    g = registry.gauge(
        "sm_breaker_state",
        "Device breaker state (0=closed, 1=half_open, 2=open), per chip "
        "('*' = the un-leased process breaker)", ("device",))
    for b in existing or [CircuitBreaker()]:
        g.labels(device=b.label).set(_STATE_CODE[b.state])
    registry.counter(
        "sm_breaker_transitions_total",
        "Device breaker state transitions, by chip and destination",
        ("device", "to"))
    registry.counter("sm_breaker_degraded_total",
                     "Scoring runs degraded to the numpy fallback")


def record_degraded() -> None:
    m = _metrics
    if m is not None:
        m.counter("sm_breaker_degraded_total",
                  "Scoring runs degraded to the numpy fallback").inc()
