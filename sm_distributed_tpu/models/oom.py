"""HBM-OOM classification and proven-safe batch memory (ISSUE 10).

An XLA ``RESOURCE_EXHAUSTED`` used to be indistinguishable from any other
device error: it fed the circuit breaker's consecutive-failure count, and
three of them degraded every following job to the numpy oracle — turning a
*sizing* problem (this dataset × this batch does not fit in HBM) into a
*health* verdict about a perfectly good chip.  This module gives the
scoring seam (``models/msm_basic.py::MSMBasicSearch._score_group``) the
vocabulary to treat OOM as what it is:

- :func:`is_oom_error` — recognizes the allocator's failure shapes
  (``XlaRuntimeError: RESOURCE_EXHAUSTED``, "out of memory" texts, and
  plain ``MemoryError`` — which the ``backend.device_error`` failpoint can
  inject deterministically);
- the **safe-batch registry** — after a backoff converges, the proven-safe
  batch size is recorded per :func:`shape_key` (dataset shape × backend ×
  device lease), so the NEXT job on the same shape starts at the size that
  fits instead of re-discovering the OOM; ``MSMBasicSearch`` consults it
  before building the backend and the checkpoint partition;
- ``sm_oom_*`` metrics through the same attach pattern as the breaker
  (``attach_metrics``; docs/OBSERVABILITY.md).

The registry is process-global plain state under one leaf lock — it is a
performance memo, not a correctness mechanism: losing it on restart only
costs one extra backoff cycle.
"""

from __future__ import annotations

import threading

from ..utils import tracing
from ..utils.logger import logger

# substrings that mark an exception as accelerator memory exhaustion; the
# XLA client raises XlaRuntimeError("RESOURCE_EXHAUSTED: Out of memory
# while trying to allocate ..."), older jaxlibs RuntimeError with the same
# text.  MemoryError is the host-side (and failpoint-injectable) shape.
_OOM_MARKERS = ("RESOURCE_EXHAUSTED", "Out of memory", "out of memory",
                "Resource exhausted")


def is_oom_error(exc: BaseException) -> bool:
    """Is this exception a memory-exhaustion signal (device or host)?
    Deliberately string-based for the XLA shapes: the concrete exception
    class moved across jaxlib versions, but the status text has not."""
    if isinstance(exc, MemoryError):
        return True
    text = str(exc)
    return any(m in text for m in _OOM_MARKERS)


def shape_key(n_pixels: int, backend: str, device_indices=None) -> str:
    """Registry key for a (dataset-shape, mesh) combination: what the
    HBM footprint of a scoring batch actually depends on.  ``None``
    device_indices = the config mesh over all local devices.

    The pixel count keys on its LATTICE BUCKET (ISSUE 13,
    ops/buckets.pixel_bucket): under the shape-bucket lattice every
    dataset size in a bucket scores through the same executables at the
    same scratch shapes, so a learned safe batch transfers across the
    whole bucket instead of being re-discovered per size."""
    from ..ops.buckets import pixel_bucket

    devs = ",".join(str(int(i)) for i in device_indices) \
        if device_indices else "*"
    return f"pxb{pixel_bucket(int(n_pixels))}|{backend}|dev[{devs}]"


class _GuardedRegistry:
    """The module singleton's state, lock-guarded (smlint guarded-by)."""

    _GUARDED_BY = {"_safe": "_lock", "_events": "_lock",
                   "_recoveries": "_lock"}

    def __init__(self):
        self._lock = threading.Lock()
        self._safe: dict[str, int] = {}
        self._events = 0              # OOM exceptions classified
        self._recoveries = 0          # backoffs that converged

    def record_event(self) -> None:
        with self._lock:
            self._events += 1

    def record_safe(self, key: str, batch: int) -> None:
        with self._lock:
            self._safe[key] = int(batch)
            self._recoveries += 1

    def safe_batch_for(self, key: str) -> int | None:
        with self._lock:
            return self._safe.get(key)

    def snapshot(self) -> dict:
        with self._lock:
            return {"events": self._events, "recoveries": self._recoveries,
                    "safe_batches": dict(self._safe)}

    def reset(self) -> None:
        with self._lock:
            self._safe.clear()
            self._events = 0
            self._recoveries = 0


_registry = _GuardedRegistry()
_metrics = None
_metrics_lock = threading.Lock()


def record_oom_event(where: str, error: str) -> None:
    """An OOM was classified at the scoring seam (before any retry)."""
    _registry.record_event()
    tracing.event("oom", where=where, error=error[:300])
    m = _metrics
    if m is not None:
        m.counter("sm_oom_events_total",
                  "Device/host memory-exhaustion errors classified at the "
                  "scoring seam").inc()


def record_safe_batch(key: str, batch: int) -> None:
    """A backoff converged: ``batch`` is proven to fit for ``key``; later
    jobs on the same shape start there."""
    _registry.record_safe(key, batch)
    logger.warning("oom: learned safe batch %d for %s", batch, key)
    tracing.event("oom_safe_batch", key=key, batch=int(batch))
    m = _metrics
    if m is not None:
        m.counter("sm_oom_recoveries_total",
                  "OOM backoffs that converged to a fitting batch size").inc()
        m.gauge("sm_oom_safe_batch",
                "Most recently learned proven-safe formula batch").set(batch)


def safe_batch_for(key: str) -> int | None:
    return _registry.safe_batch_for(key)


def snapshot() -> dict:
    """Registry contents for ``GET /debug/resources``."""
    return _registry.snapshot()


def reset() -> None:
    """Forget learned sizes and counts (tests)."""
    _registry.reset()


def attach_metrics(registry) -> None:
    """Export the ``sm_oom_*`` family through a service MetricsRegistry;
    counts recorded before attachment are backfilled."""
    global _metrics
    with _metrics_lock:
        _metrics = registry
    snap = _registry.snapshot()
    registry.counter(
        "sm_oom_events_total",
        "Device/host memory-exhaustion errors classified at the scoring "
        "seam").inc(snap["events"])
    registry.counter(
        "sm_oom_recoveries_total",
        "OOM backoffs that converged to a fitting batch size").inc(
        snap["recoveries"])
    g = registry.gauge("sm_oom_safe_batch",
                       "Most recently learned proven-safe formula batch")
    if snap["safe_batches"]:
        g.set(list(snap["safe_batches"].values())[-1])
