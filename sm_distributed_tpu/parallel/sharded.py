"""Mesh-sharded fused extract+score graph (multi-chip path).

TPU-native replacement for the reference's distributed runtime (SURVEY.md
§5.8): where the reference broadcasts peak tables and runs a cluster-wide
``groupByKey`` shuffle of (ion, pixel, intensity) hits
(``formula_imager_segm.compute_sf_images`` [U], §3.3), here:

- the spectral data is resident in HBM as per-pixel-shard FLAT sorted peak
  lists sharded over the ``"pixels"`` mesh axis — the RDD-partition analog.
  (Round-2 switch from the padded cube: per-shard bytes track the actual
  peak count instead of pixels x max-spectrum-length, which is what a
  ragged >200k-pixel DESI slide needs, and extraction uses the same
  flat-banded kernel as the single-device path);
- the isotope window/intensity tables are sharded over ``"formulas"`` and
  replicated over ``"pixels"`` — the broadcast analog (XLA materializes it as
  an all-gather over ICI);
- the shuffle is ONE ``all_to_all`` along the pixel axis: each device trades
  its pixel slice of most ions for ALL pixels of a 1/n_pix ion sub-batch.
  This is the round-2 comms redesign (VERDICT r1 item 3): the round-1 step
  ``all_gather``-ed every device a full (B_loc, K, P_full) image block, so
  per-device memory grew with TOTAL pixels and (n_pix-1)/n_pix of the metric
  compute was redundant.  Now per-device image bytes are B_loc*K*P_full/n_pix
  — constant in the shard count for a fixed total batch — metric compute is
  partitioned (no redundancy), and because image pixel values are exact
  integers on the shared intensity grid (ops/quantize.py), each ion's full
  image is bit-identical to the single-device path, so metrics are computed
  by the SAME code on the SAME bits.  A final tiny ``all_gather`` of the
  (B_loc/n_pix, 4) metric rows reassembles the formula shard's output.

The whole step stays a single jitted program per dataset (static shapes), so
multi-chip keeps the north star's one-fused-graph property per batch.

ISSUE 18 scope note: the mesh step adopts the bf16 resident-cube
compaction (per-shard rows cast on host, expanded to f32 in-graph at the
top of the step), but NOT the fused Pallas scoring kernel — the step's
all_to_all trades materialized image blocks between pixel shards, and the
correlation moments need the post-shuffle global-pixel mean, so the fused
kernel's image-free partials cannot cross the shuffle without a second
collective pass.  int8 falls back to f32 here: per-tile scale vectors do
not align with shard rows.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from ..analysis.numerics import numerics_surface
from ..analysis.surface import compile_surface
from ..io.dataset import SpectralDataset
from ..ops import buckets as shape_buckets
from ..ops.imager_jax import (
    BAND_WINDOWS as _BAND_WINDOWS,
)
from ..ops.imager_jax import (
    batch_peak_band,
    batch_peak_runs,
    compact_peaks,
    extract_images_flat_banded,
    flat_bound_ranks,
    prepare_flat_sharded_arrays,
    window_chunks,
    window_rank_grid,
)
from ..ops.isocalc import IsotopePatternTable
from ..ops.metrics_jax import batch_metrics
from ..utils import tracing
from ..ops.quantize import expand_cube_jnp, quantize_window
from ..utils.config import DSConfig, SMConfig
from ..utils.logger import logger
from .mesh import FORMULAS_AXIS, PIXELS_AXIS, make_mesh, shard_map

# Declared compile surface (ISSUE 12, analysis/surface.py): the sharded
# step's statics ride in through make()'s partial closure, so the whole
# mesh path mints ONE executable per (gc_width, n_keep, w_cap) triple —
# sticky stream-fixpoint capacities keep the triple set closed per stream.
COMPILE_SURFACE = compile_surface(__name__, {
    "step":
        "statics=closure(gc_width,n_keep,w_cap); buckets=one executable per "
        "(gc_width, n_keep, w_cap) triple — sticky _grow_static_shapes "
        "fixpoint + band_bucket ladder bound the triple set per stream; "
        "per-shard pixel rows and resident peak slots snap to the "
        "ops/buckets lattice with a traced real-pixel count (ISSUE 13), "
        "so dataset sizes sharing a bucket share the executable; the "
        "extract_ion_images step is a second, statics-free export program",
    "sharded":
        "statics=closure(gc_width,n_keep,w_cap); buckets=jit of the "
        "shard_mapped step, cached per triple in ShardedJaxBackend._fns",
})

# Declared numerics contracts (ISSUE 15): the sharded step slices its
# all_to_all concat to the SAME row bucket the single-device path uses
# (ISSUE 13), so sharded scoring is BIT-equal to the single-device fused
# graph — the strongest cross-variant contract in the tree.  The shard
# rows ride the lattice, hence `padded=px_s,in_s` for the
# masked-reduction taint.
NUMERICS = numerics_surface(__name__, {
    "step":
        "contract=bit_exact; test=tests/test_parallel.py::"
        "test_sharded_matches_single_device; padded=px_s,in_s",
    "sharded":
        "contract=bit_exact; test=tests/test_parallel.py::"
        "test_sharded_peak_compaction_bit_exact",
})


def build_sharded_score_factory(
    mesh: Mesh,
    *,
    p_loc: int,
    nrows: int,
    ncols: int,
    nlevels: int,
    do_preprocessing: bool,
    q: float,
):
    """Returns ``make(gc_width) -> jitted sharded step``: the step maps
    (flat peak shards, window shards) -> (B, 4) metrics; the factory exists
    because the band width is a static shape (ShardedJaxBackend caches one
    executable per gc_width, normally exactly one thanks to the sticky
    pre-sized band).

    Layouts: the flat peak arrays (pixel + intensity rows, one row per pixel
    shard) are sharded P("pixels", None); the per-(pixel-shard x formula-
    shard) bound ranks P("pixels", "formulas"); the window-chunk plan per
    formula shard P("formulas", ...); output P("formulas", None).  The
    extraction inside each device block is exactly the single-device
    flat-banded kernel on the shard's pixel slice.
    """

    n_pix = mesh.shape[PIXELS_AXIS]

    def step(px_s, in_s, pos, starts, r_lo_loc, r_hi_loc, inv,
             theor_ints, n_valid, run_pos, run_delta, n_b, n_real,
             *, gc_width, n_keep, w_cap):
        # Per-device blocks: px_s/in_s (1, Nmax); pos (1, G_loc); plan
        # (C, Wc)/(C,)/(W_loc,); theor (B_loc, K); n_valid (B_loc,);
        # compaction runs (1, R_pad)/(1, R_pad)/(1, 1) per (pixel-shard x
        # formula-shard).  Exactly one of n_keep/w_cap is nonzero: n_keep
        # selects the compaction path, w_cap the band-slice path (scatter a
        # contiguous dynamic slice of this shard's sorted peaks — the cell's
        # window-union rank band; run_pos doubles as the (1, 1) per-cell
        # band start), 0/0 the plain path.  One executable per
        # (gc_width, n_keep, w_cap) triple, mirroring JaxBackend._VARIANTS.
        b, k = theor_ints.shape
        # f32 view of a (possibly bf16-compacted) shard row — a no-op for
        # legacy f32 residents, so that HLO is byte-identical (ISSUE 18)
        in_s = expand_cube_jnp(in_s, None)
        if n_keep:
            px_loc, in_loc = compact_peaks(
                px_s[0], in_s[0], run_pos[0], run_delta[0], n_b[0, 0],
                n_keep=n_keep, n_pixels=p_loc)
        elif w_cap:
            w_start = run_pos[0, 0]
            px_loc = jax.lax.dynamic_slice(px_s[0], (w_start,), (w_cap,))
            in_loc = jax.lax.dynamic_slice(in_s[0], (w_start,), (w_cap,))
        else:
            px_loc, in_loc = px_s[0], in_s[0]
        imgs_loc = extract_images_flat_banded(
            px_loc, in_loc, pos[0], starts, r_lo_loc, r_hi_loc, inv,
            gc_width=gc_width, n_pixels=p_loc)
        # materialize before the metric consumers (see models/msm_jax.py:
        # measured 3.4x fusion regression at 65k pixels without it)
        imgs_loc = jax.lax.optimization_barrier(imgs_loc)
        imgs_loc = imgs_loc.reshape(b, k, -1)            # (B_loc, K, P_loc)
        # The "shuffle": trade pixel slices for full-pixel ion sub-batches.
        # Device j of the pixel group ends with (B_loc/n_pix, K, P_full).
        imgs_mine = jax.lax.all_to_all(
            imgs_loc, PIXELS_AXIS, split_axis=0, concat_axis=2, tiled=True)
        imgs_mine = imgs_mine[:, :, : nrows * ncols]
        ti = theor_ints.reshape(n_pix, b // n_pix, k)
        nv = n_valid.reshape(n_pix, b // n_pix)
        my = jax.lax.axis_index(PIXELS_AXIS)
        # ``nrows`` is the (possibly row-bucketed) metric grid; ``n_real``
        # carries the dataset's true pixel count as a traced scalar so the
        # masked centering stays bit-identical on lattice padding
        out_mine = batch_metrics(
            imgs_mine, ti[my], nv[my], nrows, ncols, nlevels,
            do_preprocessing=do_preprocessing, q=q, n_real=n_real[0],
        )                                                # (B_loc/n_pix, 4)
        # reassemble the formula shard's rows (ion chunks are in pixel-shard
        # order, matching the original ion order)
        return jax.lax.all_gather(out_mine, PIXELS_AXIS, axis=0, tiled=True)

    def make(gc_width, n_keep=0, w_cap=0):
        from functools import partial

        sharded = shard_map(
            partial(step, gc_width=gc_width, n_keep=n_keep, w_cap=w_cap),
            mesh=mesh,
            in_specs=(
                P(PIXELS_AXIS, None),             # px_s (S, Nmax)
                P(PIXELS_AXIS, None),             # in_s (S, Nmax)
                P(PIXELS_AXIS, FORMULAS_AXIS),    # pos (S, F*G_loc)
                P(FORMULAS_AXIS),                 # starts (F*C,)
                P(FORMULAS_AXIS, None),           # r_lo_loc (F*C, Wc)
                P(FORMULAS_AXIS, None),           # r_hi_loc (F*C, Wc)
                P(FORMULAS_AXIS),                 # inv (F*W_loc,)
                P(FORMULAS_AXIS, None),           # theor_ints
                P(FORMULAS_AXIS),                 # n_valid
                P(PIXELS_AXIS, FORMULAS_AXIS),    # run_pos (S, F*R_pad)
                P(PIXELS_AXIS, FORMULAS_AXIS),    # run_delta (S, F*R_pad)
                P(PIXELS_AXIS, FORMULAS_AXIS),    # n_b (S, F)
                P(None),                          # n_real (1,) replicated
            ),
            out_specs=P(FORMULAS_AXIS, None),
            # The output IS replicated over "pixels" (tiled all_gather of the
            # per-shard metric rows).  JAX's VMA type system can't infer
            # replication through tiled all_gather (no all_gather_invariant
            # in jax 0.9), so the static check is disabled.
            check_vma=False,
        )
        return jax.jit(sharded)

    return make


def _round_up(n: int, m: int) -> int:
    return -(-n // m) * m


class ShardedJaxBackend:
    """Multi-chip scorer: same interface/semantics as models.msm_jax.JaxBackend,
    data sharded over the ("pixels", "formulas") mesh."""

    name = "jax_tpu"

    def __init__(
        self,
        ds: SpectralDataset,
        ds_config: DSConfig,
        sm_config: SMConfig,
        mesh: Mesh | None = None,
        restrict_table: IsotopePatternTable | None = None,
    ):
        from .distributed import enable_compile_cache

        self.ds = ds
        self.ds_config = ds_config
        enable_compile_cache(sm_config)
        self.mesh = mesh if mesh is not None else make_mesh(sm_config.parallel)
        n_pix_shards = self.mesh.shape[PIXELS_AXIS]
        n_form_shards = self.mesh.shape[FORMULAS_AXIS]
        # shape-bucket lattice (ISSUE 13, ops/buckets.py): the pad-to
        # batch snaps to a lattice point first, then to the mesh granule
        self._buckets = shape_buckets.buckets_enabled(sm_config.parallel)
        from .distributed import compile_cache_path

        shape_buckets.bind_manifest_dir(compile_cache_path(sm_config))
        # Static batch padded so each formula shard's block further splits
        # evenly across the pixel shards (the all_to_all ion sub-batches).
        self.batch = _round_up(
            shape_buckets.effective_batch(sm_config.parallel),
            n_form_shards * n_pix_shards)
        img_cfg = ds_config.image_generation
        self.ppm = img_cfg.ppm
        if sm_config.parallel.mz_chunk:
            # a silently-ignored memory knob is exactly how an opaque OOM
            # happens later — refuse instead of warn (VERDICT r2 weak #3)
            raise ValueError(
                "parallel.mz_chunk applies only to the single-device cube "
                "path; on a multi-device mesh, per-device memory is bounded "
                f"by sharding (pixels/{n_pix_shards}) — unset mz_chunk, or "
                "reduce parallel.formula_batch / grow the pixels axis to "
                "shrink per-shard scratch")
        # HBM guard, per-shard arithmetic (the single-device backend fails
        # early with guidance — msm_jax.py — and an 8-GiB-per-shard scatter
        # scratch OOMs just as opaquely on a mesh; VERDICT r2 weak #3)
        k_est = ds_config.isotope_generation.n_peaks
        b_loc = self.batch // n_form_shards
        p_loc_est = -(-ds.n_pixels // n_pix_shards)
        # same clamped-scratch formula as the single-device guard
        scratch = 4 * (p_loc_est + 1) * max(2 * b_loc * k_est + 1, 4098)
        if scratch > (8 << 30):
            raise ValueError(
                f"per-shard histogram scratch would be ~{scratch / 2**30:.0f}"
                f" GiB ({p_loc_est} pixels/shard x {b_loc} ions/formula-shard"
                f" x {k_est} peaks); reduce parallel.formula_batch, grow the"
                " pixels mesh axis, or add formula shards")

        if self._buckets:
            # per-shard pixel capacity = lattice WHOLE rows (each shard
            # owns complete image rows, so the concatenated padding stays
            # a contiguous tail) and peak slots on the shared lattice.
            # The metric grid is the SAME row bucket the single-device
            # path uses — the step slices its concat down to it — so
            # sharded metrics reduce over the identical padded length and
            # stay BIT-EQUAL to the single-device fused graph, while every
            # dataset size in the bucket shares the step executable
            nrows_b = shape_buckets.row_bucket(ds.nrows)
            r_loc_b = shape_buckets.pow2ish(
                -(-nrows_b // n_pix_shards), 1)
            mz_s, px_s, in_s, self._p_loc = prepare_flat_sharded_arrays(
                ds, self.ppm, n_pix_shards, p_loc=r_loc_b * ds.ncols,
                slot_bucket=shape_buckets.peak_bucket)
            self._nrows_metric = nrows_b
        else:
            mz_s, px_s, in_s, self._p_loc = prepare_flat_sharded_arrays(
                ds, self.ppm, n_pix_shards)
            self._nrows_metric = ds.nrows
        # the dataset's true pixel count, shipped replicated to every
        # device for the masked metric centering (lattice, ISSUE 13)
        self._n_real_host = np.full(1, ds.n_pixels, np.int32)
        if restrict_table is not None:
            mz_s, px_s, in_s = self._restrict_shards(
                mz_s, px_s, in_s, restrict_table)
        # resident-cube compaction (ISSUE 18): bf16 halves the per-shard
        # HBM rows (expanded to f32 in-graph at the top of the step); int8
        # per-tile scale vectors do not align with shard rows, so the mesh
        # path falls back to exact f32 rather than silently mis-scale
        self._cube_dtype = sm_config.parallel.cube_dtype
        if self._cube_dtype == "int8":
            logger.warning(
                "parallel.cube_dtype=int8 is single-device only (per-tile "
                "scales do not shard); mesh path keeps f32 residents")
            self._cube_dtype = "f32"
        if self._cube_dtype == "bf16":
            import ml_dtypes  # jax dependency; baked into the image

            in_s = in_s.astype(ml_dtypes.bfloat16)
        self._compaction = sm_config.parallel.peak_compaction
        self._band_mode = sm_config.parallel.band_slice
        self._n_keep = 0          # sticky compacted capacity (see JaxBackend)
        self._r_pad = 0           # sticky run-list capacity
        self.int_scale = ds.intensity_quantization(self.ppm)[1]
        flat_sharding = NamedSharding(self.mesh, P(PIXELS_AXIS, None))
        self._mz_shards = mz_s                 # host-side, for bound ranks
        self._px_s = jax.device_put(px_s, flat_sharding)
        self._in_s = jax.device_put(in_s, flat_sharding)
        self._pos_sharding = NamedSharding(
            self.mesh, P(PIXELS_AXIS, FORMULAS_AXIS))
        self._form_sharding = NamedSharding(self.mesh, P(FORMULAS_AXIS, None))
        self._nv_sharding = NamedSharding(self.mesh, P(FORMULAS_AXIS))
        self._rep_sharding = NamedSharding(self.mesh, P(None))
        self._n_form_shards = n_form_shards
        logger.info(
            "jax_tpu sharded flat peaks resident: %s over mesh %s "
            "(pixels=%d, formulas=%d, p_loc=%d)",
            px_s.shape, dict(self.mesh.shape), n_pix_shards, n_form_shards,
            self._p_loc,
        )
        self._make_fn = build_sharded_score_factory(
            self.mesh,
            p_loc=self._p_loc,
            nrows=self._nrows_metric,
            ncols=ds.ncols,
            nlevels=img_cfg.nlevels,
            do_preprocessing=img_cfg.do_preprocessing,
            q=img_cfg.q,
        )
        self._fns: dict[int, object] = {}      # gc_width -> jitted step
        self._gc_width = 0                     # sticky (see JaxBackend)
        # the smallest legal batch: each formula shard's block must still
        # split evenly across the pixel shards (see __init__ padding)
        self._batch_granule = n_form_shards * n_pix_shards

    def shrink_batch(self, batch: int) -> None:
        """HBM-OOM backoff hook (ISSUE 10, models/oom.py) — same contract
        as ``JaxBackend.shrink_batch`` but clamped to the mesh's batch
        granule (formula shards × pixel shards): below that, padding
        cannot shrink and memory relief must come from the mesh geometry
        instead (more pixel shards)."""
        new = max(self._batch_granule,
                  _round_up(max(1, int(batch)), self._batch_granule))
        if new < self.batch:
            logger.warning("sharded jax_tpu backend: formula batch %d -> %d "
                           "(OOM backoff, granule %d)", self.batch, new,
                           self._batch_granule)
            self.batch = new

    def _restrict_shards(self, mz_s, px_s, in_s, table):
        """Drop peaks outside the union of ``table``'s windows from every
        pixel shard's row and re-pad rows to the new common length (exact —
        ops/imager_jax.restrict_flat_to_windows)."""
        from ..ops.imager_jax import restrict_flat_to_windows

        lo_q, hi_q = quantize_window(table.mzs, self.ppm)
        mz_k, px_k, in_k, n_eff = restrict_flat_to_windows(
            mz_s, px_s, in_s, lo_q, hi_q, overflow_row=self._p_loc)
        logger.info(
            "window-union restriction: %d -> %d peaks/shard max",
            mz_s.shape[1], n_eff)
        return mz_k, px_k, in_k

    def _flat_plan(self, table: IsotopePatternTable):
        """Host prep: per-formula-shard bound grids + chunk plans + the
        per-(pixel-shard, formula-shard) bound ranks."""
        n = table.n_ions
        b = self.batch
        if n > b:
            raise ValueError(f"batch of {n} ions exceeds formula_batch={b}")
        k = table.max_peaks
        lo_q, hi_q = quantize_window(table.mzs, self.ppm)
        lo_p = np.zeros((b, k), dtype=np.int32)
        hi_p = np.zeros((b, k), dtype=np.int32)
        ints_p = np.zeros((b, k), dtype=np.float32)
        nv_p = np.zeros(b, dtype=np.int32)
        lo_p[:n], hi_p[:n] = lo_q, hi_q
        ints_p[:n] = table.ints
        nv_p[:n] = table.n_valid
        # Per-formula-shard bound grids: shard f histograms only its windows.
        n_px = self._mz_shards.shape[0]
        poss, starts_l, rlo_l, rhi_l, invs, gc = [], [], [], [], [], 0
        runs_sf: list[list] = [[] for _ in range(n_px)]  # [s][f] run plans
        bands_sf: list[list] = [[] for _ in range(n_px)]  # [s][f] rank bands
        for sl, _grid, rl, rh, pos_rows in self._shard_grids(lo_p, hi_p):
            st, rll, rhl, inv, gcs = window_chunks(rl, rh, _BAND_WINDOWS)
            gc = max(gc, gcs)
            starts_l.append(st)
            rlo_l.append(rll)
            rhi_l.append(rhl)
            invs.append(inv)
            if self._compaction != "off":
                for px in range(n_px):
                    runs_sf[px].append(batch_peak_runs(
                        self._mz_shards[px], lo_p[sl], hi_p[sl],
                        pos_rows[px]))
            if self._band_mode != "off":
                # each (pixel-shard, formula-shard) cell's contiguous rank
                # band of the shard's sorted peaks under THIS formula
                # shard's window union — with an m/z-ordered table the
                # formula shards are m/z sub-ranges of the batch, so cells
                # are even narrower than the whole batch's band
                for px in range(n_px):
                    bands_sf[px].append(batch_peak_band(
                        self._mz_shards[px], lo_p[sl], hi_p[sl]))
            poss.append(np.stack(pos_rows))
        runs = runs_sf if self._compaction != "off" else None
        bands = bands_sf if self._band_mode != "off" else None
        return (np.concatenate(poss, axis=1), np.concatenate(starts_l),
                np.concatenate(rlo_l), np.concatenate(rhi_l),
                np.concatenate(invs), ints_p, nv_p, gc, runs, bands)

    def _shard_grids(self, lo_p: np.ndarray, hi_p: np.ndarray):
        """Per formula shard: (row slice, bound grid, r_lo, r_hi, and each
        pixel shard's bound ranks) — the shared host prep of the score and
        image-export paths (they must stay in lockstep or the bit-identical
        contract breaks)."""
        f = self._n_form_shards
        n_px = self._mz_shards.shape[0]
        b_loc = lo_p.shape[0] // f
        for fi in range(f):
            sl = slice(fi * b_loc, (fi + 1) * b_loc)
            grid, rl, rh = window_rank_grid(lo_p[sl], hi_p[sl])
            pos_rows = [flat_bound_ranks(self._mz_shards[px], grid)
                        for px in range(n_px)]
            yield sl, grid, rl, rh, pos_rows

    def _variant_for(self, runs, bands) -> str:
        """Per-batch MESH-WIDE extraction variant (all devices run one
        program, so the decision keys on the busiest cell): 'band', 'compact'
        or 'plain' — the same measured-rate estimator as
        JaxBackend._variant_for (scatter ~14 ns/slot, packed-run gather ~23
        ns -> compact ~37 ns per capacity slot), on per-device work.  'on'
        modes force a variant for tests, band first.  Capacities are grown
        to a stream fixpoint first (_grow_static_shapes), so decisions are
        order-independent for a planned stream."""
        if self._band_mode == "on" and bands is not None:
            return "band"
        if self._compaction == "on" and runs is not None:
            return "compact"
        n = int(self._px_s.shape[1])
        est = {"plain": 14.0 * n}
        if runs is not None and self._compaction != "off":
            max_keep = max((r[2] for row in runs for r in row), default=1)
            cap_c = max(-(-max(max_keep, 1) // (1 << 16)) * (1 << 16),
                        self._n_keep)
            est["compact"] = 37.0 * min(cap_c, n)
        if bands is not None and self._band_mode != "off":
            cap = self._band_cap(bands)
            if cap < n:
                est["band"] = 14.0 * cap
        return min(est, key=est.get)

    def _band_cap(self, bands) -> int:
        """Static band-slice width for one batch: the bucketed max cell
        width (every cell slices the same static width; narrower cells'
        extra slice peaks land in gap bins with zero membership — exact)."""
        from ..ops.imager_jax import band_bucket

        w = max((b[1] for row in bands for b in row), default=0)
        return min(band_bucket(w), int(self._px_s.shape[1]))

    def _grow_compact_capacity(self, runs) -> None:
        # capacity clamps at the per-shard resident row length: padding
        # slots still gather/scatter, so a 64k rounding floor on a 10k-peak
        # shard would cost MORE than the plain path
        cap = max(1, int(self._px_s.shape[1]))
        rnd = 1 << 16
        max_keep = max((r[2] for row in runs for r in row), default=1)
        max_runs = max((r[0].size for row in runs for r in row), default=1)
        want = min(-(-max(max_keep, 1) // rnd) * rnd, cap)
        self._n_keep = max(self._n_keep, want)
        self._r_pad = max(self._r_pad, -(-max(max_runs, 1) // 4096) * 4096)

    def _pack_runs(self, runs):
        """(run_pos (S, F*R_pad), run_delta (S, F*R_pad), n_b (S, F),
        pos_b (S, F*G_loc)) padded to the sticky capacities."""
        n_px, f = len(runs), len(runs[0])
        rp = np.full((n_px, f * self._r_pad), self._n_keep, np.int32)
        rd = np.zeros((n_px, f * self._r_pad), np.int32)
        nb = np.zeros((n_px, f), np.int32)
        posb = []
        for s in range(n_px):
            row_pos = []
            for fi in range(f):
                run_pos, run_delta, n_b, pos_b = runs[s][fi]
                o = fi * self._r_pad
                rp[s, o : o + run_pos.size] = run_pos
                rd[s, o : o + run_delta.size] = run_delta
                nb[s, fi] = n_b
                row_pos.append(pos_b)
            posb.append(np.concatenate(row_pos))
        return rp, rd, nb, np.stack(posb)

    def _pack_bands(self, bands, pos, w_cap):
        """(w_start (S, F) i32, pos_b (S, F*G_loc) band-space bound ranks).

        Mirrors JaxBackend's band dispatch: each cell's start is clamped so
        the static-width slice stays inside the shard row; bounds outside
        the slice clip to 0/w_cap, exactly how the full plain path treats
        peaks before/after the band (see
        models/msm_jax.py::fused_score_fn_flat_banded_sliced)."""
        n_px, f = len(bands), len(bands[0])
        n = int(self._px_s.shape[1])
        g_loc = pos.shape[1] // f
        ws = np.zeros((n_px, f), np.int32)
        pos_b = np.empty_like(pos)
        for s in range(n_px):
            for fi in range(f):
                b_lo, _w = bands[s][fi]
                start = max(0, min(b_lo, n - w_cap))
                ws[s, fi] = start
                sl = slice(fi * g_loc, (fi + 1) * g_loc)
                pos_b[s, sl] = np.clip(pos[s, sl] - start, 0, w_cap)
        return ws, pos_b.astype(np.int32)

    def _dispatch(self, table: IsotopePatternTable, flat_plan=None):
        """Async: enqueue one padded sharded batch, return (device_out, n)."""
        if flat_plan is None:
            flat_plan = self._flat_plan(table)
        pos, starts, rlo, rhi, inv, ints_p, nv_p, gc, runs, bands = flat_plan
        self._gc_width = max(self._gc_width, gc)
        gc = self._gc_width
        n_px = self._mz_shards.shape[0]
        f = self._n_form_shards
        variant = self._variant_for(runs, bands)
        n_keep = w_cap = 0
        if variant == "compact":
            self._grow_compact_capacity(runs)
            n_keep = self._n_keep
            rp, rd, nb, posb = self._pack_runs(runs)
            pos = posb                 # kept-space bound ranks
        elif variant == "band":
            w_cap = self._band_cap(bands)
            rp, pos = self._pack_bands(bands, pos, w_cap)  # rp = band starts
            rd = np.zeros((n_px, f), np.int32)
            nb = np.zeros((n_px, f), np.int32)
        else:
            rp = np.zeros((n_px, f), np.int32)   # unused dummies, (1,1) blocks
            rd = np.zeros((n_px, f), np.int32)
            nb = np.zeros((n_px, f), np.int32)
        key = (gc, n_keep, w_cap)
        if key not in self._fns:
            self._fns[key] = self._make_fn(gc, n_keep, w_cap)
        pos_d = jax.device_put(pos, self._pos_sharding)
        starts_d = jax.device_put(starts, self._nv_sharding)
        rlo_d = jax.device_put(rlo, self._form_sharding)
        rhi_d = jax.device_put(rhi, self._form_sharding)
        inv_d = jax.device_put(inv, self._nv_sharding)
        ints_d = jax.device_put(ints_p, self._form_sharding)
        nv_d = jax.device_put(nv_p, self._nv_sharding)
        rp_d = jax.device_put(rp, self._pos_sharding)
        rd_d = jax.device_put(rd, self._pos_sharding)
        nb_d = jax.device_put(nb, self._pos_sharding)
        nr_d = jax.device_put(self._n_real_host, self._rep_sharding)
        if self._buckets:
            shape_buckets.record_spec(
                self._sharded_spec(variant, key, pos, starts, rlo, inv,
                                   ints_p))
        out = self._fns[key](self._px_s, self._in_s, pos_d, starts_d,
                             rlo_d, rhi_d, inv_d, ints_d, nv_d,
                             rp_d, rd_d, nb_d, nr_d)
        return out, table.n_ions

    def _sharded_spec(self, variant: str, key: tuple, pos, starts, rlo,
                      inv, ints_p) -> dict:
        """BucketSpec of one sharded step executable (ops/buckets.py) —
        recorded for the /debug/compile lattice view AND for the AOT
        primer (service/primer.py), which since ISSUE 14 rebuilds the
        byte-identical mesh-shaped program from it on any host whose
        visible device count covers the mesh.  The spec therefore carries
        the full lease topology (mesh axes, per-shard pixel capacity) and
        every host-plan shape the step's avals depend on — a
        post-quarantine SHRUNKEN mesh records its own spec at first
        dispatch and is warm for every later job of that lease shape."""
        gc, n_keep, w_cap = key
        img = self.ds_config.image_generation
        spec = {
            "kind": "sharded", "variant": variant,
            "nrows": int(self._nrows_metric), "ncols": int(self.ds.ncols),
            "nlevels": int(img.nlevels),
            "do_preprocessing": bool(img.do_preprocessing),
            "q": float(img.q),
            "n_resident": int(self._px_s.shape[1]),
            "b": int(self.batch), "k": int(ints_p.shape[1]),
            "gc_width": int(gc), "n_keep": int(n_keep),
            "r_pad": int(self._r_pad), "w_cap": int(w_cap),
            "g": int(pos.shape[1]), "c": int(starts.shape[0]),
            "wc": int(rlo.shape[1]), "w": int(inv.shape[0]),
            "devices": int(self.mesh.size),
            "mesh_pix": int(self.mesh.shape[PIXELS_AXIS]),
            "mesh_form": int(self.mesh.shape[FORMULAS_AXIS]),
            "p_loc": int(self._p_loc),
        }
        # recorded only when compacted, like JaxBackend._bucket_spec —
        # legacy spec strings stay byte-stable
        if self._cube_dtype != "f32":
            spec["cube_dtype"] = self._cube_dtype
        return spec

    def score_batch(self, table: IsotopePatternTable) -> np.ndarray:
        from ..models.msm_jax import to_numpy_global

        out, n = self._dispatch(table)
        return to_numpy_global(out)[:n].astype(np.float64)

    def extract_ion_images(self, table: IsotopePatternTable) -> np.ndarray:
        """(n_ions, K, n_pix) de-quantized ion images off the DEVICE shards —
        the mesh-path analog of JaxBackend.extract_ion_images, so annotated
        image export needs no CPU re-extraction on multi-chip runs either.

        Collective-free: each device extracts its (formula-shard window
        block x pixel-shard slice); the output is sharded over BOTH mesh
        axes and assembled on host (to_numpy_global).  Bit-identical to the
        numpy extractor via the shared integer grids."""
        from ..models.msm_jax import to_numpy_global
        from ..ops.imager_jax import extract_images_flat

        n, b = table.n_ions, self.batch
        if n > b:
            from ..models.msm_basic import _slice_table

            out = [self.extract_ion_images(_slice_table(table, s, min(s + b, n)))
                   for s in range(0, n, b)]
            return np.concatenate(out)
        k = table.max_peaks
        lo_q, hi_q = quantize_window(table.mzs, self.ppm)
        lo_p = np.zeros((b, k), dtype=np.int32)
        hi_p = np.zeros((b, k), dtype=np.int32)
        lo_p[:n], hi_p[:n] = lo_q, hi_q
        rlo_l, rhi_l, poss = [], [], []
        for _sl, _grid, rl, rh, pos_rows in self._shard_grids(lo_p, hi_p):
            rlo_l.append(rl)
            rhi_l.append(rh)
            poss.append(np.stack(pos_rows))
        p_loc = self._p_loc

        def step(px_s, in_s, pos, rlo, rhi):
            return extract_images_flat(
                px_s[0], expand_cube_jnp(in_s[0], None), pos[0], rlo, rhi,
                n_pixels=p_loc)

        if not hasattr(self, "_extract_fn"):
            self._extract_fn = jax.jit(shard_map(
                step,
                mesh=self.mesh,
                in_specs=(
                    P(PIXELS_AXIS, None),             # px_s (S, Nmax)
                    P(PIXELS_AXIS, None),             # in_s (S, Nmax)
                    P(PIXELS_AXIS, FORMULAS_AXIS),    # pos (S, F*G_loc)
                    P(FORMULAS_AXIS),                 # r_lo (F*W_loc,)
                    P(FORMULAS_AXIS),                 # r_hi (F*W_loc,)
                ),
                out_specs=P(FORMULAS_AXIS, PIXELS_AXIS),
                check_vma=False,
            ))
        out = self._extract_fn(
            self._px_s, self._in_s,
            jax.device_put(np.concatenate(poss, axis=1), self._pos_sharding),
            jax.device_put(np.concatenate(rlo_l), self._nv_sharding),
            jax.device_put(np.concatenate(rhi_l), self._nv_sharding))
        # smlint: host-sync-ok[image EXPORT; assembling the both-axes-sharded output on host is the method's product]
        imgs = np.array(
            to_numpy_global(out)).reshape(b, k, -1)[:n, :, : self.ds.n_pixels]
        imgs /= np.float32(self.int_scale)   # exact power-of-two division
        valid = np.arange(k)[None, :] < table.n_valid[:, None]
        imgs[~valid] = 0.0
        return imgs

    def score_batches(self, tables, cancel=None) -> list[np.ndarray]:
        """Pipelined like the single-device backend: every batch enqueued
        (async dispatch + sharded device_put) before any result is synced;
        results fetched concurrently (models/msm_jax.fetch_scored_batches).
        Plans are built up front so the band width (and hence the ONE
        executable) is fixed before the first dispatch.  ``cancel`` is
        checked once before the group enqueues (checkpoint-group grain —
        multi-host collectives must stay in lockstep, so no per-batch
        bail-out mid-pipeline)."""
        from ..models.msm_jax import fetch_scored_batches

        tables = list(tables)
        if cancel is not None:
            cancel.check("score_batches")
        plans = [self._flat_plan(t) for t in tables]
        self._grow_static_shapes(plans)
        pending = []
        mesh_ids = [int(d.id) for d in self.mesh.devices.flat]
        for t, plan in zip(tables, plans):
            with tracing.span("score_batch", backend="jax_tpu_sharded",
                              ions=int(t.n_ions), enqueue=True,
                              mesh=dict(self.mesh.shape)):
                pending.append(self._dispatch(t, plan))
        # the device_sync span carries the sub-mesh's chip ids, so a trace
        # shows WHICH chips a sharded group occupied (the PR 5 tracer's
        # per-device view of the pool lease)
        with tracing.span("device_sync", batches=len(pending),
                          devices=mesh_ids):
            out = fetch_scored_batches(pending)
        self._trace_mesh_hbm(mesh_ids)
        return out

    def _trace_mesh_hbm(self, mesh_ids: list[int]) -> None:
        """Per-chip HBM of THIS mesh's devices onto the ambient trace (the
        PR 6 telemetry, scoped to the lease) — no-op on platforms without
        memory stats (CPU)."""
        from ..utils import devicemem

        per = {
            str(s["id"]): s["bytes_in_use"]
            for s in devicemem.device_stats()
            if s["id"] in set(mesh_ids) and s["bytes_in_use"] is not None
        }
        if per:
            tracing.event("mesh_hbm", devices=per)

    def _grow_static_shapes(self, plans) -> None:
        # fixpoint, like JaxBackend._grow_for_stream: growing the compact
        # capacity can flip a batch's variant, so repeat until stable
        # (monotone + bounded -> terminates; 2 passes in practice)
        while True:
            before = (self._gc_width, self._n_keep, self._r_pad)
            for plan in plans:
                self._gc_width = max(self._gc_width, plan[7])
                if self._variant_for(plan[8], plan[9]) == "compact":
                    self._grow_compact_capacity(plan[8])
            if before == (self._gc_width, self._n_keep, self._r_pad):
                return

    def presize(self, tables) -> None:
        """Grow the sticky static shapes to cover ``tables`` without scoring
        (see JaxBackend.presize — avoids mid-search recompiles when the
        orchestrator scores in checkpoint groups)."""
        self._grow_static_shapes([self._flat_plan(t) for t in tables])

    def warmup(self, tables) -> None:
        """Compile every executable variant the stream will use: one
        representative batch per (plain | compaction) kind, pre-sized
        (mirrors JaxBackend.warmup for bench/daemon callers)."""
        from ..models.msm_jax import to_numpy_global

        tables = list(tables)
        plans = [self._flat_plan(t) for t in tables]
        self._grow_static_shapes(plans)
        seen: set[tuple] = set()
        for t, plan in zip(tables, plans):
            variant = self._variant_for(plan[8], plan[9])
            # each band w_cap bucket is its own executable
            bucket = self._band_cap(plan[9]) if variant == "band" else 0
            kind = (variant, bucket)
            if kind not in seen:
                seen.add(kind)
                # reuse the precomputed plan — _flat_plan is the expensive
                # host pass (per-cell searchsorted over the shard peaks)
                to_numpy_global(self._dispatch(t, plan)[0])


def make_jax_backend(ds: SpectralDataset, ds_config: DSConfig,
                     sm_config: SMConfig, restrict_table=None,
                     device_indices=None):
    """Pick single-device fused graph or the mesh-sharded variant.

    ``device_indices`` (ISSUE 7): a device-pool lease's chip indices.  A
    1-chip lease gets the single-device fused graph PINNED to that chip
    (so two 1-chip jobs score on distinct chips concurrently); an N-chip
    lease gets the pjit/GSPMD-sharded path over a sub-mesh of exactly
    those chips.  ``None`` keeps the pre-pool behavior: mesh geometry from
    ``SMConfig.parallel`` over all local devices (1x1 mesh -> single
    device, no collectives).

    ``restrict_table``: the search's full ion table — peaks outside the
    union of its windows are dropped from the device arrays (exact)."""
    from .distributed import maybe_initialize_distributed
    from .mesh import lease_devices

    maybe_initialize_distributed(sm_config.parallel)  # no-op single-process
    devices = lease_devices(device_indices)
    # host×chip topology of the lease (ISSUE 11): the pool hands out chip
    # indices host-major, so the sub-mesh can confine cross-host (DCN)
    # traffic to pixel-axis boundaries; `hosts` here is how many host
    # failure domains THIS lease spans, not the whole pool's
    hosts = 1
    pool_hosts = max(1, int(getattr(sm_config.service,
                                    "device_pool_hosts", 1)))
    if devices is not None and device_indices is not None and pool_hosts > 1:
        from ..service.device_pool import resolve_pool_size
        from ..service.health import split_host_ranges
        from .mesh import host_topology

        # explicit per-host ranges (ISSUE 17): ragged pools attribute every
        # chip to its real host instead of skipping topology entirely
        pool_size = resolve_pool_size(sm_config.service)
        hosts = max(1, len(host_topology(
            device_indices, split_host_ranges(pool_size, pool_hosts))))
    if devices is not None and len(devices) == 1:
        from ..models.msm_jax import JaxBackend

        return JaxBackend(ds, ds_config, sm_config,
                          restrict_table=restrict_table, device=devices[0])
    mesh = make_mesh(sm_config.parallel, devices=devices, hosts=hosts)
    if mesh.size == 1:
        from ..models.msm_jax import JaxBackend

        return JaxBackend(ds, ds_config, sm_config,
                          restrict_table=restrict_table,
                          device=devices[0] if devices else None)
    return ShardedJaxBackend(ds, ds_config, sm_config, mesh=mesh,
                             restrict_table=restrict_table)
