"""Mesh-sharded fused extract+score graph (multi-chip path).

TPU-native replacement for the reference's distributed runtime (SURVEY.md
§5.8): where the reference broadcasts peak tables and runs a cluster-wide
``groupByKey`` shuffle of (ion, pixel, intensity) hits
(``formula_imager_segm.compute_sf_images`` [U], §3.3), here:

- the spectral cube is resident in HBM, sharded over the ``"pixels"`` mesh
  axis (``NamedSharding(mesh, P("pixels", None))``) — the RDD-partition analog;
- the isotope window/intensity tables are sharded over ``"formulas"`` and
  replicated over ``"pixels"`` — the broadcast analog (XLA materializes it as
  an all-gather over ICI);
- the shuffle is ONE ``all_to_all`` along the pixel axis: each device trades
  its pixel slice of most ions for ALL pixels of a 1/n_pix ion sub-batch.
  This is the round-2 comms redesign (VERDICT r1 item 3): the round-1 step
  ``all_gather``-ed every device a full (B_loc, K, P_full) image block, so
  per-device memory grew with TOTAL pixels and (n_pix-1)/n_pix of the metric
  compute was redundant.  Now per-device image bytes are B_loc*K*P_full/n_pix
  — constant in the shard count for a fixed total batch — metric compute is
  partitioned (no redundancy), and because image pixel values are exact
  integers on the shared intensity grid (ops/quantize.py), each ion's full
  image is bit-identical to the single-device path, so metrics are computed
  by the SAME code on the SAME bits.  A final tiny ``all_gather`` of the
  (B_loc/n_pix, 4) metric rows reassembles the formula shard's output.

The whole step stays a single jitted program per dataset (static shapes), so
multi-chip keeps the north star's one-fused-graph property per batch.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from ..io.dataset import SpectralDataset
from ..ops.imager_jax import extract_images, prepare_cube_arrays, window_rank_grid
from ..ops.isocalc import IsotopePatternTable
from ..ops.metrics_jax import batch_metrics
from ..ops.quantize import quantize_window
from ..utils.config import DSConfig, SMConfig
from ..utils.logger import logger
from .mesh import FORMULAS_AXIS, PIXELS_AXIS, make_mesh


def build_sharded_score_fn(
    mesh: Mesh,
    *,
    nrows: int,
    ncols: int,
    nlevels: int,
    do_preprocessing: bool,
    q: float,
):
    """Jitted sharded step: (cube shards, window shards) -> (B, 4) metrics.

    Layouts: mz_q_cube/int_cube sharded P("pixels", None); the window-bound
    grid + ranks are built per formula shard on host (each shard histograms
    only its own windows' bounds) and sharded P("formulas", ...); output
    sharded P("formulas", None).
    """

    n_pix = mesh.shape[PIXELS_AXIS]

    def step(mz_q_cube, int_cube, grid, r_lo, r_hi, theor_ints, n_valid):
        # Per-device block: cube (P_loc, L); windows (B_loc, K); grid (G_loc,).
        b, k = r_lo.shape
        imgs_loc = extract_images(mz_q_cube, int_cube, grid, r_lo.ravel(), r_hi.ravel())
        imgs_loc = imgs_loc.reshape(b, k, -1)            # (B_loc, K, P_loc)
        # The "shuffle": trade pixel slices for full-pixel ion sub-batches.
        # Device j of the pixel group ends with (B_loc/n_pix, K, P_full).
        imgs_mine = jax.lax.all_to_all(
            imgs_loc, PIXELS_AXIS, split_axis=0, concat_axis=2, tiled=True)
        imgs_mine = imgs_mine[:, :, : nrows * ncols]
        ti = theor_ints.reshape(n_pix, b // n_pix, k)
        nv = n_valid.reshape(n_pix, b // n_pix)
        my = jax.lax.axis_index(PIXELS_AXIS)
        out_mine = batch_metrics(
            imgs_mine, ti[my], nv[my], nrows, ncols, nlevels,
            do_preprocessing=do_preprocessing, q=q,
        )                                                # (B_loc/n_pix, 4)
        # reassemble the formula shard's rows (ion chunks are in pixel-shard
        # order, matching the original ion order)
        return jax.lax.all_gather(out_mine, PIXELS_AXIS, axis=0, tiled=True)

    sharded = jax.shard_map(
        step,
        mesh=mesh,
        in_specs=(
            P(PIXELS_AXIS, None),      # mz_q_cube
            P(PIXELS_AXIS, None),      # int_cube
            P(FORMULAS_AXIS),          # grid (concatenated per-shard grids)
            P(FORMULAS_AXIS, None),    # r_lo
            P(FORMULAS_AXIS, None),    # r_hi
            P(FORMULAS_AXIS, None),    # theor_ints
            P(FORMULAS_AXIS),          # n_valid
        ),
        out_specs=P(FORMULAS_AXIS, None),
        # The output IS replicated over "pixels" (tiled all_gather of the
        # per-shard metric rows).  JAX's VMA type system can't infer
        # replication through tiled all_gather (no all_gather_invariant in
        # jax 0.9), so the static check is disabled.
        check_vma=False,
    )
    return jax.jit(sharded)


def _round_up(n: int, m: int) -> int:
    return -(-n // m) * m


class ShardedJaxBackend:
    """Multi-chip scorer: same interface/semantics as models.msm_jax.JaxBackend,
    data sharded over the ("pixels", "formulas") mesh."""

    name = "jax_tpu"

    def __init__(
        self,
        ds: SpectralDataset,
        ds_config: DSConfig,
        sm_config: SMConfig,
        mesh: Mesh | None = None,
    ):
        from .distributed import enable_compile_cache

        self.ds = ds
        self.ds_config = ds_config
        enable_compile_cache(sm_config)
        self.mesh = mesh if mesh is not None else make_mesh(sm_config.parallel)
        n_pix_shards = self.mesh.shape[PIXELS_AXIS]
        n_form_shards = self.mesh.shape[FORMULAS_AXIS]
        # Static batch padded so each formula shard's block further splits
        # evenly across the pixel shards (the all_to_all ion sub-batches).
        self.batch = _round_up(
            max(1, sm_config.parallel.formula_batch),
            n_form_shards * n_pix_shards)
        img_cfg = ds_config.image_generation
        self.ppm = img_cfg.ppm

        mz_q, int_cube = prepare_cube_arrays(
            ds, pixels_multiple=n_pix_shards, ppm=self.ppm)
        self.int_scale = ds.intensity_quantization(self.ppm)[1]
        cube_sharding = NamedSharding(self.mesh, P(PIXELS_AXIS, None))
        self._mz_q = jax.device_put(mz_q, cube_sharding)
        self._ints = jax.device_put(int_cube, cube_sharding)
        self._form_sharding = NamedSharding(self.mesh, P(FORMULAS_AXIS, None))
        self._nv_sharding = NamedSharding(self.mesh, P(FORMULAS_AXIS))
        self._n_form_shards = n_form_shards
        logger.info(
            "jax_tpu sharded cube resident: %s over mesh %s (pixels=%d, formulas=%d)",
            mz_q.shape, dict(self.mesh.shape), n_pix_shards, n_form_shards,
        )
        self._fn = build_sharded_score_fn(
            self.mesh,
            nrows=ds.nrows,
            ncols=ds.ncols,
            nlevels=img_cfg.nlevels,
            do_preprocessing=img_cfg.do_preprocessing,
            q=img_cfg.q,
        )

    def _dispatch(self, table: IsotopePatternTable):
        """Async: enqueue one padded sharded batch, return (device_out, n)."""
        n = table.n_ions
        b = self.batch
        if n > b:
            raise ValueError(f"batch of {n} ions exceeds formula_batch={b}")
        k = table.max_peaks
        lo_q, hi_q = quantize_window(table.mzs, self.ppm)
        lo_p = np.zeros((b, k), dtype=np.int32)
        hi_p = np.zeros((b, k), dtype=np.int32)
        ints_p = np.zeros((b, k), dtype=np.float32)
        nv_p = np.zeros(b, dtype=np.int32)
        lo_p[:n], hi_p[:n] = lo_q, hi_q
        ints_p[:n] = table.ints
        nv_p[:n] = table.n_valid
        # Per-formula-shard bound grids: shard f histograms only its windows.
        f = self._n_form_shards
        b_loc = b // f
        grids, r_los, r_his = [], [], []
        for s in range(f):
            sl = slice(s * b_loc, (s + 1) * b_loc)
            g, rl, rh = window_rank_grid(lo_p[sl], hi_p[sl])
            grids.append(g)
            r_los.append(rl.reshape(b_loc, k))
            r_his.append(rh.reshape(b_loc, k))
        grid_d = jax.device_put(np.concatenate(grids), self._nv_sharding)
        rlo_d = jax.device_put(np.concatenate(r_los), self._form_sharding)
        rhi_d = jax.device_put(np.concatenate(r_his), self._form_sharding)
        ints_d = jax.device_put(ints_p, self._form_sharding)
        nv_d = jax.device_put(nv_p, self._nv_sharding)
        out = self._fn(self._mz_q, self._ints, grid_d, rlo_d, rhi_d, ints_d, nv_d)
        return out, n

    def score_batch(self, table: IsotopePatternTable) -> np.ndarray:
        out, n = self._dispatch(table)
        return np.asarray(out)[:n].astype(np.float64)

    def score_batches(self, tables) -> list[np.ndarray]:
        """Pipelined like the single-device backend: every batch enqueued
        (async dispatch + sharded device_put) before any result is synced;
        results fetched concurrently (models/msm_jax.fetch_scored_batches)."""
        from ..models.msm_jax import fetch_scored_batches

        return fetch_scored_batches([self._dispatch(t) for t in tables])


def make_jax_backend(ds: SpectralDataset, ds_config: DSConfig, sm_config: SMConfig):
    """Pick single-device fused graph or the mesh-sharded variant based on the
    resolved mesh size (1x1 mesh -> single device, no collectives)."""
    from .distributed import maybe_initialize_distributed

    maybe_initialize_distributed(sm_config.parallel)  # no-op single-process
    mesh = make_mesh(sm_config.parallel)
    if mesh.size == 1:
        from ..models.msm_jax import JaxBackend

        return JaxBackend(ds, ds_config, sm_config)
    return ShardedJaxBackend(ds, ds_config, sm_config, mesh=mesh)
