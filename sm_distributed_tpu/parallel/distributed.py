"""Multi-host runtime — the DCN analog of the reference's Spark cluster.

Reference: ``sm_config['spark']`` carries the cluster master address and
executor settings [U] (SURVEY.md #20, §5.8).  The TPU-native equivalent is
single-controller JAX: every host process calls
``jax.distributed.initialize(coordinator, num_processes, process_id)`` and
``jax.devices()`` then spans all hosts; the ("pixels", "formulas") mesh and
its collectives (all_to_all over ICI within a slice, DCN across slices) need
no further changes — shard_map code is topology-agnostic.

Launch (one process per host), e.g.:

    SM_COORDINATOR=host0:8476 SM_NUM_PROCESSES=4 SM_PROCESS_ID=$i \
        python -m sm_distributed_tpu.engine.cli run ...

or set ``parallel.coordinator_address`` / ``num_processes`` / ``process_id``
in the engine config.  On Cloud TPU pods, plain ``jax.distributed
.initialize()`` auto-discovers everything; we pass explicit values only when
configured.  Single-process (the default) is a strict no-op.

Managed runtime (ISSUE 17): this module is no longer a fire-once shim —

- **launch-race tolerance**: every host process races the coordinator's
  bind at pod startup, so ``initialize`` retries with exponential backoff
  (``parallel.init_retries`` / ``init_backoff_s``) before the failure is
  considered real.  The ``dist.initialize`` failpoint sits inside each
  attempt (docs/RECOVERY.md); a retried-then-successful init records the
  ``dist.init_retry`` recovery event.
- **shutdown/reset seam**: ``shutdown()`` tears the runtime down
  (``jax.distributed.shutdown()`` when live) and clears the idempotence
  latch so repeated in-process pod tests don't leak coordinator state.
- **process identity**: ``process_identity()`` resolves this process's
  ``(process_id, host)`` — stamped into tracing records
  (``utils/tracing.set_process``), telemetry samples, and ``/peers``.
  ``SM_HOST_NAME`` names the simulated host on CPU pods.
- **simulation seam**: ``SM_DIST_SIMULATE=1`` skips the real
  ``jax.distributed.initialize`` call while exercising the whole managed
  path (settings resolution, retry ladder, identity) — what the chaos
  harness's single-box "hosts" use; the real 2-process init is covered by
  the slow multi-process test (tests/test_distributed.py).
"""

from __future__ import annotations

import os
import socket
import sys
import time

from ..utils.config import ParallelConfig
from ..utils.failpoints import failpoint, record_recovery, register_failpoint
from ..utils.logger import logger

FP_DIST_INIT = register_failpoint(
    "dist.initialize",
    "inside each jax.distributed.initialize attempt (raise here is the "
    "coordinator-not-yet-up launch race; the backoff ladder retries)")

_initialized = False
_simulated = False


def compile_cache_path(sm_config):
    """The resolved persistent-cache directory (Path), or None when "off".
    Shared by ``enable_compile_cache`` and the warmup-manifest trim
    (models/msm_jax.py::JaxBackend.warmup)."""
    d = sm_config.parallel.compile_cache_dir
    if d == "off":
        return None
    from pathlib import Path

    return Path(d) if d else Path(sm_config.work_dir) / "xla_cache"


def enable_compile_cache(sm_config) -> None:
    """Point XLA's persistent compilation cache at a work-dir subdirectory
    so a dataset's second job (same shapes) skips the compile entirely —
    measured 15-20 s per dataset on a tunneled v5e, ~0.1 s warm.  ``"off"``
    disables; idempotent (jax.config.update is)."""
    path = compile_cache_path(sm_config)
    if path is None:
        return
    import jax

    jax.config.update("jax_compilation_cache_dir", str(path))
    # persist EVERY compile (ISSUE 13): the old 1.0 s floor meant fast
    # compiles were never written — which is exactly what made a "primed"
    # cache unreliable (the warmup manifest's entries==0 special case
    # exists because of it).  Entries are small; the disk-budget governor
    # and retention GC bound the directory like any other cache.
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)


def resolve_distributed_settings(cfg: ParallelConfig) -> tuple[str, int, int]:
    """(coordinator, num_processes, process_id) from env (priority) or cfg."""
    coord = os.environ.get("SM_COORDINATOR", cfg.coordinator_address)
    n_proc = int(os.environ.get("SM_NUM_PROCESSES", cfg.num_processes))
    proc_id = int(os.environ.get("SM_PROCESS_ID", cfg.process_id))
    return coord, n_proc, proc_id


def initialize_kwargs(coord: str, n_proc: int, proc_id: int) -> dict:
    """The exact kwargs handed to jax.distributed.initialize — factored out
    so the mapping stays unit-testable without spawning processes (omitted
    keys let JAX auto-discover on Cloud TPU pods)."""
    kwargs: dict = {}
    if coord:
        kwargs["coordinator_address"] = coord
    if n_proc > 1:
        kwargs["num_processes"] = n_proc
    if proc_id >= 0:
        kwargs["process_id"] = proc_id
    return kwargs


def is_initialized() -> bool:
    """True after a successful ``maybe_initialize_distributed`` (real or
    simulated) until ``shutdown()``."""
    return _initialized


def process_identity() -> dict:
    """This process's pod identity ``{"process_id": int, "host": str}``.

    ``process_id``: ``SM_PROCESS_ID`` env when set (the launcher contract),
    else the live ``jax.process_index()`` once the runtime is up, else 0.
    ``host``: ``SM_HOST_NAME`` env (the simulated-pod seam — a single box
    pretending to be several hosts names them apart) or the real hostname.
    """
    pid = -1
    env = os.environ.get("SM_PROCESS_ID")
    if env is not None:
        try:
            pid = int(env)
        except ValueError:
            pid = -1
    if pid < 0:
        mod = sys.modules.get("jax")
        if mod is not None and _initialized and not _simulated:
            try:
                pid = int(mod.process_index())
            except Exception as exc:  # pragma: no cover - defensive
                logger.debug("process_identity: jax.process_index "
                             "unavailable (%s); defaulting to 0", exc)
                pid = -1
    host = os.environ.get("SM_HOST_NAME") or socket.gethostname()
    return {"process_id": max(0, pid), "host": host}


def maybe_initialize_distributed(cfg: ParallelConfig) -> bool:
    """Initialize the multi-host runtime when configured; returns True when
    the runtime came (or already was) up.  Idempotent; single-process
    settings (num_processes <= 1 and no coordinator) are a no-op.

    Coordinator-not-yet-up is the NORMAL launch race, not an error: each
    attempt that raises backs off ``init_backoff_s * 2^attempt`` (capped at
    30 s) up to ``init_retries`` retries before the exception propagates.
    """
    global _initialized, _simulated
    coord, n_proc, proc_id = resolve_distributed_settings(cfg)
    if n_proc <= 1 and not coord:
        return False
    if _initialized:
        return True
    kwargs = initialize_kwargs(coord, n_proc, proc_id)
    retries = max(0, int(getattr(cfg, "init_retries", 5)))
    backoff = max(0.0, float(getattr(cfg, "init_backoff_s", 1.0)))
    simulate = os.environ.get("SM_DIST_SIMULATE", "") not in ("", "0")
    logger.info("initializing multi-host runtime: %s%s", kwargs,
                " (SM_DIST_SIMULATE: no real coordinator)" if simulate else "")
    attempt = 0
    while True:
        try:
            failpoint(FP_DIST_INIT)
            if not simulate:
                import jax

                jax.distributed.initialize(**kwargs)
            break
        except Exception as exc:
            if attempt >= retries:
                logger.error(
                    "multi-host init failed after %d attempt(s): %s",
                    attempt + 1, exc)
                raise
            delay = min(backoff * (2 ** attempt), 30.0)
            attempt += 1
            logger.warning(
                "multi-host init attempt %d failed (%s: %s) — coordinator "
                "not up yet?  retrying in %.2fs (%d retr%s left)",
                attempt, type(exc).__name__, exc, delay,
                retries - attempt + 1, "y" if retries - attempt + 1 == 1
                else "ies")
            if delay > 0:
                time.sleep(delay)
    if attempt:
        record_recovery("dist.init_retry")
    _initialized = True
    _simulated = simulate
    ident = process_identity()
    logger.info("multi-host runtime up: process %d on host %s",
                ident["process_id"], ident["host"])
    return True


def shutdown() -> None:
    """Tear the runtime down and reset the idempotence latch (the
    test/repeated-pod seam): calls ``jax.distributed.shutdown()`` when this
    process really initialized it; a failure there is logged, not raised —
    the latch clears either way so the next init starts clean."""
    global _initialized, _simulated
    if _initialized and not _simulated:
        try:
            import jax

            jax.distributed.shutdown()
        except Exception as exc:
            logger.warning("jax.distributed.shutdown failed: %s", exc)
    _initialized = False
    _simulated = False
