"""Multi-host runtime init — the DCN analog of the reference's Spark cluster.

Reference: ``sm_config['spark']`` carries the cluster master address and
executor settings [U] (SURVEY.md #20, §5.8).  The TPU-native equivalent is
single-controller JAX: every host process calls
``jax.distributed.initialize(coordinator, num_processes, process_id)`` and
``jax.devices()`` then spans all hosts; the ("pixels", "formulas") mesh and
its collectives (all_to_all over ICI within a slice, DCN across slices) need
no further changes — shard_map code is topology-agnostic.

Launch (one process per host), e.g.:

    SM_COORDINATOR=host0:8476 SM_NUM_PROCESSES=4 SM_PROCESS_ID=$i \
        python -m sm_distributed_tpu.engine.cli run ...

or set ``parallel.coordinator_address`` / ``num_processes`` / ``process_id``
in the engine config.  On Cloud TPU pods, plain ``jax.distributed
.initialize()`` auto-discovers everything; we pass explicit values only when
configured.  Single-process (the default) is a strict no-op.
"""

from __future__ import annotations

import os

from ..utils.config import ParallelConfig
from ..utils.logger import logger

_initialized = False


def compile_cache_path(sm_config):
    """The resolved persistent-cache directory (Path), or None when "off".
    Shared by ``enable_compile_cache`` and the warmup-manifest trim
    (models/msm_jax.py::JaxBackend.warmup)."""
    d = sm_config.parallel.compile_cache_dir
    if d == "off":
        return None
    from pathlib import Path

    return Path(d) if d else Path(sm_config.work_dir) / "xla_cache"


def enable_compile_cache(sm_config) -> None:
    """Point XLA's persistent compilation cache at a work-dir subdirectory
    so a dataset's second job (same shapes) skips the compile entirely —
    measured 15-20 s per dataset on a tunneled v5e, ~0.1 s warm.  ``"off"``
    disables; idempotent (jax.config.update is)."""
    path = compile_cache_path(sm_config)
    if path is None:
        return
    import jax

    jax.config.update("jax_compilation_cache_dir", str(path))
    # persist EVERY compile (ISSUE 13): the old 1.0 s floor meant fast
    # compiles were never written — which is exactly what made a "primed"
    # cache unreliable (the warmup manifest's entries==0 special case
    # exists because of it).  Entries are small; the disk-budget governor
    # and retention GC bound the directory like any other cache.
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)


def resolve_distributed_settings(cfg: ParallelConfig) -> tuple[str, int, int]:
    """(coordinator, num_processes, process_id) from env (priority) or cfg."""
    coord = os.environ.get("SM_COORDINATOR", cfg.coordinator_address)
    n_proc = int(os.environ.get("SM_NUM_PROCESSES", cfg.num_processes))
    proc_id = int(os.environ.get("SM_PROCESS_ID", cfg.process_id))
    return coord, n_proc, proc_id


def initialize_kwargs(coord: str, n_proc: int, proc_id: int) -> dict:
    """The exact kwargs handed to jax.distributed.initialize — factored out
    so the mapping stays unit-testable without spawning processes (omitted
    keys let JAX auto-discover on Cloud TPU pods)."""
    kwargs: dict = {}
    if coord:
        kwargs["coordinator_address"] = coord
    if n_proc > 1:
        kwargs["num_processes"] = n_proc
    if proc_id >= 0:
        kwargs["process_id"] = proc_id
    return kwargs


def maybe_initialize_distributed(cfg: ParallelConfig) -> bool:
    """Initialize the multi-host runtime when configured; returns True when
    jax.distributed.initialize was called.  Idempotent; single-process
    settings (num_processes <= 1 and no coordinator) are a no-op."""
    global _initialized
    coord, n_proc, proc_id = resolve_distributed_settings(cfg)
    if n_proc <= 1 and not coord:
        return False
    if _initialized:
        return True
    import jax

    kwargs = initialize_kwargs(coord, n_proc, proc_id)
    logger.info("initializing multi-host runtime: %s", kwargs)
    jax.distributed.initialize(**kwargs)
    _initialized = True
    return True
