"""Mesh-sharded distributed runtime (the reference's Spark layer, TPU-native).

- mesh:    ("pixels", "formulas") device-mesh construction from config.
- sharded: shard_map fused extract+score step + multi-chip backend.
"""

from .mesh import FORMULAS_AXIS, PIXELS_AXIS, make_mesh, resolve_axis_sizes
from .sharded import ShardedJaxBackend, build_sharded_score_factory, make_jax_backend

__all__ = [
    "FORMULAS_AXIS",
    "PIXELS_AXIS",
    "make_mesh",
    "resolve_axis_sizes",
    "ShardedJaxBackend",
    "build_sharded_score_factory",
    "make_jax_backend",
]
