"""Device-mesh construction — the TPU-native replacement for the reference's
Spark cluster topology.

The reference expresses parallelism as Spark settings (``spark.master``,
executor counts — ``sm_config['spark']`` [U], SURVEY.md #20) and its data
layout as RDD partitions over the pixel axis plus broadcast peak tables
(SURVEY.md §2d).  Here the same two degrees of freedom are mesh axes:

- ``"pixels"``  — shards the spectral cube's pixel dimension (the RDD
  partition analog; BASELINE config #5: >200k-pixel DESI slide on v4-32).
- ``"formulas"`` — shards the formula-batch dimension (the analog of
  parallelizing over (sf, adduct) pairs; BASELINE config #4).

Axis sizes come from ``SMConfig.parallel`` where ``-1`` means "use all
remaining devices".  A 1x1 mesh degrades gracefully to the single-device
fused graph (models/msm_jax.py).
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh

from ..utils.config import ParallelConfig

PIXELS_AXIS = "pixels"
FORMULAS_AXIS = "formulas"


def shard_map(f, *, mesh: Mesh, in_specs, out_specs, check_vma: bool = True):
    """Version-compatible ``shard_map`` (ISSUE 7 satellite).

    jax >= 0.6 exposes ``jax.shard_map`` with the VMA type-system knob
    ``check_vma``; the 0.4.x line only ships
    ``jax.experimental.shard_map.shard_map`` whose equivalent knob is
    ``check_rep``.  Every mesh-sharded program in this repo goes through
    this one seam so the rest of parallel/ never has to care which jax is
    installed.
    """
    if hasattr(jax, "shard_map"):
        try:
            return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                                 out_specs=out_specs, check_vma=check_vma)
        except TypeError:
            # transitional releases: jax.shard_map exists but still takes
            # the old replication-check keyword
            return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                                 out_specs=out_specs, check_rep=check_vma)
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_rep=check_vma)


def resolve_axis_sizes(n_devices: int, cfg: ParallelConfig) -> tuple[int, int]:
    """(pixels, formulas) axis sizes using exactly their product <= n_devices.

    ``-1`` entries absorb all devices left over after the explicit axes.
    Both -1: all devices go to the pixel axis (the dominant data axis).
    """
    pix, form = cfg.pixels_axis, cfg.formulas_axis
    if pix < -1 or form < -1 or pix == 0 or form == 0:
        raise ValueError(
            f"mesh axis sizes must be -1 or positive, got pixels_axis={pix}, "
            f"formulas_axis={form}")
    if pix == -1 and form == -1:
        pix, form = n_devices, 1
    elif pix == -1:
        if n_devices % form:
            raise ValueError(f"formulas_axis={form} does not divide {n_devices} devices")
        pix = n_devices // form
    elif form == -1:
        if n_devices % pix:
            raise ValueError(f"pixels_axis={pix} does not divide {n_devices} devices")
        form = n_devices // pix
    if pix * form > n_devices:
        raise ValueError(
            f"mesh {pix}x{form} needs {pix * form} devices, only {n_devices} available"
        )
    return pix, form


def make_mesh(cfg: ParallelConfig, devices=None, hosts: int = 1) -> Mesh:
    """Build the ("pixels", "formulas") mesh from config + available devices.

    ``hosts`` (ISSUE 11) declares the host×chip topology the device list
    came from (a ``jax.distributed``-style multi-host pool, simulated on
    CPU).  The device order is host-major, so with ``hosts`` dividing the
    pixels axis each host's chips form a contiguous block of pixel shards
    — cross-host (DCN) traffic is confined to the pixel-axis reductions
    and a whole-host failure takes out a contiguous, re-computable shard
    range instead of a stripe through every shard.  A topology the grid
    cannot honor is logged and ignored (topology is an optimization, never
    a reason to fail the job)."""
    devices = list(devices if devices is not None else jax.devices())
    pix, form = resolve_axis_sizes(len(devices), cfg)
    if hosts > 1:
        from ..utils.logger import logger

        if pix % hosts:
            logger.warning(
                "make_mesh: %d hosts does not divide the %d-shard pixels "
                "axis; host blocks will straddle mesh rows", hosts, pix)
        else:
            logger.info("make_mesh: %dx%d mesh over %d host(s) "
                        "(%d pixel shard(s) per host)",
                        pix, form, hosts, pix // hosts)
    dev_grid = np.array(devices[: pix * form]).reshape(pix, form)
    return Mesh(dev_grid, (PIXELS_AXIS, FORMULAS_AXIS))


def host_topology(device_indices, chips_per_host) -> dict[int, tuple]:
    """Group a lease's chip indices by host failure domain:
    ``{host: (chip, ...)}`` — what the fleet controller (and a sub-mesh
    lease) uses to reason about host-level blast radius.

    ``chips_per_host`` is either the legacy int (equal hosts of that many
    chips) or, since ISSUE 17, explicit per-host ``(lo, hi)`` ranges
    (``service/health.py::split_host_ranges``) so ragged pools attribute
    every chip to the right host instead of the integer-division guess."""
    ranges = None
    if not isinstance(chips_per_host, int):
        ranges = [(int(lo), int(hi)) for lo, hi in chips_per_host]
    out: dict[int, list[int]] = {}
    for i in device_indices or ():
        i = int(i)
        if ranges is None:
            out.setdefault(i // max(1, int(chips_per_host)), []).append(i)
            continue
        for h, (lo, hi) in enumerate(ranges):
            if lo <= i < hi:
                out.setdefault(h, []).append(i)
                break
        else:
            out.setdefault(len(ranges) - 1 if ranges else 0, []).append(i)
    return {h: tuple(sorted(v)) for h, v in sorted(out.items())}


def global_device_order(devices=None) -> list:
    """The pod-wide host-major device list: ``jax.devices()`` sorted by
    ``(process_index, id)``.  JAX documents no enumeration order across
    processes, so the pool's chip index -> Device mapping goes through this
    one seam — stable under permuted enumeration, and chips of one process
    form a contiguous index run (the host failure domain the pool's
    ``hosts`` dimension names).  Unit-testable with fake device objects."""
    devs = list(devices) if devices is not None else list(jax.devices())
    return sorted(devs, key=lambda d: (int(getattr(d, "process_index", 0)),
                                       int(getattr(d, "id", 0))))


def lease_devices(device_indices) -> list | None:
    """Map a device-pool lease's chip indices (``DeviceLease.devices``) to
    jax Device objects for a sub-mesh.

    ``None`` -> ``None`` (the caller meshes over ALL local devices, the
    pre-pool behavior).  In a multi-process runtime the pool indexes the
    GLOBAL host-major order (``global_device_order``) — a lease's chips may
    live in other processes (ISSUE 17); single-process keeps the local
    list.  Indices beyond the visible device count — a simulated pool
    larger than the host, e.g. the CI smoke's 8-chip pool on a smaller box
    — are dropped with a warning; an empty result falls back to ``None``
    rather than failing the job over a telemetry-grade mismatch.
    """
    if device_indices is None:
        return None
    from ..utils.logger import logger

    try:
        multi = jax.process_count() > 1
    except Exception as exc:  # pragma: no cover - uninitialized backend
        logger.debug("lease_devices: jax backend not up (%s); "
                     "assuming single-process", exc)
        multi = False
    devs = global_device_order() if multi else jax.local_devices()
    picked = [devs[i] for i in device_indices if 0 <= int(i) < len(devs)]
    if len(picked) < len(list(device_indices)):
        logger.warning(
            "device lease %s exceeds the %d visible jax devices; %s",
            tuple(device_indices), len(devs),
            f"using {len(picked)} chip(s)" if picked
            else "falling back to the config mesh")
    return picked or None
