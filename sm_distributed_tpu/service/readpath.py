"""Production read path: governed result/tile serving over the segments.

The write plane publishes each dataset's annotations as an atomically-swapped
columnar segment (``engine/index.py``); this module is everything between
those files and a GET (ISSUE 16):

- **ReadCache** — a byte- and entry-bounded LRU shared by query results and
  rendered ion-image tiles, with an optional on-disk tile tier;
- **ReadPath** — the handlers behind ``GET /datasets``,
  ``/datasets/<id>/annotations``, ``/annotations`` (cross-dataset cohort) and
  ``/datasets/<id>/images/<sf_adduct>`` (PNG via ``engine/png.py``), each
  wrapped in read admission (more than ``read.max_concurrent`` in-flight
  reads shed with a structured 429 + Retry-After — independently of the
  write-side admission), ``sm_read_*`` metrics, a ``read`` SLO observation
  and a trace event per request.

Cache *fills* are governed: under disk pressure the ResourceGovernor's
``allow_read_cache_fill`` gate (degrade level 3, shed BEFORE submits) turns
fills off while reads keep answering from the source segments — the
``read.cache_fill`` failpoint sits on that seam so chaos can prove a failed
fill never fails the read (docs/RECOVERY.md).

Cache keys embed a validator derived from the segment/npz file identity
(``st_mtime_ns``, ``st_size``): ``os.replace`` on republish changes it, so a
re-annotated dataset invalidates its cached reads naturally — no stale entry
is ever served for a swapped segment.

COMPILE_SURFACE / NUMERICS exemption (argued): the read path is host-side
numpy + PNG encoding over stored results — no jax import, no jit, no
scoring math.  Tile bytes are ``engine/png.py`` renders of stored float32
arrays (bit-identity to the offline render is gated by
``scripts/read_smoke.py``), so there is no compile site to register and no
ULP drift to contract.
"""

from __future__ import annotations

import hashlib
import json
import threading
import time
import urllib.parse
from collections import OrderedDict
from pathlib import Path

from ..engine.index import CursorError, SegmentError, SegmentReader
from ..utils import tracing
from ..utils.config import ReadPathConfig
from ..utils.failpoints import failpoint, record_recovery, register_failpoint
from ..utils.logger import logger

FP_READ_CACHE_FILL = register_failpoint(
    "read.cache_fill",
    "between computing a read result and inserting it into the LRU cache")

_ION_IMAGES = "ion_images.npz"


class BadRequest(ValueError):
    """A malformed read request (unknown sort order, bad numeric filter,
    bad tile name) — rendered as a structured 400."""


class ReadCache:
    """Byte- and entry-bounded LRU for read results and tiles.

    Values are opaque (JSON-ready dicts or PNG bytes); the caller supplies
    the byte size at put time.  Eviction is strictly LRU and amortized into
    ``put`` — a get never evicts, so a hit is one lock + one move_to_end.
    """

    # smlint guarded-by registry (docs/ANALYSIS.md)
    _GUARDED_BY = {"_entries": "_lock", "_bytes": "_lock",
                   "_hits": "_lock", "_misses": "_lock",
                   "_evictions": "_lock"}

    def __init__(self, max_bytes: int, max_entries: int):
        self.max_bytes = int(max_bytes)
        self.max_entries = int(max_entries)
        self._lock = threading.Lock()
        self._entries: OrderedDict[tuple, tuple[object, int]] = OrderedDict()
        self._bytes = 0
        self._hits = 0
        self._misses = 0
        self._evictions = 0

    def get(self, key: tuple):
        with self._lock:
            hit = self._entries.get(key)
            if hit is None:
                self._misses += 1
                return None
            self._entries.move_to_end(key)
            self._hits += 1
            return hit[0]

    def put(self, key: tuple, value, size: int) -> None:
        size = int(size)
        if size > self.max_bytes or self.max_entries <= 0:
            return                      # never cache what can't fit at all
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old[1]
            self._entries[key] = (value, size)
            self._bytes += size
            while self._entries and (
                    self._bytes > self.max_bytes
                    or len(self._entries) > self.max_entries):
                _, (_, sz) = self._entries.popitem(last=False)
                self._bytes -= sz
                self._evictions += 1

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._bytes = 0

    def stats(self) -> dict:
        with self._lock:
            return {"entries": len(self._entries), "bytes": self._bytes,
                    "hits": self._hits, "misses": self._misses,
                    "evictions": self._evictions,
                    "max_bytes": self.max_bytes,
                    "max_entries": self.max_entries}


def _q(params, name: str) -> str | None:
    """Last value of a query parameter from a ``parse_qs`` dict (or a plain
    str dict); None when absent/empty."""
    v = params.get(name)
    if isinstance(v, (list, tuple)):
        v = v[-1] if v else None
    return v if v not in (None, "") else None


def _q_float(params, name: str) -> float | None:
    v = _q(params, name)
    if v is None:
        return None
    try:
        return float(v)
    except ValueError as exc:
        raise BadRequest(f"{name} must be a number, got {v!r}") from exc


def _q_int(params, name: str) -> int | None:
    v = _q(params, name)
    if v is None:
        return None
    try:
        return int(v)
    except ValueError as exc:
        raise BadRequest(f"{name} must be an integer, got {v!r}") from exc


class ReadPath:
    """The read-side service: admission, cache, handlers, observability.

    Handlers return ``(status, body, headers)`` where ``body`` is a
    JSON-ready dict or raw PNG bytes — ``AdminAPI`` stays a thin router.
    """

    # smlint guarded-by registry (docs/ANALYSIS.md)
    _GUARDED_BY = {"_inflight": "_lock", "_sheds": "_lock"}

    def __init__(self, results_dir: str | Path,
                 cfg: ReadPathConfig | None = None, *,
                 governor=None, metrics=None, slo=None,
                 disk_dir: str | Path | None = None):
        self.cfg = cfg or ReadPathConfig()
        self.reader = SegmentReader(results_dir)
        self.results_dir = Path(results_dir)
        self.governor = governor
        self.slo = slo
        self.cache = ReadCache(self.cfg.cache_max_bytes,
                               self.cfg.cache_max_entries)
        self.disk_dir = Path(disk_dir) if disk_dir else None
        if self.disk_dir is not None:
            self.disk_dir.mkdir(parents=True, exist_ok=True)
        self._lock = threading.Lock()
        self._inflight = 0
        self._sheds = 0
        self._m = metrics
        if metrics is not None:
            self.m_requests = metrics.counter(
                "sm_read_requests_total",
                "Read-path requests by endpoint and outcome",
                ("endpoint", "outcome"))
            self.m_hits = metrics.counter(
                "sm_read_cache_hits_total",
                "Read-cache hits by kind (tile_disk = on-disk tile tier)",
                ("kind",))
            self.m_misses = metrics.counter(
                "sm_read_cache_misses_total",
                "Read-cache misses by kind", ("kind",))
            self.m_latency = metrics.histogram(
                "sm_read_latency_seconds",
                "Read-path request latency by endpoint (sheds excluded)",
                ("endpoint",))
            self.g_bytes = metrics.gauge(
                "sm_read_cache_bytes", "Bytes held by the read LRU cache")
            self.g_entries = metrics.gauge(
                "sm_read_cache_entries", "Entries held by the read LRU cache")
            self.g_inflight = metrics.gauge(
                "sm_read_inflight", "Reads currently being served")
        else:
            self.m_requests = self.m_hits = self.m_misses = None
            self.m_latency = self.g_bytes = self.g_entries = None
            self.g_inflight = None

    # --------------------------------------------------------- admission
    def _admit(self) -> bool:
        """Read admission, independent of the write-side AdmissionController:
        a storm of reads can never starve submits and vice versa."""
        limit = self.cfg.max_concurrent
        with self._lock:
            if limit > 0 and self._inflight >= limit:
                self._sheds += 1
                return False
            self._inflight += 1
        if self.g_inflight is not None:
            self.g_inflight.inc()
        return True

    def _release(self) -> None:
        with self._lock:
            self._inflight -= 1
        if self.g_inflight is not None:
            self.g_inflight.dec()

    def _shed_reply(self, endpoint: str):
        retry = max(0.0, float(self.cfg.retry_after_s))
        if self.m_requests is not None:
            self.m_requests.labels(endpoint=endpoint, outcome="shed").inc()
        tracing.event("read_shed", endpoint=endpoint,
                      max_concurrent=self.cfg.max_concurrent)
        body = {"accepted": False, "reason": "read_overload",
                "retry_after_s": retry,
                "detail": (f"more than {self.cfg.max_concurrent} reads "
                           "in flight; retry after the indicated delay")}
        return 429, body, {"Retry-After": str(max(1, round(retry)))}

    def _serve(self, endpoint: str, fn):
        """Wrap one handler body: admission, error mapping, metrics, SLO,
        trace event.  ``fn`` returns (status, body, headers)."""
        if not self._admit():
            return self._shed_reply(endpoint)
        t0 = time.monotonic()
        try:
            status, body, headers = fn()
        except (BadRequest, CursorError) as exc:
            status, body, headers = 400, {
                "error": "bad_request", "detail": str(exc)}, {}
        except SegmentError as exc:
            # cannot happen under the atomic-swap protocol — surface loudly
            logger.error("read path hit unreadable segment: %s", exc)
            status, body, headers = 503, {
                "error": "segment_unreadable", "detail": str(exc)}, {}
        finally:
            self._release()
        elapsed = time.monotonic() - t0
        if self.m_latency is not None:
            self.m_latency.labels(endpoint=endpoint).observe(elapsed)
        if self.m_requests is not None:
            self.m_requests.labels(
                endpoint=endpoint,
                outcome="ok" if status < 400 else f"http_{status}").inc()
        if self.slo is not None:
            self.slo.observe_read(elapsed)
        tracing.event("read", endpoint=endpoint, status=status,
                      ms=round(elapsed * 1000.0, 3))
        return status, body, headers

    # ------------------------------------------------------------- cache
    def _count_cache(self, kind: str, hit: bool) -> None:
        c = self.m_hits if hit else self.m_misses
        if c is not None:
            c.labels(kind=kind).inc()

    def _sync_gauges(self) -> None:
        if self.g_bytes is not None:
            s = self.cache.stats()
            self.g_bytes.set(s["bytes"])
            self.g_entries.set(s["entries"])

    def _fill(self, key: tuple, value, size: int,
              path: Path | None = None) -> bool:
        """The governed cache-fill seam: a failed/denied fill must never
        fail the read — the caller already has the value in hand."""
        try:
            if path is not None:
                failpoint(FP_READ_CACHE_FILL, path=path)
            else:
                failpoint(FP_READ_CACHE_FILL)
            if self.governor is not None and \
                    not self.governor.allow_read_cache_fill():
                return False
            self.cache.put(key, value, size)
            self._sync_gauges()
            return True
        except OSError as exc:
            record_recovery("read.cache_fill_failed")
            logger.warning("read cache fill failed for %s: %s", key[0], exc)
            return False

    @staticmethod
    def _validator(path: Path) -> tuple[int, int] | None:
        """File identity for cache keys: changes on every ``os.replace``."""
        try:
            st = path.stat()
        except OSError:
            return None
        return (st.st_mtime_ns, st.st_size)

    # ----------------------------------------------------------- handlers
    def handle_datasets(self):
        """GET /datasets — uncached: listings must reflect every publish."""
        def fn():
            return 200, {"datasets": self.reader.datasets()}, {}
        return self._serve("datasets", fn)

    def handle_annotations(self, ds_id: str, params):
        """GET /datasets/<id>/annotations — filtered/sorted/paginated."""
        def fn():
            limit = _q_int(params, "limit")
            if limit is None:
                limit = self.cfg.page_size
            if not 0 < limit <= self.cfg.page_size_max:
                raise BadRequest(
                    f"limit must be in 1..{self.cfg.page_size_max}")
            kw = dict(
                sf=_q(params, "sf"), adduct=_q(params, "adduct"),
                max_fdr_level=_q_float(params, "fdr"),
                min_msm=_q_float(params, "min_msm"),
                mz_min=_q_float(params, "mz_min"),
                mz_max=_q_float(params, "mz_max"),
                order=_q(params, "order") or "msm",
                direction=_q(params, "dir") or "desc",
                limit=limit, cursor=_q(params, "cursor"))
            validator = self._validator(self.reader.segment_path(ds_id))
            if validator is None:
                return 404, {"error": "not_found",
                             "detail": f"dataset {ds_id} has no published "
                                       "annotations"}, {}
            key = ("annotations", ds_id, validator,
                   tuple(sorted((k, v) for k, v in kw.items()
                                if v is not None)))
            cached = self.cache.get(key)
            self._count_cache("annotations", cached is not None)
            if cached is not None:
                return 200, cached, {}
            result = self.reader.query(ds_id, **kw)
            if result is None:           # raced a first publish's rename
                return 404, {"error": "not_found",
                             "detail": f"dataset {ds_id} has no published "
                                       "annotations"}, {}
            self._fill(key, result, len(json.dumps(result)))
            return 200, result, {}
        return self._serve("annotations", fn)

    def handle_cohort(self, params):
        """GET /annotations?sf=... — per-molecule across every dataset."""
        def fn():
            sf = _q(params, "sf")
            if sf is None:
                raise BadRequest("cohort query requires sf=<formula>")
            kw = dict(adduct=_q(params, "adduct"),
                      max_fdr_level=_q_float(params, "fdr"),
                      min_msm=_q_float(params, "min_msm"))
            validator = tuple(sorted(
                (p.parent.name,) + (self._validator(p) or (0, 0))
                for p in self.results_dir.glob("*/segment.npz")))
            key = ("cohort", sf, validator,
                   tuple(sorted((k, v) for k, v in kw.items()
                                if v is not None)))
            cached = self.cache.get(key)
            self._count_cache("cohort", cached is not None)
            if cached is not None:
                return 200, cached, {}
            result = self.reader.cohort(sf, **kw)
            self._fill(key, result, len(json.dumps(result)))
            return 200, result, {}
        return self._serve("cohort", fn)

    def handle_tile(self, ds_id: str, sf_adduct: str, params):
        """GET /datasets/<id>/images/<sf_adduct> — PNG ion-image tile.

        ``<sf_adduct>`` is the URL-quoted ``sf|adduct`` ion key from the
        stored npz; ``?k=`` selects the isotope peak (default 0, the
        principal peak).  Bytes are exactly ``PngGenerator.render`` over
        the stored array — bit-identical to an offline render.
        """
        def fn():
            ion = urllib.parse.unquote(sf_adduct)
            if "|" not in ion:
                raise BadRequest(
                    f"tile name must be <sf>|<adduct> (url-quoted), "
                    f"got {ion!r}")
            k = _q_int(params, "k") or 0
            npz = self.results_dir / ds_id / _ION_IMAGES
            validator = self._validator(npz)
            if validator is None:
                return 404, {"error": "not_found",
                             "detail": f"dataset {ds_id} has no stored ion "
                                       "images"}, {}
            key = ("tile", ds_id, ion, k, validator)
            cached = self.cache.get(key)
            self._count_cache("tile", cached is not None)
            if cached is not None:
                return 200, cached, {}
            disk = self._tile_disk_path(key)
            if disk is not None:
                try:
                    png = disk.read_bytes()
                except OSError:          # never spilled, or GC-swept
                    png = b""
                if png:                  # empty = torn spill: treat as miss
                    self._count_cache("tile_disk", True)
                    self._fill(key, png, len(png))
                    return 200, png, {}
                self._count_cache("tile_disk", False)
            png = self._render_tile(npz, ion, k)
            if png is None:
                return 404, {"error": "not_found",
                             "detail": f"no ion {ion!r} peak {k} in "
                                       f"dataset {ds_id}"}, {}
            if self._fill(key, png, len(png), path=disk) and disk is not None:
                self._spill_tile(disk, png)
            return 200, png, {}
        return self._serve("tile", fn)

    # ------------------------------------------------------ tile plumbing
    def _tile_disk_path(self, key: tuple) -> Path | None:
        if self.disk_dir is None:
            return None
        digest = hashlib.sha256(repr(key).encode()).hexdigest()[:32]
        return self.disk_dir / f"{digest}.png"

    def _spill_tile(self, disk: Path, png: bytes) -> None:
        """On-disk tile tier fill (survives restarts; swept by the governor
        GC under ``cache_disk_max_bytes``).  tmp + replace so the sweeper
        and readers never see a short file."""
        try:
            tmp = disk.with_name(disk.name + ".tmp")
            tmp.write_bytes(png)
            tmp.replace(disk)
        except OSError as exc:
            record_recovery("read.cache_fill_failed")
            logger.warning("tile spill to %s failed: %s", disk, exc)

    def _render_tile(self, npz: Path, ion: str, k: int) -> bytes | None:
        from ..engine.png import PngGenerator
        from ..engine.storage import SearchResultsStore

        try:
            images, ions = SearchResultsStore.load_ion_images(npz)
        except (OSError, ValueError, KeyError) as exc:
            raise SegmentError(f"unreadable ion images {npz}: {exc}") from exc
        want = tuple(ion.split("|", 1))
        for i, got in enumerate(ions):
            if tuple(got) == want:
                if not 0 <= k < images.shape[1]:
                    return None
                return PngGenerator().render(images[i, k])
        return None

    # ------------------------------------------------------------- status
    def snapshot(self) -> dict:
        """Read-path status for /debug + tests."""
        with self._lock:
            inflight, sheds = self._inflight, self._sheds
        return {"inflight": inflight, "sheds": sheds,
                "cache": self.cache.stats(),
                "disk_dir": str(self.disk_dir) if self.disk_dir else None}
