"""Metrics registry with Prometheus text exposition (stdlib only).

The reference exposes no metrics at all — operators watch Spark UI and
RabbitMQ's management plugin.  The service layer needs its own first-class
observability: counters (monotone totals), gauges (point-in-time values),
and histograms (cumulative buckets, Prometheus semantics), all thread-safe
because scheduler workers record concurrently, plus *collect callbacks* so
existing stat holders (``DatasetResidency.stats``, spool directory depths)
can be scraped without restructuring them into push-style instruments.

Exposition follows the Prometheus text format v0.0.4: ``# HELP`` / ``# TYPE``
headers, ``name{label="value"} 1.0`` samples, histogram ``_bucket{le=...}`` /
``_sum`` / ``_count`` series with a ``+Inf`` bucket.
"""

from __future__ import annotations

import threading
from bisect import bisect_left

# Default buckets span the service's realities: sub-ms fake jobs in tests up
# through multi-hour whole-slide searches (docs/PERF.md: 32 min DESI jobs).
DEFAULT_BUCKETS = (
    0.001, 0.005, 0.025, 0.1, 0.5, 1.0, 5.0, 30.0, 120.0, 600.0, 3600.0,
)


def _fmt_value(v: float) -> str:
    f = float(v)
    return str(int(f)) if f.is_integer() else repr(f)


def _fmt_labels(labels: dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(
        f'{k}="{_escape(v)}"' for k, v in sorted(labels.items())
    )
    return "{" + inner + "}"


def _escape(v: str) -> str:
    return str(v).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


class _Metric:
    """Base: a named family with labelled children."""

    kind = "untyped"

    # smlint guarded-by registry (docs/ANALYSIS.md): the child map may only
    # be mutated under the family lock (scrapes iterate it concurrently)
    _GUARDED_BY = {"_children": "_lock"}

    def __init__(self, name: str, help: str = "", labelnames: tuple[str, ...] = ()):
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._lock = threading.Lock()
        self._children: dict[tuple[str, ...], object] = {}

    def labels(self, **kw):
        if set(kw) != set(self.labelnames):
            raise ValueError(
                f"{self.name}: expected labels {self.labelnames}, got {sorted(kw)}")
        key = tuple(str(kw[n]) for n in self.labelnames)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = self._children[key] = self._make_child()
            return child

    def _default_child(self):
        """Unlabelled metrics act on a single implicit child."""
        if self.labelnames:
            raise ValueError(f"{self.name} requires labels {self.labelnames}")
        return self.labels()

    def _make_child(self):
        raise NotImplementedError

    def _sample_lines(self) -> list[str]:
        raise NotImplementedError

    def expose(self) -> list[str]:
        lines = []
        if self.help:
            lines.append(f"# HELP {self.name} {_escape(self.help)}")
        lines.append(f"# TYPE {self.name} {self.kind}")
        with self._lock:
            lines.extend(self._sample_lines())
        return lines

    def _label_dict(self, key: tuple[str, ...]) -> dict[str, str]:
        return dict(zip(self.labelnames, key))


class _CounterChild:
    __slots__ = ("value", "_lock")
    _GUARDED_BY = {"value": "_lock"}

    def __init__(self):
        self.value = 0.0
        self._lock = threading.Lock()

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError("counters only go up")
        with self._lock:
            self.value += amount


class Counter(_Metric):
    kind = "counter"

    def _make_child(self):
        return _CounterChild()

    def inc(self, amount: float = 1.0) -> None:
        self._default_child().inc(amount)

    def _sample_lines(self) -> list[str]:
        return [
            f"{self.name}{_fmt_labels(self._label_dict(k))} {_fmt_value(c.value)}"
            for k, c in sorted(self._children.items())
        ]


class _GaugeChild:
    __slots__ = ("value", "_lock")
    _GUARDED_BY = {"value": "_lock"}

    def __init__(self):
        self.value = 0.0
        self._lock = threading.Lock()

    def set(self, value: float) -> None:
        with self._lock:
            self.value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self.value += amount

    def dec(self, amount: float = 1.0) -> None:
        self.inc(-amount)


class Gauge(_Metric):
    kind = "gauge"

    def _make_child(self):
        return _GaugeChild()

    def set(self, value: float) -> None:
        self._default_child().set(value)

    def inc(self, amount: float = 1.0) -> None:
        self._default_child().inc(amount)

    def dec(self, amount: float = 1.0) -> None:
        self._default_child().dec(amount)

    def _sample_lines(self) -> list[str]:
        return [
            f"{self.name}{_fmt_labels(self._label_dict(k))} {_fmt_value(c.value)}"
            for k, c in sorted(self._children.items())
        ]


class _HistogramChild:
    __slots__ = ("buckets", "counts", "sum", "count", "_lock")
    # counts/sum/count move together; a torn view renders +Inf < a finite
    # bucket (the ISSUE 6 scrape-vs-observe fix this registry pins)
    _GUARDED_BY = {"counts": "_lock", "sum": "_lock", "count": "_lock"}

    def __init__(self, buckets: tuple[float, ...]):
        self.buckets = buckets
        self.counts = [0] * len(buckets)   # per-bucket (non-cumulative) counts
        self.sum = 0.0
        self.count = 0
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        i = bisect_left(self.buckets, value)
        with self._lock:
            if i < len(self.buckets):
                self.counts[i] += 1
            self.sum += value
            self.count += 1

    def snapshot(self) -> tuple[list[int], float, int]:
        """Consistent (counts, sum, count) under the child lock — a scrape
        racing ``observe`` must never see counts updated but count not
        (that renders a +Inf bucket SMALLER than a finite one)."""
        with self._lock:
            return list(self.counts), self.sum, self.count

    def merge(self, counts: list[int], sum_: float, count: int) -> None:
        """Fold another child's snapshot into this one.  Bucket counts are
        integers, so merging is exact: merged counts equal observing the
        union of both sample sets (the fleet-view equivalence the property
        test pins).  The float ``sum`` is added once per merge — the same
        order-of-one addition a single observer would have performed."""
        if len(counts) != len(self.buckets):
            raise ValueError(
                f"histogram merge: {len(counts)} bucket counts into "
                f"{len(self.buckets)} buckets")
        with self._lock:
            for i, n in enumerate(counts):
                self.counts[i] += n
            self.sum += sum_
            self.count += count

    def fraction_below(self, threshold: float) -> tuple[float, int]:
        """(fraction of observations <= threshold, total count) — the SLO
        attainment primitive.  Exact at bucket boundaries; inside a bucket
        the fraction interpolates linearly (observations beyond the last
        finite bucket count only toward the denominator)."""
        counts, _sum, total = self.snapshot()
        if total == 0:
            return 0.0, 0
        below = 0.0
        lo = 0.0
        for le, n in zip(self.buckets, counts):
            if threshold >= le:
                below += n
            elif threshold > lo:
                below += n * (threshold - lo) / (le - lo)
                break
            else:
                break
            lo = le
        return min(1.0, below / total), total


class Histogram(_Metric):
    kind = "histogram"

    def __init__(self, name, help="", labelnames=(), buckets=DEFAULT_BUCKETS):
        super().__init__(name, help, labelnames)
        self.buckets = tuple(sorted(buckets))

    def _make_child(self):
        return _HistogramChild(self.buckets)

    def observe(self, value: float) -> None:
        self._default_child().observe(value)

    def _sample_lines(self) -> list[str]:
        lines = []
        for key, c in sorted(self._children.items()):
            base = self._label_dict(key)
            counts, total_sum, count = c.snapshot()
            cum = 0
            for le, n in zip(c.buckets, counts):
                cum += n
                lines.append(
                    f"{self.name}_bucket{_fmt_labels({**base, 'le': _fmt_value(le)})} {cum}")
            lines.append(
                f"{self.name}_bucket{_fmt_labels({**base, 'le': '+Inf'})} {count}")
            lines.append(f"{self.name}_sum{_fmt_labels(base)} {_fmt_value(total_sum)}")
            lines.append(f"{self.name}_count{_fmt_labels(base)} {count}")
        return lines

    def merge(self, other: "Histogram") -> None:
        """Fold ``other``'s children into this family, creating children for
        label sets seen only on ``other``.  Equivalent to having observed the
        union of both families' samples: bucket counts and totals add as
        integers, sums add once per child.  Bucket boundaries must match —
        merging across different schemas has no exact meaning."""
        if tuple(other.buckets) != tuple(self.buckets):
            raise ValueError(
                f"histogram merge: bucket mismatch {other.buckets} vs "
                f"{self.buckets}")
        with other._lock:
            src = list(other._children.items())
        for key, child in src:
            counts, sum_, count = child.snapshot()
            with self._lock:
                dst = self._children.get(key)
                if dst is None:
                    dst = self._children[key] = self._make_child()
            dst.merge(counts, sum_, count)

    def fraction_below(self, threshold: float) -> tuple[float, int]:
        """Aggregate ``fraction_below`` across all children (SLO helper)."""
        with self._lock:
            children = list(self._children.values())
        below = total = 0
        for c in children:
            f, n = c.fraction_below(threshold)
            below += f * n
            total += n
        return (below / total if total else 0.0), total


def rate_collector(registry: "MetricsRegistry", name: str, help: str,
                   count_fn) -> None:
    """Register a scrape-time collector that derives a per-second rate gauge
    from a monotone count supplier ``count_fn()``.

    Prometheus clients usually rate() counters server-side, but the engine's
    in-process consumers (admin API, chaos drivers, the isocalc progress
    line) want a ready-made gauge: the value is the count delta since the
    previous scrape divided by the elapsed wall time (0 on the first scrape
    or when time stands still)."""
    import time

    state = {"count": None, "t": None}

    def collect(reg: "MetricsRegistry") -> None:
        now = time.monotonic()
        count = float(count_fn())
        prev_c, prev_t = state["count"], state["t"]
        rate = 0.0
        if prev_c is not None and now > prev_t:
            rate = max(0.0, count - prev_c) / (now - prev_t)
        state["count"], state["t"] = count, now
        reg.gauge(name, help).set(rate)

    registry.add_collector(collect)


def build_info_collector(registry: "MetricsRegistry", backend: str) -> None:
    """``sm_build_info{version=,jax_version=,backend=} 1`` — the constant
    gauge dashboards join on (the Prometheus build-info idiom).  Versions
    come from installed-package metadata so no heavy import happens at
    scrape time."""
    from importlib import metadata

    def _ver(dist: str, fallback: str) -> str:
        try:
            return metadata.version(dist)
        except metadata.PackageNotFoundError:
            return fallback

    version = _ver("sm-distributed-tpu", "dev")
    if version == "dev":
        try:
            from .. import __version__ as version  # source checkout
        except ImportError:
            pass
    jax_version = _ver("jax", "unknown")
    registry.gauge("sm_build_info",
                   "Build identity (constant 1; the labels are the data)",
                   ("version", "jax_version", "backend")).labels(
        version=version, jax_version=jax_version, backend=backend).set(1)


def process_collector(registry: "MetricsRegistry") -> None:
    """Scrape-time process gauges: RSS bytes, thread count, open FDs —
    the leak signals (ISSUE 5 satellite) the load sweep only catches in
    tests.  /proc is preferred; platforms without it fall back to
    ``resource`` for RSS and skip the FD gauge."""
    import os

    def collect(reg: "MetricsRegistry") -> None:
        rss = 0.0
        try:
            with open("/proc/self/statm") as f:
                rss = float(f.read().split()[1]) * os.sysconf("SC_PAGE_SIZE")
        except (OSError, IndexError, ValueError):
            try:
                import resource

                # ru_maxrss is KiB on Linux (peak, not current — still a
                # usable leak signal on /proc-less platforms)
                rss = float(resource.getrusage(
                    resource.RUSAGE_SELF).ru_maxrss) * 1024.0
            except (ImportError, OSError, ValueError):
                pass                  # no RSS source at all: gauge omitted
        if rss:
            reg.gauge("sm_process_resident_memory_bytes",
                      "Resident set size of the service process").set(rss)
        reg.gauge("sm_process_threads",
                  "Live threads in the service process").set(
            threading.active_count())
        try:
            n_fds = len(os.listdir("/proc/self/fd"))
        except OSError:
            n_fds = 0
        if n_fds:
            reg.gauge("sm_process_open_fds",
                      "Open file descriptors in the service process").set(
                n_fds)

    registry.add_collector(collect)


class MetricsRegistry:
    """Registry: owns metric families + scrape-time collect callbacks."""

    # smlint guarded-by registry (docs/ANALYSIS.md)
    _GUARDED_BY = {"_metrics": "_lock", "_collectors": "_lock"}

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: dict[str, _Metric] = {}
        self._collectors: list = []
        # exception-safe collector dispatch (ISSUE 6 satellite): one broken
        # callback must not break the scrape OR starve the collectors after
        # it, and the failure count itself is a scrapable signal
        self._collect_errors = self.counter(
            "sm_metrics_collect_errors_total",
            "Collect callbacks that raised during a /metrics scrape",
            ("collector",))

    def _register(self, metric: _Metric) -> _Metric:
        with self._lock:
            existing = self._metrics.get(metric.name)
            if existing is not None:
                if type(existing) is not type(metric):
                    raise ValueError(
                        f"metric {metric.name} re-registered with a different type")
                return existing
            self._metrics[metric.name] = metric
            return metric

    def counter(self, name, help="", labelnames=()) -> Counter:
        return self._register(Counter(name, help, labelnames))

    def gauge(self, name, help="", labelnames=()) -> Gauge:
        return self._register(Gauge(name, help, labelnames))

    def histogram(self, name, help="", labelnames=(), buckets=DEFAULT_BUCKETS) -> Histogram:
        return self._register(Histogram(name, help, labelnames, buckets))

    def value(self, name: str) -> float | None:
        """Summed child values of an existing counter/gauge family, or
        ``None`` when the family was never registered — the scrape-free
        read the telemetry snapshot ring uses."""
        with self._lock:
            m = self._metrics.get(name)
        if not isinstance(m, (Counter, Gauge)):
            return None
        with m._lock:
            children = list(m._children.values())
        return float(sum(c.value for c in children))

    def add_collector(self, fn) -> None:
        """``fn(registry)`` runs at each scrape BEFORE exposition — the hook
        that pulls ``DatasetResidency.stats`` / spool depths into gauges."""
        with self._lock:
            self._collectors.append(fn)

    def expose(self) -> str:
        with self._lock:
            collectors = list(self._collectors)
        for fn in collectors:
            try:
                fn(self)
            except Exception:  # a broken collector must not kill /metrics
                from ..utils.logger import logger

                name = getattr(fn, "__qualname__",
                               getattr(fn, "__name__", repr(fn)))
                self._collect_errors.labels(collector=str(name)[:80]).inc()
                logger.warning("metrics collector %r failed", fn, exc_info=True)
        with self._lock:
            metrics = [self._metrics[k] for k in sorted(self._metrics)]
        out = []
        for m in metrics:
            out.extend(m.expose())
        return "\n".join(out) + "\n"
