"""Quantitative telemetry: device/HBM monitor, SLO tracker, snapshot ring.

ISSUE 6 tentpole.  PR 5 gave every job a *trace* (causality); this module
adds the *quantities* the ROADMAP's scale-out items need eyes on:

- **DeviceMonitor** — a sampling thread reading per-device HBM
  bytes-in-use / peak (``utils/devicemem.py``; ``None``-safe on CPU), the
  scheduler's device-token occupancy (fraction of recent samples that
  found the TPU token held — the single-token serialization bottleneck
  item 1 replaces), XLA persistent-cache size, and process RSS.  Every
  sample updates gauges on the shared ``MetricsRegistry`` AND lands in a
  bounded in-memory **time-series ring** served by ``GET
  /debug/timeseries`` — a scrape-free flight recorder for quantities, the
  same idea ``GET /debug/events`` is for spans.  The monitor also installs
  a ``phase_timer`` observer so every traced job phase records its peak
  HBM (gauge ``sm_phase_hbm_peak_bytes{phase=}`` + an ``hbm`` trace
  event) without the engine importing the service layer.

- **SLOTracker** — first-class SLO instrumentation: histograms for
  queue-wait (submit → first attempt start), submit → first annotation
  (the first scored checkpoint group; ``models/msm_basic.py`` notifies
  through a module-level observer list, same pattern as phase observers),
  and end-to-end latency (submit → terminal outcome), recorded at the
  scheduler's seams.  ``report()`` computes attainment against the
  configured objectives straight from the histogram buckets
  (``Histogram.fraction_below``) plus the error-budget burn rate —
  ``GET /slo``.

Config: ``SMConfig.telemetry`` (enabled, sample_interval_s,
timeseries_len, slo_* objectives).  Docs: docs/OBSERVABILITY.md.
"""

from __future__ import annotations

import os
import sys
import threading
import time
from collections import deque
from pathlib import Path

from ..utils import devicemem, tracing
from ..utils.config import TelemetryConfig
from ..utils.logger import add_phase_observer, logger, remove_phase_observer

# occupancy is the mean of the most recent N token samples — at the default
# 5 s cadence this is a ~5 min sliding window, long enough to smooth one
# job's hold/release flapping, short enough to show a saturation trend
_OCCUPANCY_WINDOW = 60


class DeviceMonitor:
    """Sample device/HBM/cache/occupancy state into gauges + a ring."""

    # smlint guarded-by registry (docs/ANALYSIS.md): the snapshot ring is
    # appended by the sampling thread and listed by HTTP handlers; _occ is
    # deliberately sampling-thread-private (no lock declared)
    _GUARDED_BY = {"_ring": "_lock"}

    def __init__(self, registry, cfg: TelemetryConfig | None = None,
                 device_token=None, queue_root: str | Path | None = None,
                 compile_cache_dir: str | Path | None = None,
                 device_pool=None, replica_id: str = "",
                 readpath=None, stream_ingest=None):
        self.registry = registry
        self.cfg = cfg or TelemetryConfig()
        # replica identity (ISSUE 8): stamped on every timeseries sample so
        # a dashboard merging N replicas' /debug/timeseries can tell the
        # streams apart
        self.replica_id = replica_id
        # the scheduler's device pool (service/device_pool.py) — or, for
        # legacy callers, the old single TPU token (threading.Lock).  A
        # pool passed via ``device_token`` (the pool speaks the Lock
        # protocol) is recognized by duck-typing.  Sampled, never taken.
        if device_pool is None and hasattr(device_token, "per_device_in_use"):
            device_pool, device_token = device_token, None
        self.device_pool = device_pool
        self.device_token = device_token
        self.queue_root = Path(queue_root) if queue_root else None
        self.compile_cache_dir = (Path(compile_cache_dir)
                                  if compile_cache_dir else None)
        # PR 16/19 planes (ISSUE 20 satellite): the read path's cache /
        # in-flight state and the stream ingest's chunk counters sample
        # into the ring too, so fleet status can chart them over time
        self.readpath = readpath
        self.stream_ingest = stream_ingest
        self._ring: deque = deque(maxlen=self.cfg.timeseries_len)
        self._occ: deque = deque(maxlen=_OCCUPANCY_WINDOW)
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._prev_cache_entries: int | None = None
        m = registry
        self.g_hbm_in_use = m.gauge(
            "sm_device_hbm_bytes_in_use",
            "HBM bytes currently allocated, per device", ("device",))
        self.g_hbm_peak = m.gauge(
            "sm_device_hbm_peak_bytes",
            "Peak HBM bytes allocated since process start, per device",
            ("device",))
        self.g_hbm_limit = m.gauge(
            "sm_device_hbm_limit_bytes",
            "HBM capacity available to the allocator, per device",
            ("device",))
        self.g_devices = m.gauge(
            "sm_device_count", "Local accelerator devices visible to jax")
        self.g_occupancy = m.gauge(
            "sm_device_token_occupancy_ratio",
            "Fraction of recent samples that found the device token held "
            "(with a device pool: windowed mean of the pool-wide in-use "
            "ratio)")
        self.g_pool_ratio = m.gauge(
            "sm_device_pool_occupancy_ratio",
            "Fraction of pool chips currently held by job leases")
        self.g_phase_hbm = m.gauge(
            "sm_phase_hbm_peak_bytes",
            "Peak HBM observed at each pipeline phase's exit", ("phase",))
        self.g_cache_entries = m.gauge(
            "sm_xla_cache_entries",
            "Executable entries in the persistent XLA compile cache")
        self.g_cache_bytes = m.gauge(
            "sm_xla_cache_bytes",
            "Total size of the persistent XLA compile cache")
        self.c_cache_miss = m.counter(
            "sm_xla_cache_misses_total",
            "Cold compiles observed as new persistent-cache entries")
        self.c_warmup_cache = m.counter(
            "sm_xla_cache_warmup_total",
            "Backend warmups by persistent-cache outcome (hit = manifest "
            "proved warm, executions skipped)", ("result",))

    # ------------------------------------------------------------- sampling
    def _cache_stats(self) -> tuple[int | None, int | None]:
        """(entry count, total bytes) of the persistent XLA cache, or
        (None, None) when no cache dir is configured/present.  Counts only
        real ``jit_*`` executable entries (the bench.py rule), so growth
        strictly implies cold compiles."""
        d = self.compile_cache_dir
        if d is None or not d.is_dir():
            return None, None
        import re

        entry_re = re.compile(r"^jit_.+-[0-9a-f]{32,}(-cache)?$")
        n = size = 0
        try:
            for p in d.iterdir():
                if p.is_file() and entry_re.match(p.name):
                    n += 1
                    size += p.stat().st_size
        except OSError:
            return None, None
        return n, size

    def sample(self) -> dict:
        """Take one snapshot: update every gauge and append to the ring."""
        now = time.time()
        devices = devicemem.device_stats()
        hbm_in_use = hbm_peak = None
        for d in devices:
            label = f"{d['id']}:{d['kind']}"
            if d["bytes_in_use"] is not None:
                self.g_hbm_in_use.labels(device=label).set(d["bytes_in_use"])
                hbm_in_use = (hbm_in_use or 0) + d["bytes_in_use"]
            if d["peak_bytes"] is not None:
                self.g_hbm_peak.labels(device=label).set(d["peak_bytes"])
                hbm_peak = max(hbm_peak or 0, d["peak_bytes"])
            if d["limit_bytes"] is not None:
                self.g_hbm_limit.labels(device=label).set(d["limit_bytes"])
        self.g_devices.set(len(devices))

        locked = None
        pool_snap = None
        if self.device_pool is not None:
            # half-open device recovery (ISSUE 14): quarantined chips past
            # their cooldown are re-probed on the sampling cadence too, so
            # an idle service readmits recovered chips without waiting for
            # the next lease to trigger it
            health = getattr(self.device_pool, "health", None)
            if health is not None:
                try:
                    health.reprobe_due()
                except Exception:
                    logger.warning("telemetry: device re-probe failed",
                                   exc_info=True)
            # per-chip pool occupancy (ISSUE 7 satellite): the pool updates
            # its own sm_device_pool_in_use{device=} gauge at grant/release
            # (event-exact); here we sample the pool-WIDE ratio into the
            # window + ring so /debug/timeseries shows the saturation trend
            pool_snap = self.device_pool.snapshot()
            ratio = pool_snap["in_use"] / max(1, pool_snap["size"])
            locked = pool_snap["in_use"] >= pool_snap["size"]
            self.g_pool_ratio.set(ratio)
            self._occ.append(ratio)
            occupancy = sum(self._occ) / len(self._occ)
            self.g_occupancy.set(occupancy)
        elif self.device_token is not None:
            locked = bool(self.device_token.locked())
            self._occ.append(1.0 if locked else 0.0)
            occupancy = sum(self._occ) / len(self._occ)
            self.g_occupancy.set(occupancy)
        else:
            occupancy = None

        entries, cache_bytes = self._cache_stats()
        if entries is not None:
            self.g_cache_entries.set(entries)
            self.g_cache_bytes.set(cache_bytes or 0)
            if self._prev_cache_entries is not None and \
                    entries > self._prev_cache_entries:
                self.c_cache_miss.inc(entries - self._prev_cache_entries)
            self._prev_cache_entries = entries
        self._collect_warmup_events()

        # pod identity (ISSUE 17): samples from different host processes
        # interleave in shared dashboards — stamp which process took each
        proc_id, proc_host = tracing.process()
        snap = {
            "ts": round(now, 3),
            **({"replica": self.replica_id} if self.replica_id else {}),
            **({"process": proc_id} if proc_id >= 0 else {}),
            **({"host": proc_host} if proc_host else {}),
            "devices": len(devices),
            "device_kind": devices[0]["kind"] if devices else None,
            "hbm_bytes_in_use": hbm_in_use,
            "hbm_peak_bytes": hbm_peak,
            "device_token_locked": locked,
            "device_token_occupancy": (round(occupancy, 4)
                                       if occupancy is not None else None),
            "xla_cache_entries": entries,
            "xla_cache_bytes": cache_bytes,
            "rss_bytes": _rss_bytes(),
        }
        if pool_snap is not None:
            snap["device_pool_size"] = pool_snap["size"]
            snap["device_pool_hosts"] = pool_snap.get("hosts", 1)
            snap["device_pool_per_host_in_use"] = pool_snap.get(
                "per_host_in_use")
            snap["device_pool_in_use"] = pool_snap["in_use"]
            snap["device_pool_ratio"] = round(
                pool_snap["in_use"] / max(1, pool_snap["size"]), 4)
            snap["device_pool_waiters"] = pool_snap["waiters"]
            snap["device_pool_grants_total"] = pool_snap["grants_total"]
            # chip-level health roll-up (ISSUE 14, service/health.py):
            # state counts + the fenced chip list, so /debug/timeseries
            # shows quarantines/readmits as a trend without scraping
            health = pool_snap.get("health")
            if health is not None:
                snap["device_health_ok"] = health["ok"]
                snap["device_health_suspect"] = health["suspect"]
                snap["device_health_quarantined"] = health["quarantined"]
                snap["device_quarantined"] = [
                    c["device"] for c in health["chips"]
                    if c["state"] == "quarantined"]
                snap["device_quarantines_total"] = (
                    health["quarantines_total"])
        if self.queue_root is not None:
            try:
                snap["queue_pending"] = len(
                    list(self.queue_root.glob("pending/*.json")))
                snap["queue_running"] = len(
                    list(self.queue_root.glob("running/*.json")))
            except OSError:
                pass
        # PR 16 read plane (ISSUE 20 satellite): cache + in-flight state,
        # so /debug/timeseries charts read saturation beside device state
        if self.readpath is not None:
            rp = self.readpath.snapshot()
            cache = rp.get("cache", {})
            snap["read_inflight"] = rp.get("inflight")
            snap["read_sheds"] = rp.get("sheds")
            snap["read_cache_hits"] = cache.get("hits")
            snap["read_cache_misses"] = cache.get("misses")
            snap["read_cache_bytes"] = cache.get("bytes")
            snap["read_cache_entries"] = cache.get("entries")
        # PR 19 stream plane: chunk/pixel/re-rank totals (from the shared
        # registry) + acquisitions currently open on the shared stream root
        if self.stream_ingest is not None:
            snap["stream_chunks_total"] = self.registry.value(
                "sm_stream_chunks_total")
            snap["stream_pixels_total"] = self.registry.value(
                "sm_stream_pixels_total")
            snap["stream_reranks_total"] = self.registry.value(
                "sm_stream_reranks_total")
            try:
                snap["stream_in_flight"] = self.stream_ingest.in_flight()
            except OSError:
                pass
        with self._lock:
            self._ring.append(snap)
        return snap

    def _collect_warmup_events(self) -> None:
        """Pull warmup cache hit/miss counts from the jax backend module —
        lazily, ONLY if it was ever imported (a CPU-only service never pays
        for it).  Counters move by delta, same as the residency collector."""
        mod = sys.modules.get("sm_distributed_tpu.models.msm_jax")
        if mod is None or not hasattr(mod, "warmup_cache_events"):
            return
        for result, count in mod.warmup_cache_events().items():
            child = self.c_warmup_cache.labels(result=result)
            child.inc(max(0.0, count - child.value))

    def timeseries(self, n: int | None = None) -> list[dict]:
        with self._lock:
            items = list(self._ring)
        return items if n is None else items[-max(0, int(n)):]

    # ------------------------------------------------------ phase HBM hook
    def _observe_phase(self, phase: str, seconds: float) -> None:
        """phase_timer observer: record peak HBM at every phase exit (gauge
        + an ``hbm`` event on the job's ambient trace).  No-op on platforms
        without memory stats."""
        peak = devicemem.hbm_peak_bytes()
        if peak is None:
            return
        self.g_phase_hbm.labels(phase=phase).set(peak)
        tracing.event("hbm", phase=phase, peak_bytes=peak)

    # ------------------------------------------------------------ lifecycle
    def _loop(self) -> None:
        while not self._stop.wait(self.cfg.sample_interval_s):
            try:
                self.sample()
            except Exception:  # telemetry must never kill the service
                logger.warning("telemetry sample failed", exc_info=True)

    def start(self) -> None:
        if self._thread is not None:
            return
        add_phase_observer(self._observe_phase)
        self.sample()                     # the ring is never empty once up
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="telemetry-monitor")
        self._thread.start()

    def stop(self) -> None:
        remove_phase_observer(self._observe_phase)
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None


def _rss_bytes() -> int | None:
    try:
        with open("/proc/self/statm") as f:
            return int(f.read().split()[1]) * os.sysconf("SC_PAGE_SIZE")
    except (OSError, IndexError, ValueError):
        return None


# ------------------------------------------------------------------- SLOs
class SLOTracker:
    """Latency SLIs as histograms + attainment/error-budget reporting.

    Four objectives (``SMConfig.telemetry.slo_*``), each "fraction of
    observations under T seconds >= target".  The scheduler records
    queue-wait at each
    job's FIRST attempt start and end-to-end latency at every terminal
    outcome; ``models/msm_basic.py`` notifies the first scored checkpoint
    group through its first-annotation observer list (the moment the first
    FDR-rankable metrics exist — the ROADMAP item 3 time-to-first-result
    measure).  Attainment comes from the histogram buckets themselves, so
    ``/slo`` and ``/metrics`` can never disagree.
    """

    # smlint guarded-by registry (docs/ANALYSIS.md)
    _GUARDED_BY = {"_submits": "_lock", "_first_noted": "_lock"}

    def __init__(self, registry, cfg: TelemetryConfig | None = None):
        self.cfg = cfg or TelemetryConfig()
        self.h_queue_wait = registry.histogram(
            "sm_slo_queue_wait_seconds",
            "Submit -> first attempt start, per job")
        self.h_first_annotation = registry.histogram(
            "sm_slo_first_annotation_seconds",
            "Submit -> first scored checkpoint group, per job")
        self.h_e2e = registry.histogram(
            "sm_slo_e2e_seconds",
            "Submit -> terminal outcome, per job (all outcomes)")
        self.h_read = registry.histogram(
            "sm_slo_read_seconds",
            "Read-path request latency (annotations/cohort/tile GETs)")
        self.h_stream_partial = registry.histogram(
            "sm_slo_stream_partial_seconds",
            "Chunk commit -> provisional re-rank published, per re-rank")
        self._lock = threading.Lock()
        self._submits: dict[str, float] = {}     # job_id -> submit epoch
        self._first_noted: set[str] = set()

    # ------------------------------------------------------ recording seams
    def job_started(self, job_id: str, submit_ts: float,
                    attempt_start: float, attempt: int) -> None:
        """Scheduler seam: an attempt is starting.  Queue wait is observed
        once per job (first attempt only — retries are failure latency and
        belong to e2e, not to admission)."""
        with self._lock:
            self._submits[job_id] = submit_ts
        if attempt == 1:
            self.h_queue_wait.observe(max(0.0, attempt_start - submit_ts))

    def note_first_annotation(self, job_id: str = "") -> None:
        """msm_basic observer: the first checkpoint group finished scoring.
        ``job_id`` defaults to the ambient trace context's (the scoring
        thread runs under the attempt span).  Unknown jobs (offline CLI
        runs never registered by a scheduler) are ignored."""
        if not job_id:
            ctx = tracing.current()
            job_id = ctx.job_id if ctx is not None else ""
        if not job_id:
            return
        with self._lock:
            submit_ts = self._submits.get(job_id)
            if submit_ts is None or job_id in self._first_noted:
                return
            self._first_noted.add(job_id)
        self.h_first_annotation.observe(max(0.0, time.time() - submit_ts))

    def observe_read(self, seconds: float) -> None:
        """Read-path seam (service/readpath.py): one served read — sheds
        (429) are excluded; they are admission outcomes, not latency."""
        self.h_read.observe(max(0.0, seconds))

    def observe_stream_partial(self, seconds: float) -> None:
        """Streaming seam (ISSUE 19): one provisional re-rank became
        visible on the partial channel, ``seconds`` after the newest chunk
        it covers was committed to the acquisition manifest."""
        self.h_stream_partial.observe(max(0.0, seconds))

    def observe_terminal(self, job_id: str, state: str,
                         submit_ts: float) -> None:
        """Scheduler seam: terminal outcome — close out the job."""
        self.h_e2e.observe(max(0.0, time.time() - submit_ts))
        with self._lock:
            self._submits.pop(job_id, None)
            self._first_noted.discard(job_id)

    # -------------------------------------------------------------- report
    def report(self) -> dict:
        """The ``GET /slo`` body: per-SLI objective, attainment computed
        from the live histogram, and error-budget burn (attained shortfall
        over the allowed shortfall; >= 1.0 means the budget is exhausted
        at the current rate)."""
        target = self.cfg.slo_target
        out = {"target": target, "slos": {}}
        for name, hist, objective_s in (
                ("queue_wait", self.h_queue_wait, self.cfg.slo_queue_wait_s),
                ("first_annotation", self.h_first_annotation,
                 self.cfg.slo_first_annotation_s),
                ("e2e", self.h_e2e, self.cfg.slo_e2e_s),
                ("read", self.h_read, self.cfg.slo_read_s),
                ("stream_partial", self.h_stream_partial,
                 self.cfg.slo_stream_partial_s)):
            attained, count = hist.fraction_below(objective_s)
            entry = {
                "objective_s": objective_s,
                "target": target,
                "count": count,
                "attainment": round(attained, 6) if count else None,
                "violations": (round((1.0 - attained) * count)
                               if count else 0),
                "error_budget_burn": (
                    round((1.0 - attained) / (1.0 - target), 4)
                    if count else None),
            }
            out["slos"][name] = entry
        return out
