"""Fleet observability plane: cross-replica aggregation + device profiling.

ISSUE 20 tentpole.  The engine is a pod (PRs 8/11/17/19) but every
observability surface was strictly per-replica — answering "is the FLEET
meeting its SLOs" meant hand-merging N scrapes.  This module puts the
single pane on the serving replica:

- **FleetView** — discovers live peers through the PR 8 ``ReplicaRegistry``
  (admin addresses are gossiped in registry heartbeats, wired by
  ``server.py`` through ``JobScheduler.add_gossip``), scrapes each peer's
  ``/metrics`` over HTTP with a bounded per-peer budget, and merges the
  expositions: **counters summed**, **gauges re-labelled** ``{replica=}``
  (a point-in-time value has no meaningful cross-replica sum), and
  **histograms bucket-merged** through ``Histogram.merge`` — provably
  equivalent to observing the union of all replicas' samples (the property
  test in tests/test_metrics_exposition.py).  Served as:

  - ``GET /fleet/metrics`` — the merged exposition;
  - ``GET /fleet/slo``     — attainment / error-budget burn for all five
    SLIs computed from the MERGED buckets with the exact ``SLOTracker``
    arithmetic, so the fleet number is what one tracker would have
    reported had it observed every replica's jobs;
  - ``GET /fleet/status``  — replicas (beat age, shard ownership, drain
    state, gossiped admin address / pool occupancy / in-flight stream
    acquisitions), hosts and evictions, plus this round's scrape evidence.

  Failure model: a peer that dies mid-scrape (or answers slower than
  ``service.fleetview.scrape_timeout_s``) degrades the view to
  *partial-with-evidence* — its error lands in
  ``sm_fleetview_scrape_errors_total{replica=}`` and in the response's
  ``scrape_errors`` block — and stale peers (no fresh heartbeat) are
  listed but never scraped.  The fleet endpoints themselves never 500 for
  a peer failure.

- **DeviceProfiler** — ``GET /debug/profile?seconds=`` runs a
  ``jax.profiler`` capture around whatever the scheduler has in flight
  (single-flight: concurrent requests get 409), attributes per-kernel
  device time through ``analysis/profiling.py`` (fused Pallas scoring
  kernel vs gather/segment-sum chain vs transfers), and injects
  ``device_kernel`` spans into every RUNNING job's trace so Perfetto shows
  host spans and device kernels on one timeline.

Config: ``service.fleetview`` + ``telemetry.profile``.  Docs:
docs/OBSERVABILITY.md ("Fleet plane", "Device profiles").
"""

from __future__ import annotations

import threading
import time
import urllib.request
from pathlib import Path

from ..utils import tracing
from ..utils.config import FleetViewConfig, ProfileConfig
from ..utils.logger import logger
from .metrics import Histogram, MetricsRegistry

# ------------------------------------------------------- exposition parsing
def _parse_labels(body: str) -> dict[str, str]:
    """Parse the inside of a ``{...}`` label block, honoring the text
    format's escapes (``\\\\``, ``\\"``, ``\\n``)."""
    labels: dict[str, str] = {}
    i = 0
    n = len(body)
    while i < n:
        eq = body.index("=", i)
        key = body[i:eq].strip()
        if body[eq + 1] != '"':
            raise ValueError(f"unquoted label value after {key!r}")
        j = eq + 2
        buf: list[str] = []
        while body[j] != '"':
            ch = body[j]
            if ch == "\\":
                nxt = body[j + 1]
                buf.append({"\\": "\\", '"': '"', "n": "\n"}.get(nxt, nxt))
                j += 2
            else:
                buf.append(ch)
                j += 1
        labels[key] = "".join(buf)
        i = j + 1
        if i < n and body[i] == ",":
            i += 1
    return labels


def parse_exposition(text: str) -> dict[str, dict]:
    """Parse Prometheus text-format v0.0.4 back into families::

        {family: {"kind": str, "help": str,
                  "samples": [(suffix, labels, value)]}}

    where ``suffix`` is ``""`` for plain samples and ``"_bucket"`` /
    ``"_sum"`` / ``"_count"`` for histogram series.  Lines that fail to
    parse are skipped (a half-written peer response must not take down the
    merge — partial evidence beats no view)."""
    families: dict[str, dict] = {}

    def fam(name: str) -> dict:
        return families.setdefault(
            name, {"kind": "untyped", "help": "", "samples": []})

    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            if line.startswith("# TYPE "):
                _, _, rest = line.partition("# TYPE ")
                name, _, kind = rest.partition(" ")
                fam(name)["kind"] = kind.strip()
                continue
            if line.startswith("# HELP "):
                _, _, rest = line.partition("# HELP ")
                name, _, help_ = rest.partition(" ")
                fam(name)["help"] = help_
                continue
            if line.startswith("#"):
                continue
            series, _, value_s = line.rpartition(" ")
            brace = series.find("{")
            if brace >= 0:
                sname = series[:brace]
                labels = _parse_labels(series[brace + 1:series.rindex("}")])
            else:
                sname, labels = series, {}
            value = float(value_s)
            # histogram series belong to their base family
            name, suffix = sname, ""
            for suf in ("_bucket", "_sum", "_count"):
                base = sname[:-len(suf)]
                if sname.endswith(suf) and \
                        families.get(base, {}).get("kind") == "histogram":
                    name, suffix = base, suf
                    break
            fam(name)["samples"].append((suffix, labels, value))
        except (ValueError, IndexError):
            continue
    return families


def merge_expositions(scrapes: dict[str, str]) -> MetricsRegistry:
    """Merge per-replica exposition texts into one registry: counters
    summed across replicas, gauges re-labelled ``{replica=}``, histograms
    bucket-merged (integer counts add exactly — equivalent to observing
    the union of samples).  Families whose shape disagrees between
    replicas (label sets, bucket boundaries — impossible from one
    codebase, possible from a half-upgraded fleet) are skipped per-sample
    rather than failing the merge."""
    reg = MetricsRegistry()
    for rid, text in sorted(scrapes.items()):
        for name, fam in parse_exposition(text).items():
            try:
                _merge_family(reg, rid, name, fam)
            except Exception:
                logger.warning("fleetview: merging family %s from %s failed",
                               name, rid, exc_info=True)
    return reg


def _merge_family(reg: MetricsRegistry, rid: str, name: str,
                  fam: dict) -> None:
    kind = fam["kind"]
    if kind == "histogram":
        _merge_histogram(reg, name, fam)
        return
    for suffix, labels, value in fam["samples"]:
        if suffix:
            continue
        if kind == "counter":
            c = reg.counter(name, fam["help"], tuple(sorted(labels)))
            c.labels(**labels).inc(max(0.0, value))
        else:                          # gauges and untyped: keep per-replica
            g = reg.gauge(name, fam["help"],
                          tuple(sorted({"replica", *labels})))
            g.labels(replica=rid, **labels).set(value)


def _merge_histogram(reg: MetricsRegistry, name: str, fam: dict) -> None:
    """Reassemble one replica's cumulative ``_bucket``/``_sum``/``_count``
    series into per-child (counts, sum, count) and fold them in through
    ``_HistogramChild.merge`` — the same primitive ``Histogram.merge``
    uses, so the equivalence proof covers this path."""
    children: dict[tuple, dict] = {}
    for suffix, labels, value in fam["samples"]:
        if suffix == "_bucket":
            le = labels.pop("le", None)
            key = tuple(sorted(labels.items()))
            slot = children.setdefault(
                key, {"labels": labels, "cum": {}, "sum": 0.0, "count": 0})
            if le is not None and le != "+Inf":
                slot["cum"][float(le)] = int(value)
        elif suffix in ("_sum", "_count"):
            key = tuple(sorted(labels.items()))
            slot = children.setdefault(
                key, {"labels": labels, "cum": {}, "sum": 0.0, "count": 0})
            if suffix == "_sum":
                slot["sum"] = value
            else:
                slot["count"] = int(value)
    for slot in children.values():
        buckets = tuple(sorted(slot["cum"]))
        if not buckets:
            continue
        hist = reg.histogram(name, fam["help"],
                             tuple(sorted(slot["labels"])), buckets=buckets)
        if tuple(hist.buckets) != buckets:   # cross-replica schema drift
            logger.warning("fleetview: bucket mismatch for %s — skipped",
                           name)
            continue
        cum = [slot["cum"][le] for le in buckets]
        counts = [cum[0]] + [cum[i] - cum[i - 1]
                             for i in range(1, len(cum))]
        hist.labels(**slot["labels"]).merge(
            counts, slot["sum"], slot["count"])


# the five SLIs: (report key, histogram family, TelemetryConfig objective)
SLI_FAMILIES = (
    ("queue_wait", "sm_slo_queue_wait_seconds", "slo_queue_wait_s"),
    ("first_annotation", "sm_slo_first_annotation_seconds",
     "slo_first_annotation_s"),
    ("e2e", "sm_slo_e2e_seconds", "slo_e2e_s"),
    ("read", "sm_slo_read_seconds", "slo_read_s"),
    ("stream_partial", "sm_slo_stream_partial_seconds",
     "slo_stream_partial_s"),
)


def slo_report_from_registry(reg: MetricsRegistry, telemetry_cfg) -> dict:
    """``SLOTracker.report`` recomputed from a merged registry — the exact
    arithmetic (``fraction_below`` + the same rounding), so the fleet
    number is bit-equal to what one tracker observing the union of every
    replica's jobs would report."""
    target = telemetry_cfg.slo_target
    out = {"target": target, "slos": {}}
    for name, family, knob in SLI_FAMILIES:
        objective_s = getattr(telemetry_cfg, knob)
        hist = reg._metrics.get(family)
        if isinstance(hist, Histogram):
            attained, count = hist.fraction_below(objective_s)
        else:
            attained, count = 0.0, 0
        out["slos"][name] = {
            "objective_s": objective_s,
            "target": target,
            "count": count,
            "attainment": round(attained, 6) if count else None,
            "violations": (round((1.0 - attained) * count) if count else 0),
            "error_budget_burn": (
                round((1.0 - attained) / (1.0 - target), 4)
                if count else None),
        }
    return out


# ------------------------------------------------------------- fleet plane
class _Round:
    """One fleet scrape round: per-replica evidence + the merged registry."""

    __slots__ = ("ts", "replicas", "merged", "partial", "scrape_errors")

    def __init__(self, ts, replicas, merged, partial, scrape_errors):
        self.ts = ts
        self.replicas = replicas          # replica_id -> evidence dict
        self.merged = merged              # MetricsRegistry
        self.partial = partial            # any ALIVE peer failed to scrape
        self.scrape_errors = scrape_errors  # replica_id -> error string


class FleetView:
    """Registry-driven aggregation plane on the serving replica."""

    _GUARDED_BY = {"_round": "_lock"}

    def __init__(self, service, cfg: FleetViewConfig | None = None):
        self.service = service
        self.cfg = cfg or FleetViewConfig()
        m = service.metrics
        self.c_scrapes = m.counter(
            "sm_fleetview_scrapes_total",
            "Fleet scrape rounds performed by this replica")
        self.c_scrape_errors = m.counter(
            "sm_fleetview_scrape_errors_total",
            "Peer /metrics scrapes that failed, by peer replica",
            ("replica",))
        self.g_peers = m.gauge(
            "sm_fleetview_peers",
            "Replicas successfully merged in the last fleet scrape "
            "(including this one)")
        self._lock = threading.Lock()
        self._round: _Round | None = None

    # ---------------------------------------------------------- scraping
    def _scrape_http(self, admin: str, path: str) -> str:
        req = urllib.request.Request(
            f"http://{admin}{path}",
            headers={"Accept": "text/plain"})
        with urllib.request.urlopen(
                req, timeout=self.cfg.scrape_timeout_s) as resp:
            return resp.read().decode("utf-8", "replace")

    def collect(self, force: bool = False) -> _Round:
        """One fleet scrape round, reused for ``cache_ttl_s`` so N
        dashboard readers cost one round.  Self is read from the local
        registry (cannot fail); alive peers are scraped over their
        gossiped admin address; stale peers are listed, never scraped."""
        with self._lock:
            if not force and self._round is not None and \
                    time.time() - self._round.ts < self.cfg.cache_ttl_s:
                return self._round
        sched = self.service.scheduler
        self_id = sched.replica_id
        scrapes: dict[str, str] = {self_id: self.service.metrics.expose()}
        replicas: dict[str, dict] = {}
        errors: dict[str, str] = {}
        for rec in sched.registry.peers(include_self=True):
            rid = str(rec.get("replica_id", ""))
            if not rid:
                continue
            meta = {
                "alive": bool(rec.get("alive")),
                "age_s": rec.get("age_s"),
                "epoch": rec.get("epoch"),
                "draining": bool(rec.get("draining")),
                "owned": rec.get("owned"),
                "workers": rec.get("workers"),
                "host": rec.get("host"),
                "process_id": rec.get("process_id"),
                "admin": rec.get("admin"),
                "pool": rec.get("pool"),
                "streams_in_flight": rec.get("streams_in_flight"),
                "scraped": rid == self_id,
                "error": None,
            }
            if rid != self_id and meta["alive"]:
                admin = rec.get("admin")
                if not admin:
                    meta["error"] = "no admin address gossiped"
                else:
                    try:
                        scrapes[rid] = self._scrape_http(str(admin),
                                                         "/metrics")
                        meta["scraped"] = True
                    except Exception as exc:  # noqa: BLE001 — evidence,
                        meta["error"] = f"{type(exc).__name__}: {exc}"
                if meta["error"]:
                    errors[rid] = meta["error"]
                    self.c_scrape_errors.labels(replica=rid).inc()
            replicas[rid] = meta
        self.c_scrapes.inc()
        self.g_peers.set(len(scrapes))
        merged = merge_expositions(scrapes)
        rnd = _Round(time.time(), replicas, merged,
                     partial=bool(errors), scrape_errors=errors)
        with self._lock:
            self._round = rnd
        return rnd

    # ---------------------------------------------------------- endpoints
    def metrics_text(self) -> str:
        """``GET /fleet/metrics`` body: the merged exposition, prefixed
        with machine-readable evidence comments (partiality is visible in
        the artifact itself, not only in /fleet/status)."""
        rnd = self.collect()
        head = [f"# fleetview: merged {len(rnd.replicas)} replica(s), "
                f"partial={'true' if rnd.partial else 'false'}"]
        for rid, err in sorted(rnd.scrape_errors.items()):
            head.append(f"# fleetview: scrape of {rid} failed: "
                        f"{err.splitlines()[0][:200]}")
        return "\n".join(head) + "\n" + rnd.merged.expose()

    def slo(self) -> tuple[int, dict]:
        """``GET /fleet/slo``: fleet-wide attainment / error-budget burn
        for all five SLIs from the merged buckets.  Never 500s for a peer
        failure — a partial round is served with evidence."""
        rnd = self.collect()
        body = slo_report_from_registry(
            rnd.merged, self.service.sm_config.telemetry)
        body["fleet"] = {
            "replicas_merged": sum(1 for r in rnd.replicas.values()
                                   if r["scraped"]),
            "replicas_known": len(rnd.replicas),
            "partial": rnd.partial,
            "scrape_errors": rnd.scrape_errors,
        }
        return 200, body

    def status(self) -> tuple[int, dict]:
        """``GET /fleet/status``: replicas + hosts + evictions + pool
        occupancy + in-flight stream acquisitions, fleet-wide."""
        rnd = self.collect()
        sched = self.service.scheduler
        pool_size = pool_in_use = 0
        hosts: dict[str, list[str]] = {}
        streams = 0
        for rid, meta in rnd.replicas.items():
            pool = meta.get("pool")
            if isinstance(pool, dict):
                pool_size += int(pool.get("size", 0) or 0)
                pool_in_use += int(pool.get("in_use", 0) or 0)
            host = meta.get("host")
            if host:
                hosts.setdefault(str(host), []).append(rid)
            # the stream root is shared disk — every replica reports the
            # same count; take the max rather than a nonsensical sum
            try:
                streams = max(streams, int(meta.get("streams_in_flight")
                                           or 0))
            except (TypeError, ValueError):
                pass
        body = {
            "ts": round(rnd.ts, 3),
            "serving_replica": sched.replica_id,
            "replicas": rnd.replicas,
            "alive": sum(1 for r in rnd.replicas.values() if r["alive"]),
            "draining": sum(1 for r in rnd.replicas.values()
                            if r["draining"]),
            "hosts": hosts,
            "evicted_hosts": sorted(sched._evicted_hosts),
            "pool": {"size": pool_size, "in_use": pool_in_use,
                     "occupancy": (round(pool_in_use / pool_size, 4)
                                   if pool_size else None)},
            "streams_in_flight": streams,
            "partial": rnd.partial,
            "scrape_errors": rnd.scrape_errors,
        }
        return 200, body


# --------------------------------------------------------- device profiling
class DeviceProfiler:
    """Single-flight ``jax.profiler`` capture behind ``/debug/profile``."""

    def __init__(self, service, cfg: ProfileConfig | None = None):
        self.service = service
        self.cfg = cfg or ProfileConfig()
        self.dir = Path(cfg.dir) if cfg and cfg.dir else \
            Path(service.sm_config.work_dir) / "profiles"
        self._busy = threading.Lock()
        self.c_captures = service.metrics.counter(
            "sm_profile_captures_total",
            "Completed /debug/profile capture sessions")

    def run(self, seconds: float | None) -> tuple[int, dict]:
        if not self.cfg.enabled:
            return 404, {"error": "device profiling disabled "
                                  "(telemetry.profile.enabled)",
                         "reason": "not_found"}
        if seconds is not None and seconds <= 0:
            return 400, {"error": "'seconds' must be positive",
                         "reason": "invalid_request"}
        secs = min(float(seconds or self.cfg.default_seconds),
                   self.cfg.max_seconds)
        if not self._busy.acquire(blocking=False):
            return 409, {"error": "a profile capture is already running",
                         "reason": "busy"}
        try:
            from ..analysis.profiling import ProfileSession

            session = ProfileSession(self.dir)
            running = [j for j in self.service.scheduler.jobs()
                       if j["state"] == "running"]
            try:
                session.start()
            except RuntimeError as exc:
                return 503, {"error": str(exc),
                             "reason": "profiler_unavailable"}
            time.sleep(secs)
            result = session.stop()
            injected = self._inject_device_spans(result["events"], running)
            self.c_captures.inc()
            return 200, {
                "seconds": secs,
                "duration_s": result["duration_s"],
                "trace_file": result["trace_file"],
                "attribution": result["attribution"],
                "jobs_running": [j["msg_id"] for j in running],
                "injected_spans": injected,
            }
        finally:
            self._busy.release()

    # a capture window can cover thousands of kernel launches; the job
    # trace gets the longest ones (the attribution table carries the rest)
    _MAX_INJECTED = 64

    def _inject_device_spans(self, events: list[dict],
                             running: list[dict]) -> int:
        """Inject ``device_kernel`` spans (wall-clock mapped) into every
        running job's trace file, so the Perfetto view of ``GET
        /jobs/<id>/trace`` shows host spans and device kernels on one
        timeline.  Returns the number of spans written (0 with no running
        traced jobs — the capture result still carries the attribution)."""
        trace_dir = getattr(self.service, "trace_dir", None)
        if not events or not running or not trace_dir:
            return 0
        top = sorted(events, key=lambda e: e["dur_s"],
                     reverse=True)[:self._MAX_INJECTED]
        injected = 0
        for job in running:
            tid = job.get("trace_id")
            if not tid:
                continue
            ctx = tracing.TraceContext(
                trace_id=tid, span_id=tracing.new_id(),
                job_id=job["msg_id"],
                file=str(tracing.trace_path(trace_dir, tid)))
            for e in top:
                tracing.emit_span(
                    ctx, "device_kernel", ts=e["ts_wall"], dur=e["dur_s"],
                    module=e["module"], op=e["op"],
                    kernel_class=e["class"])
                injected += 1
        return injected
