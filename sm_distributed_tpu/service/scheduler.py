"""Concurrent job scheduler over the file-spool queue.

Replaces the daemon's one-message-at-a-time blocking loop
(``engine/daemon.py::QueueConsumer.run``) with a production serving shape:

- a **dispatcher** thread scans ``pending/`` and admits messages in
  (priority class, per-tenant fairness, FIFO) order, claiming each by the
  same atomic rename the daemon uses, into a bounded hand-off queue;
- a **worker pool** executes claimed jobs concurrently.  Device-bound
  phases are serialized through a single **TPU token** (``device_token``,
  handed to the callback via ``JobContext`` and acquired inside
  ``SearchJob.run`` around the compiled-search phase) so CPU-bound
  staging/parse of the next job overlaps the current job's device time —
  the service-level analog of the host/device pipelining the backends do
  per batch;
- a **failure policy**: per-job timeout (message ``timeout_s`` overrides
  the config default), retry with exponential backoff + jitter, bounded
  attempts, then dead-letter into ``failed/`` with the recorded traceback.
  Retries persist their state (``attempts``, ``next_retry_at``) INTO the
  message file and move it back to ``pending/`` — a scheduler crash between
  attempts loses nothing;
- **heartbeat files** (``engine/daemon.py::ClaimHeartbeat``) touched for
  every running claim, so ``requeue_stale()`` distinguishes crashed claims
  from slow jobs;
- graceful drain: ``shutdown()`` stops admission, requeues
  claimed-but-unstarted messages, waits for running jobs, and leaves
  ``running/`` empty.

Priority classes come from message metadata: ``priority`` is ``"high"`` /
``"normal"`` / ``"low"`` (or an int, lower = sooner); ``tenant`` scopes
fairness — among equal priorities the dispatcher favors the tenant with the
fewest in-flight jobs, so one tenant's burst cannot starve the rest.
"""

from __future__ import annotations

import json
import os
import queue as _queue_mod
import random
import threading
import time
import traceback
from dataclasses import dataclass, field
from pathlib import Path

from ..engine.daemon import (
    FP_COMPLETE,
    QUEUE_ANNOTATE,
    ClaimHeartbeat,
    _STATES,
    clear_heartbeat,
    sweep_orphan_tmp,
)
from ..utils.config import ServiceConfig
from ..utils.failpoints import failpoint, register_failpoint
from ..utils.logger import logger

FP_RETRY_PUBLISH = register_failpoint(
    "sched.retry_publish",
    "between a retry's updated tmp write and its republish into pending/")

PRIORITY_CLASSES = {"high": 0, "normal": 1, "low": 2}

# terminal + live job states surfaced via /jobs
JOB_STATES = ("queued", "claimed", "running", "retry_wait", "done", "failed")


def _priority_rank(value) -> int:
    if isinstance(value, (int, float)):
        return int(value)
    return PRIORITY_CLASSES.get(str(value), PRIORITY_CLASSES["normal"])


@dataclass
class RetryPolicy:
    """Exponential backoff with additive jitter; attempts are bounded."""

    max_attempts: int = 3
    base_s: float = 1.0
    max_s: float = 60.0
    jitter: float = 0.1            # delay *= 1 + U[0, jitter]

    def backoff_s(self, attempt: int) -> float:
        """Delay before retry number ``attempt`` (1-based: after the first
        failure attempt=1).  Always >= base_s * 2^(attempt-1) capped at
        max_s; jitter only ADDS (de-synchronizes retry thundering herds
        without ever retrying early)."""
        delay = min(self.max_s, self.base_s * (2.0 ** (attempt - 1)))
        return delay * (1.0 + random.random() * self.jitter)

    @staticmethod
    def from_config(cfg: ServiceConfig) -> "RetryPolicy":
        return RetryPolicy(
            max_attempts=cfg.max_attempts,
            base_s=cfg.backoff_base_s,
            max_s=cfg.backoff_max_s,
            jitter=cfg.backoff_jitter,
        )


@dataclass
class JobRecord:
    """In-memory tracking row for one message (served by ``GET /jobs``)."""

    msg_id: str
    ds_id: str = ""
    tenant: str = "default"
    priority: str | int = "normal"
    state: str = "queued"
    attempts: int = 0
    published_at: float = 0.0
    claimed_at: float = 0.0
    started_at: float = 0.0
    finished_at: float = 0.0
    next_retry_at: float = 0.0
    error: str = ""

    def to_dict(self) -> dict:
        return {
            "msg_id": self.msg_id, "ds_id": self.ds_id, "tenant": self.tenant,
            "priority": self.priority, "state": self.state,
            "attempts": self.attempts, "published_at": self.published_at,
            "claimed_at": self.claimed_at, "started_at": self.started_at,
            "finished_at": self.finished_at,
            "next_retry_at": self.next_retry_at, "error": self.error,
        }


@dataclass
class JobContext:
    """Handed to callbacks that accept a second argument."""

    msg_id: str
    attempt: int
    device_token: threading.Lock = field(repr=False, default=None)
    metrics: object = field(repr=False, default=None)


def _callback_takes_ctx(fn) -> bool:
    """Callbacks may be legacy single-arg (``cb(msg)``, plain daemon style)
    or service-aware (``cb(msg, ctx)``)."""
    import inspect

    try:
        params = list(inspect.signature(fn).parameters.values())
    except (TypeError, ValueError):
        return False
    positional = [
        p for p in params
        if p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD)
    ]
    if any(p.kind == p.VAR_POSITIONAL for p in params):
        return True
    return len(positional) >= 2


class _Attempt(threading.Thread):
    """One callback invocation, joinable with a timeout.  A timed-out
    attempt thread is abandoned (daemon thread — Python cannot kill it);
    all spool file moves happen in the owning worker, so a zombie attempt
    can never corrupt queue state."""

    def __init__(self, fn, msg, ctx, takes_ctx: bool):
        super().__init__(daemon=True, name=f"attempt-{ctx.msg_id}-{ctx.attempt}")
        self.fn, self.msg, self.ctx, self.takes_ctx = fn, msg, ctx, takes_ctx
        self.error: BaseException | None = None
        self.tb: str = ""

    def run(self) -> None:
        try:
            if self.takes_ctx:
                self.fn(self.msg, self.ctx)
            else:
                self.fn(self.msg)
        except BaseException as exc:  # noqa: BLE001 — recorded, not swallowed
            self.error = exc
            self.tb = traceback.format_exc()


class JobScheduler:
    """Drain the spool with a worker pool under the service failure policy."""

    def __init__(
        self,
        queue_dir: str | Path,
        callback,
        config: ServiceConfig | None = None,
        queue: str = QUEUE_ANNOTATE,
        metrics=None,
    ):
        self.root = Path(queue_dir) / queue
        for s in _STATES:
            (self.root / s).mkdir(parents=True, exist_ok=True)
        self.callback = callback
        self._cb_takes_ctx = _callback_takes_ctx(callback)
        self.cfg = config or ServiceConfig()
        self.retry = RetryPolicy.from_config(self.cfg)
        self.metrics = metrics
        # ONE token: device-bound phases of concurrent jobs serialize here
        self.device_token = threading.Lock()
        self._records: dict[str, JobRecord] = {}
        self._records_lock = threading.Lock()
        # bounded hand-off: at most `workers` messages sit claimed-but-
        # unstarted, so a SIGTERM drain requeues a bounded set
        self._handoff: _queue_mod.Queue = _queue_mod.Queue(maxsize=max(1, self.cfg.workers))
        self._stop = threading.Event()
        self._drained = threading.Event()
        self._threads: list[threading.Thread] = []
        self._inflight_by_tenant: dict[str, int] = {}
        self._terminal_count = 0
        self._started = False
        if metrics is not None:
            self._init_metrics(metrics)

    # ------------------------------------------------------------- metrics
    def _init_metrics(self, m) -> None:
        self.m_jobs = m.counter(
            "sm_jobs_total", "Terminal job outcomes by state", ("state",))
        self.m_retries = m.counter(
            "sm_job_retries_total", "Retry attempts scheduled")
        self.m_timeouts = m.counter(
            "sm_job_timeouts_total", "Attempts killed by the per-job timeout")
        self.m_running = m.gauge(
            "sm_jobs_running", "Jobs currently executing in the worker pool")
        self.m_duration = m.histogram(
            "sm_job_duration_seconds", "Per-attempt job wall clock")
        self.m_backoff = m.histogram(
            "sm_retry_backoff_seconds", "Backoff delays scheduled before retries",
            buckets=(0.01, 0.05, 0.25, 1.0, 5.0, 30.0, 120.0))
        m.add_collector(self._collect_queue_depths)

    def _collect_queue_depths(self, m) -> None:
        g = m.gauge("sm_queue_depth", "Messages per spool state", ("state",))
        for s in _STATES:
            g.labels(state=s).set(len(list(self.root.glob(f"{s}/*.json"))))

    # ------------------------------------------------------------- records
    def _record(self, msg_id: str) -> JobRecord:
        with self._records_lock:
            rec = self._records.get(msg_id)
            if rec is None:
                rec = self._records[msg_id] = JobRecord(msg_id=msg_id)
            return rec

    def jobs(self) -> list[dict]:
        with self._records_lock:
            return [r.to_dict() for r in self._records.values()]

    def stats(self) -> dict:
        with self._records_lock:
            by_state: dict[str, int] = {}
            for r in self._records.values():
                by_state[r.state] = by_state.get(r.state, 0) + 1
        return {
            "workers": self.cfg.workers,
            "states": by_state,
            "terminal": self._terminal_count,
            "stopping": self._stop.is_set(),
        }

    # ---------------------------------------------------------- dispatcher
    def _scan_pending(self, now: float) -> list[tuple[tuple, Path, dict]]:
        """Eligible pending messages with their admission sort key."""
        out = []
        with self._records_lock:
            inflight = dict(self._inflight_by_tenant)
        for p in sorted(self.root.glob("pending/*.json")):
            try:
                msg = json.loads(p.read_text())
                if not isinstance(msg, dict):
                    msg = {}
            except FileNotFoundError:
                continue              # claimed by another scheduler mid-scan
            except (OSError, json.JSONDecodeError):
                # poison payload — still admitted; claim+run dead-letters it
                msg = {}
            svc = msg.get("service", {})
            if float(svc.get("next_retry_at", 0.0)) > now:
                continue              # backoff not elapsed yet
            tenant = str(msg.get("tenant", "default"))
            rank = _priority_rank(msg.get("priority", "normal"))
            published = float(msg.get("published_at", 0.0))
            key = (rank, inflight.get(tenant, 0), published, p.name)
            out.append((key, p, msg))
        out.sort(key=lambda t: t[0])
        return out

    def _claim(self, p: Path) -> Path | None:
        dst = self.root / "running" / p.name
        try:
            os.replace(p, dst)        # atomic claim (same as QueueConsumer)
            return dst
        except FileNotFoundError:
            return None               # another scheduler/daemon won the race

    def _dispatch_loop(self) -> None:
        while not self._stop.is_set():
            admitted = self._admit_one()
            if not admitted:
                self._stop.wait(self.cfg.poll_interval_s)
        self._drain_handoff()
        self._drained.set()

    def _admit_one(self) -> bool:
        """Claim and hand off the single best eligible message, then return
        so the next admission re-scans with FRESH fairness keys (per-tenant
        in-flight counts move with every claim)."""
        for _key, p, msg in self._scan_pending(time.time()):
            if self._stop.is_set():
                return False
            claimed = self._claim(p)
            if claimed is None:
                continue              # another scheduler/daemon won the race
            msg_id = claimed.stem
            rec = self._record(msg_id)
            rec.ds_id = str(msg.get("ds_id", ""))
            rec.tenant = str(msg.get("tenant", "default"))
            rec.priority = msg.get("priority", "normal")
            rec.published_at = float(msg.get("published_at", 0.0))
            rec.attempts = int(msg.get("service", {}).get("attempts", 0))
            rec.state = "claimed"
            rec.claimed_at = time.time()
            with self._records_lock:
                self._inflight_by_tenant[rec.tenant] = (
                    self._inflight_by_tenant.get(rec.tenant, 0) + 1)
            # blocks when all workers are busy and the hand-off buffer is
            # full — natural admission backpressure
            while not self._stop.is_set():
                try:
                    self._handoff.put((claimed, msg), timeout=0.2)
                    return True
                except _queue_mod.Full:
                    continue
            self._requeue_unstarted(claimed, msg)
            return False
        return False

    def _requeue_unstarted(self, claimed: Path, msg: dict) -> None:
        rec = self._record(claimed.stem)
        try:
            os.replace(claimed, self.root / "pending" / claimed.name)
        except FileNotFoundError:
            return
        clear_heartbeat(claimed)
        rec.state = "queued"
        with self._records_lock:
            t = rec.tenant
            self._inflight_by_tenant[t] = max(0, self._inflight_by_tenant.get(t, 1) - 1)
        logger.info("scheduler: requeued claimed-but-unstarted %s", claimed.name)

    def _drain_handoff(self) -> None:
        """On shutdown: claimed-but-unstarted messages go back to pending/."""
        while True:
            try:
                claimed, msg = self._handoff.get_nowait()
            except _queue_mod.Empty:
                return
            self._requeue_unstarted(claimed, msg)

    # -------------------------------------------------------------- worker
    def _job_timeout_s(self, msg: dict) -> float:
        svc = msg.get("service", {}) if isinstance(msg, dict) else {}
        return float(svc.get("timeout_s", msg.get("timeout_s",
                                                  self.cfg.job_timeout_s)))

    def _job_max_attempts(self, msg: dict) -> int:
        svc = msg.get("service", {}) if isinstance(msg, dict) else {}
        return int(svc.get("max_attempts", msg.get("max_attempts",
                                                   self.retry.max_attempts)))

    def _worker_loop(self) -> None:
        while True:
            try:
                claimed, msg = self._handoff.get(timeout=0.2)
            except _queue_mod.Empty:
                if self._stop.is_set() and self._drained.is_set():
                    return
                continue
            try:
                self._run_one(claimed, msg)
            except Exception:        # never kill a worker thread
                logger.error("scheduler: internal error running %s",
                             claimed.name, exc_info=True)

    def _run_one(self, claimed: Path, msg: dict) -> None:
        msg_id = claimed.stem
        rec = self._record(msg_id)
        rec.state = "running"
        rec.started_at = time.time()
        rec.attempts += 1
        if self.metrics:
            self.m_running.inc()
        hb = ClaimHeartbeat(claimed, interval_s=self.cfg.heartbeat_interval_s)
        hb.start()
        timed_out = False
        try:
            if not isinstance(msg, dict) or not msg:
                # poison message (unparseable JSON): dead-letter immediately,
                # keeping the raw payload as evidence (daemon contract)
                raw = ""
                try:
                    raw = claimed.read_text()
                    msg = json.loads(raw)
                except (OSError, json.JSONDecodeError) as exc:
                    self._dead_letter(claimed, {"raw": raw}, rec,
                                      f"poison message: {exc}", "")
                    return
            ctx = JobContext(msg_id=msg_id, attempt=rec.attempts,
                             device_token=self.device_token,
                             metrics=self.metrics)
            attempt = _Attempt(self.callback, msg, ctx, self._cb_takes_ctx)
            t0 = time.perf_counter()
            attempt.start()
            attempt.join(timeout=self._job_timeout_s(msg))
            dt = time.perf_counter() - t0
            if self.metrics:
                self.m_duration.observe(dt)
            if attempt.is_alive():
                timed_out = True
                if self.metrics:
                    self.m_timeouts.inc()
                self._handle_failure(
                    claimed, msg, rec,
                    f"timeout: attempt {rec.attempts} exceeded "
                    f"{self._job_timeout_s(msg):.1f}s (abandoned)", "")
            elif attempt.error is not None:
                self._handle_failure(claimed, msg, rec,
                                     str(attempt.error), attempt.tb)
            else:
                self._finish(claimed, rec)
        finally:
            if timed_out:
                # the zombie attempt must not keep refreshing the heartbeat
                hb.stop()
            else:
                hb.stop()
            if self.metrics:
                self.m_running.dec()
            with self._records_lock:
                t = rec.tenant
                self._inflight_by_tenant[t] = max(
                    0, self._inflight_by_tenant.get(t, 1) - 1)

    def _finish(self, claimed: Path, rec: JobRecord) -> None:
        # same seam as the daemon consumer's: job succeeded, message not yet
        # in done/ — a crash here must reprocess idempotently, never lose it
        failpoint(FP_COMPLETE, path=claimed)
        os.replace(claimed, self.root / "done" / claimed.name)
        clear_heartbeat(claimed)
        rec.state = "done"
        rec.finished_at = time.time()
        with self._records_lock:
            self._terminal_count += 1
        if self.metrics:
            self.m_jobs.labels(state="done").inc()
        logger.info("scheduler: %s done (attempt %d)", claimed.name, rec.attempts)

    def _handle_failure(self, claimed: Path, msg: dict, rec: JobRecord,
                        error: str, tb: str) -> None:
        max_attempts = self._job_max_attempts(msg)
        rec.error = error
        if rec.attempts >= max_attempts:
            self._dead_letter(claimed, msg, rec, error, tb)
            return
        delay = self.retry.backoff_s(rec.attempts)
        rec.state = "retry_wait"
        rec.next_retry_at = time.time() + delay
        if self.metrics:
            self.m_retries.inc()
            self.m_backoff.observe(delay)
        # persist retry state INTO the message, then atomically republish:
        # a scheduler crash here leaves either the old running/ copy (crash
        # recovery requeues it) or the updated pending/ copy — never neither
        updated = dict(msg)
        svc = dict(updated.get("service", {}))
        svc["attempts"] = rec.attempts
        svc["next_retry_at"] = rec.next_retry_at
        svc["last_error"] = error
        updated["service"] = svc
        tmp = self.root / "pending" / f".{claimed.name}.tmp"
        tmp.write_text(json.dumps(updated, indent=2))
        failpoint(FP_RETRY_PUBLISH, path=tmp)
        os.replace(tmp, self.root / "pending" / claimed.name)
        claimed.unlink()
        clear_heartbeat(claimed)
        logger.warning(
            "scheduler: %s attempt %d/%d failed (%s); retry in %.2fs",
            claimed.name, rec.attempts, max_attempts, error, delay)

    def _dead_letter(self, claimed: Path, msg: dict, rec: JobRecord,
                     error: str, tb: str) -> None:
        failed = dict(msg) if msg else {}
        failed["error"] = error
        if tb:
            failed["traceback"] = tb
        failed["attempts"] = rec.attempts
        (self.root / "failed" / claimed.name).write_text(
            json.dumps(failed, indent=2))
        try:
            claimed.unlink()
        except FileNotFoundError:
            pass
        clear_heartbeat(claimed)
        rec.state = "failed"
        rec.finished_at = time.time()
        with self._records_lock:
            self._terminal_count += 1
        if self.metrics:
            self.m_jobs.labels(state="failed").inc()
        logger.error("scheduler: %s dead-lettered after %d attempt(s): %s",
                     claimed.name, rec.attempts, error)

    # ------------------------------------------------------------ lifecycle
    def start(self) -> None:
        if self._started:
            raise RuntimeError("scheduler already started")
        self._started = True
        # crash recovery first: claims with dead heartbeats return to pending
        n = self.requeue_stale()
        if n:
            logger.info("scheduler: requeued %d stale claim(s) on startup", n)
        # orphaned publish/retry tmp files older than the staleness horizon
        # can have no live writer — the crash that leaked them also killed it
        sweep_orphan_tmp(self.root, max_age_s=self.cfg.stale_after_s)
        d = threading.Thread(target=self._dispatch_loop, daemon=True,
                             name="sched-dispatch")
        d.start()
        self._threads.append(d)
        for i in range(self.cfg.workers):
            w = threading.Thread(target=self._worker_loop, daemon=True,
                                 name=f"sched-worker-{i}")
            w.start()
            self._threads.append(w)
        logger.info("scheduler: started (%d workers, queue %s)",
                    self.cfg.workers, self.root)

    def requeue_stale(self) -> int:
        """Heartbeat-aware crash recovery (delegates to the daemon's)."""
        from ..engine.daemon import QueueConsumer

        consumer = QueueConsumer(self.root.parent, callback=None,
                                 queue=self.root.name)
        return consumer.requeue_stale(max_age_s=self.cfg.stale_after_s)

    def shutdown(self, timeout_s: float | None = None) -> bool:
        """Graceful drain: stop admission, requeue claimed-but-unstarted,
        wait for running jobs.  Returns True when fully drained in time."""
        timeout_s = self.cfg.drain_timeout_s if timeout_s is None else timeout_s
        self._stop.set()
        deadline = time.time() + timeout_s
        ok = True
        for t in self._threads:
            t.join(timeout=max(0.0, deadline - time.time()))
            ok = ok and not t.is_alive()
        # belt and braces: anything still claimed (worker died mid-move)
        self._drain_handoff()
        logger.info("scheduler: shutdown %s", "clean" if ok else "TIMED OUT")
        return ok

    def wait_for_terminal(self, n: int, timeout_s: float = 60.0) -> bool:
        """Block until ``n`` jobs reached a terminal state (tests/smoke)."""
        deadline = time.time() + timeout_s
        while time.time() < deadline:
            if self._terminal_count >= n:
                return True
            time.sleep(0.02)
        return self._terminal_count >= n
